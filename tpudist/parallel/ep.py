"""Expert parallelism — Mixture-of-Experts with GShard-style einsum dispatch
over the ``expert`` mesh axis.

No reference counterpart (SURVEY.md §2.12: the reference's only strategy is
DDP, /root/reference/main.py:83); built so the framework scales parameter
count past dense models. TPU-native design:

- **Static shapes everywhere.** Routing is expressed as dense one-hot
  dispatch/combine tensors (the GShard/Switch formulation), not gather/
  scatter with data-dependent sizes: each expert has a fixed ``capacity``
  slot count and tokens beyond capacity are dropped (their contribution is
  zero; transformer residuals carry them through unchanged). XLA sees only
  einsums — all of it tiles onto the MXU.
- **Expert placement = sharding metadata.** Stacked expert FFN weights
  ``[E, d, ff]`` carry ``nn.with_partitioning(..., ('expert', ...))``; the
  dispatched activations ``[E, capacity, d]`` are sharding-constrained to
  ``P('expert')`` on the expert dim. From those two constraints GSPMD derives
  the token all-to-all (data-sharded tokens → expert-sharded slots and back)
  and schedules it on ICI — there is no hand-written collective, mirroring
  how tpudist's DP lets XLA derive the gradient all-reduce (SURVEY.md §2.5).
- **Load balance is a differentiable aux loss** (Switch-style
  ``E · Σ_e f_e·P_e``), sowed into the ``losses`` collection; the train step
  (tpudist.train) adds any sowed losses to the task loss.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpudist.mesh import EXPERT_AXIS, TENSOR_AXIS


def expert_capacity(
    num_tokens: int, num_experts: int, *, top_k: int, capacity_factor: float
) -> int:
    """Per-expert slot count: ``ceil(top_k · T / E) · capacity_factor``,
    rounded up — the static buffer size every expert processes."""
    import math

    base = (top_k * num_tokens + num_experts - 1) // num_experts
    return max(1, math.ceil(base * capacity_factor))


def top_k_dispatch(probs: jax.Array, top_k: int, capacity: int):
    """Router probabilities → (dispatch, combine, aux_loss).

    ``probs``: ``[T, E]`` softmax router output.
    ``dispatch``: ``[T, E, C]`` 0/1 — token t occupies slot c of expert e.
    ``combine``: ``dispatch`` weighted by the token's (renormalized) gate.
    ``aux_loss``: Switch-style load-balance loss, 1.0 at perfect balance.

    Slot assignment order is token order (cumsum over the token dim), with
    all k-th choices placed after all (k-1)-th choices — the GShard priority
    rule, so a token's secondary expert never evicts another's primary.
    """
    T, E = probs.shape
    gates, masks = [], []
    p = probs
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=probs.dtype)  # [T, E]
        gates.append(jnp.sum(p * m, axis=-1))  # [T]
        masks.append(m)
        p = p * (1.0 - m)

    # aux loss from primary assignments: E · Σ_e (token fraction)·(mean prob)
    f = jnp.mean(masks[0], axis=0)
    pr = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(f * pr)

    # top-1 (Switch) keeps the raw gate — renormalizing a single gate to ~1
    # would zero the router's task-loss gradient; top-k≥2 renormalizes the
    # kept gates to sum to 1 (GShard)
    if top_k > 1:
        denom = sum(gates) + 1e-9
        gates = [g / denom for g in gates]

    dispatch = jnp.zeros((T, E, capacity), probs.dtype)
    combine = jnp.zeros((T, E, capacity), probs.dtype)
    counts = jnp.zeros((E,), jnp.int32)  # slots consumed by earlier choices
    for g, m in zip(gates, masks):
        # positions in int32 — a float cumsum in low-precision dtypes (bf16
        # tops out at 256) would collide positions and double-book slots
        mi = m.astype(jnp.int32)
        pos = jnp.cumsum(mi, axis=0) - mi + counts  # [T, E]
        pos_t = jnp.sum(pos * mi, axis=-1)  # [T]
        keep = (pos_t < capacity) & (jnp.sum(mi, axis=-1) > 0)
        slot = jax.nn.one_hot(pos_t, capacity, dtype=probs.dtype)
        d = m[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + d
        combine = combine + d * g[:, None, None]
        counts = counts + jnp.sum(mi, axis=0)
    return dispatch, combine, aux_loss


class MoEMlp(nn.Module):
    """Mixture-of-experts FFN (drop-in for a transformer's dense MLP).

    ``x: [batch, seq, d] → [batch, seq, d]``; top-``top_k`` routing into
    ``num_experts`` gelu FFNs of width ``mlp_ratio·d``; expert weights are
    expert-sharded (and FFN-dim tensor-sharded) via partitioning metadata.
    Sows the scaled load-balance loss into the ``losses`` collection.

    Routing is **grouped** (GShard): tokens are split into ``num_groups``
    independent dispatch groups (default: one per batch row, so groups ride
    the existing ``data`` sharding) and capacity is per-group. This keeps the
    dispatch/combine one-hots at O(group_size²·E⁻¹) instead of O(T²·E⁻¹) —
    ungrouped routing over batch·seq tokens would put multi-hundred-MB
    mostly-zero tensors in HBM at realistic LM shapes.
    """

    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    mlp_ratio: int = 4
    ffn_dim: int | None = None  # overrides mlp_ratio·d when set
    # "gelu": GPT-2-style single-FFN experts; "swiglu": Mixtral-style
    # gated experts (silu(x·w_gate)·(x·w_up))·w_down
    expert_act: str = "gelu"
    aux_loss_weight: float = 0.01
    num_groups: int = 0  # 0 → one group per batch row
    dtype: Any = jnp.float32
    mesh: Any = None  # when set, activations get explicit expert shardings

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        E = self.num_experts
        ff = self.ffn_dim or self.mlp_ratio * d
        G = self.num_groups or b
        T = b * s
        if T % G:
            raise ValueError(f"{T} tokens not divisible into {G} groups")
        t = T // G
        tokens = x.reshape(G, t, d)

        # router in fp32 — cheap, and argmax ties/probs stay stable in bf16 runs
        wr = self.param(
            "router", nn.initializers.lecun_normal(), (d, E), jnp.float32
        )
        probs = jax.nn.softmax(
            jnp.einsum("gtd,de->gte", tokens.astype(jnp.float32), wr)
        )
        capacity = expert_capacity(
            t, E, top_k=self.top_k, capacity_factor=self.capacity_factor
        )
        dispatch, combine, aux = jax.vmap(
            lambda p: top_k_dispatch(p, self.top_k, capacity)
        )(probs)
        self.sow(
            "losses", "moe_aux_loss", self.aux_loss_weight * jnp.mean(aux),
            reduce_fn=lambda a, b: a + b, init_fn=lambda: jnp.zeros((), jnp.float32),
        )

        def ew(name, shape, spec):
            return self.param(
                name,
                nn.with_partitioning(nn.initializers.lecun_normal(), spec),
                shape, jnp.float32,
            )

        col = (EXPERT_AXIS, None, TENSOR_AXIS)
        row = (EXPERT_AXIS, TENSOR_AXIS, None)

        # tokens (data-sharded groups) → expert slots: GSPMD turns the
        # sharding jump into the all-to-all
        slots = jnp.einsum(
            "gtec,gtd->gecd", dispatch.astype(self.dtype), tokens.astype(self.dtype)
        )
        slots = self._constrain(slots)
        if self.expert_act == "swiglu":
            wg = ew("w_gate", (E, d, ff), col)
            wu = ew("w_up", (E, d, ff), col)
            wd = ew("w_down", (E, ff, d), row)
            h = nn.silu(
                jnp.einsum("gecd,edf->gecf", slots, wg.astype(self.dtype))
            ) * jnp.einsum("gecd,edf->gecf", slots, wu.astype(self.dtype))
            out = jnp.einsum("gecf,efd->gecd", h, wd.astype(self.dtype))
        elif self.expert_act == "gelu":
            w1 = ew("w1", (E, d, ff), col)
            w2 = ew("w2", (E, ff, d), row)
            h = jnp.einsum("gecd,edf->gecf", slots, w1.astype(self.dtype))
            h = nn.gelu(h)
            out = jnp.einsum("gecf,efd->gecd", h, w2.astype(self.dtype))
        else:
            raise ValueError(f"unknown expert_act {self.expert_act!r}")
        out = self._constrain(out)
        # expert slots → tokens (the reverse all-to-all), gate-weighted
        y = jnp.einsum("gtec,gecd->gtd", combine.astype(self.dtype), out)
        return y.reshape(b, s, d)

    def _constrain(self, slots):
        if self.mesh is None:
            return slots
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tpudist.mesh import DATA_AXIS, FSDP_AXIS

        return jax.lax.with_sharding_constraint(
            slots,
            NamedSharding(
                self.mesh, P((DATA_AXIS, FSDP_AXIS), EXPERT_AXIS, None, None)
            ),
        )


