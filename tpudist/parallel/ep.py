"""Expert parallelism — Mixture-of-Experts with GShard-style routing over
the ``expert`` mesh axis, with two dispatch implementations.

No reference counterpart (SURVEY.md §2.12: the reference's only strategy is
DDP, /root/reference/main.py:83); built so the framework scales parameter
count past dense models. TPU-native design:

- **Static shapes everywhere.** Each expert has a fixed ``capacity`` slot
  count and tokens beyond capacity are dropped (their contribution is zero;
  transformer residuals carry them through unchanged). Routing itself is
  shared (:func:`top_k_routing`: argmax/cumsum slot assignment with the
  GShard priority rule); what differs is how tokens reach their slots:

  - ``dispatch_impl="einsum"`` — the GShard/Switch one-hot formulation:
    dense ``[t, E, C]`` dispatch/combine tensors contracted on the MXU.
    O(t·E·C) FLOPs and bytes, but every op is an einsum; this is the
    bit-checked oracle the index path is certified against.
  - ``dispatch_impl="index"`` — slot-index gather/scatter: each kept
    (token, choice) computes its flat slot id ``e·C + pos``; a scatter of
    token ids builds the slot→token map, one ``take`` gathers tokens into
    ``[E, C, d]`` slots, and the combine is a gather from the expert
    outputs whose backward is the scatter-add. O(t·k) index work instead
    of O(t·E·C) — the dense one-hots never materialize.

- **Expert placement = sharding metadata.** Stacked expert FFN weights
  ``[E, d, ff]`` carry ``nn.with_partitioning(..., ('expert', ...))``. On
  the einsum path the dispatched activations are sharding-constrained to
  ``P('expert')`` and GSPMD derives the token all-to-all. On the index
  path with a real (>1) ``expert`` axis the collective is EXPLICIT: a
  ``shard_map`` over the mesh in which each expert shard gathers only its
  own experts' slots from its (expert-replicated) local tokens, runs its
  local FFNs, and one ``all_gather`` over ``expert`` ships the slot
  OUTPUTS back — wire bytes equal dispatched-token bytes
  (``G·E·C·d``·dtype per direction), not whatever GSPMD derives from the
  one-hot einsums.
- **Load balance is a differentiable aux loss** (Switch-style
  ``E · Σ_e f_e·P_e``), sowed into the ``losses`` collection; the train
  step (tpudist.train) adds any sowed losses to the task loss. Optional
  router hardening: ``router_z_loss`` (penalizes ``logsumexp(logits)²``,
  keeping the fp32 router's logits from drifting to magnitudes where
  softmax saturates) and ``router_jitter`` (multiplicative uniform input
  noise, train-only) — both off by default and byte-inert when off.
- **Router observability**: per-expert load fractions, the dropped-token
  rate, and the unscaled aux value are sowed into the ``moe_stats``
  collection; the train step forwards them to telemetry when it runs with
  ``telemetry=True`` (docs/OBSERVABILITY.md §1).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpudist.mesh import DATA_AXIS, EXPERT_AXIS, FSDP_AXIS, TENSOR_AXIS


def expert_capacity(
    num_tokens: int, num_experts: int, *, top_k: int, capacity_factor: float
) -> int:
    """Per-expert slot count: ``ceil(top_k · T / E) · capacity_factor``,
    rounded up — the static buffer size every expert processes."""
    import math

    base = (top_k * num_tokens + num_experts - 1) // num_experts
    return max(1, math.ceil(base * capacity_factor))


def top_k_routing(probs: jax.Array, top_k: int, capacity: int):
    """Router probabilities → per-(token, choice) routing decisions.

    ``probs``: ``[T, E]`` softmax router output. Returns
    ``(idx, gates, pos, keep, aux_loss)`` with ``idx`` ``[T, k]`` int32
    expert choices, ``gates`` ``[T, k]`` the (renormalized) gate weights,
    ``pos`` ``[T, k]`` int32 slot positions within the chosen expert,
    ``keep`` ``[T, k]`` bool capacity survival, and the Switch-style
    load-balance ``aux_loss`` (1.0 at perfect balance).

    This is the ONE routing implementation both dispatch paths consume:
    slot assignment order is token order (int32 cumsum over the token dim
    — a float cumsum in low-precision dtypes would collide positions),
    with all k-th choices placed after all (k-1)-th choices (the GShard
    priority rule, so a token's secondary expert never evicts another's
    primary). Top-1 (Switch) keeps the raw gate — renormalizing a single
    gate to ~1 would zero the router's task-loss gradient; top-k≥2
    renormalizes the kept gates to sum to 1 (GShard).
    """
    T, E = probs.shape
    gates, idxs, masks = [], [], []
    p = probs
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=probs.dtype)  # [T, E]
        gates.append(jnp.sum(p * m, axis=-1))  # [T]
        idxs.append(idx.astype(jnp.int32))
        masks.append(m)
        p = p * (1.0 - m)

    # aux loss from primary assignments: E · Σ_e (token fraction)·(mean prob)
    f = jnp.mean(masks[0], axis=0)
    pr = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(f * pr)

    if top_k > 1:
        denom = sum(gates) + 1e-9
        gates = [g / denom for g in gates]

    poss, keeps = [], []
    counts = jnp.zeros((E,), jnp.int32)  # slots consumed by earlier choices
    for m in masks:
        mi = m.astype(jnp.int32)
        pos = jnp.cumsum(mi, axis=0) - mi + counts  # [T, E]
        pos_t = jnp.sum(pos * mi, axis=-1)  # [T]
        keep = (pos_t < capacity) & (jnp.sum(mi, axis=-1) > 0)
        poss.append(pos_t)
        keeps.append(keep)
        counts = counts + jnp.sum(mi, axis=0)
    return (
        jnp.stack(idxs, axis=-1),
        jnp.stack(gates, axis=-1),
        jnp.stack(poss, axis=-1),
        jnp.stack(keeps, axis=-1),
        aux_loss,
    )


def _one_hot_dispatch(idx, gates, pos, keep, num_experts: int, capacity: int,
                      dtype):
    """Routing decisions → dense one-hot ``(dispatch, combine)`` tensors
    (``[..., E, C]``), the GShard einsum formulation. Sequential adds in
    choice order — the exact op order of the original oracle."""
    shape = idx.shape[:-1] + (num_experts, capacity)
    dispatch = jnp.zeros(shape, dtype)
    combine = jnp.zeros(shape, dtype)
    for j in range(idx.shape[-1]):
        m = jax.nn.one_hot(idx[..., j], num_experts, dtype=dtype)
        slot = jax.nn.one_hot(pos[..., j], capacity, dtype=dtype)
        d = m[..., :, None] * slot[..., None, :] * keep[..., j, None, None]
        dispatch = dispatch + d
        combine = combine + d * gates[..., j, None, None]
    return dispatch, combine


def top_k_dispatch(probs: jax.Array, top_k: int, capacity: int):
    """Router probabilities → (dispatch, combine, aux_loss) — the einsum
    oracle's dense form.

    ``dispatch``: ``[T, E, C]`` 0/1 — token t occupies slot c of expert e.
    ``combine``: ``dispatch`` weighted by the token's (renormalized) gate.
    ``aux_loss``: Switch-style load-balance loss, 1.0 at perfect balance.

    Built from :func:`top_k_routing` (one routing implementation for both
    dispatch paths); numerics are unchanged from the original fused loop.
    """
    idx, gates, pos, keep, aux_loss = top_k_routing(probs, top_k, capacity)
    E = probs.shape[-1]
    dispatch, combine = _one_hot_dispatch(
        idx, gates, pos, keep, E, capacity, probs.dtype
    )
    return dispatch, combine, aux_loss


def _flat_dest(idx, pos, keep, capacity: int, num_experts: int):
    """Per-(token, choice) flat slot id ``e·C + pos``; dropped choices
    point at the one-past-the-end garbage slot ``E·C``."""
    return jnp.where(keep, idx * capacity + pos, num_experts * capacity)


def _index_dispatch(tokens, dest, num_experts: int, capacity: int):
    """Tokens → ``[E, C, d]`` slots via slot-index scatter/gather.

    ``tokens``: ``[t, d]``; ``dest``: ``[t, k]`` flat slot ids
    (:func:`_flat_dest`). A scatter of token ids builds the slot→token
    map (kept destinations are unique by construction — one token per
    slot — so the scatter is order-independent and deterministic; all
    dropped pairs collide harmlessly on the garbage slot), then ONE
    gather materializes the slots. Empty slots read the appended zero row
    — the same zeros the einsum dispatch produces. The gather's backward
    is a scatter-add into the token gradients.
    """
    t, d = tokens.shape
    k = dest.shape[-1]
    n_slots = num_experts * capacity
    token_ids = jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[:, None], (t, k)
    )
    # index t (one past the tokens) marks "empty": it reads the zero row
    slot_token = jnp.full((n_slots + 1,), t, jnp.int32)
    slot_token = slot_token.at[dest.reshape(-1)].set(token_ids.reshape(-1))
    tokens_pad = jnp.concatenate(
        [tokens, jnp.zeros((1, d), tokens.dtype)], axis=0
    )
    slots = jnp.take(tokens_pad, slot_token[:n_slots], axis=0)
    return slots.reshape(num_experts, capacity, d)


def _index_combine(out, dest, gates, keep, dtype):
    """Expert outputs → per-token mix via gather.

    ``out``: ``[E, C, d]``; ``dest``/``gates``/``keep``: ``[t, k]``.
    ``y[t] = Σ_j gate_j·keep_j·out[dest_j]`` — dropped choices gather the
    appended zero row. Sequential adds in choice order; the gate weights
    are cast exactly like the einsum path's combine tensor
    (``dtype(gate·keep)``). Dispatch and the expert outputs match the
    oracle BIT-exactly (tests/test_moe.py asserts it on the composed
    layer); this final mix matches to ≤1 ulp — the oracle's contraction
    accumulates with FMA (one rounding per term), this explicit
    multiply-add rounds the product first — which greedy decode and the
    train-loss trajectory absorb (both pinned by tests)."""
    E, C, d = out.shape
    out_pad = jnp.concatenate(
        [out.reshape(E * C, d), jnp.zeros((1, d), out.dtype)], axis=0
    )
    w = (gates * keep.astype(gates.dtype)).astype(dtype)  # [t, k]
    y = jnp.zeros((dest.shape[0], d), dtype)
    for j in range(dest.shape[-1]):
        y = y + w[:, j, None] * jnp.take(out_pad, dest[:, j], axis=0)
    return y


class MoEMlp(nn.Module):
    """Mixture-of-experts FFN (drop-in for a transformer's dense MLP).

    ``x: [batch, seq, d] → [batch, seq, d]``; top-``top_k`` routing into
    ``num_experts`` FFNs of width ``mlp_ratio·d`` (or ``ffn_dim``); expert
    weights are expert-sharded (and FFN-dim tensor-sharded) via
    partitioning metadata. Sows the scaled load-balance loss into the
    ``losses`` collection and router stats into ``moe_stats``.

    Routing is **grouped** (GShard): tokens are split into ``num_groups``
    independent dispatch groups (default: one per batch row, so groups ride
    the existing ``data`` sharding) and capacity is per-group. On the
    einsum path this keeps the dispatch/combine one-hots at
    O(group_size²·E⁻¹) instead of O(T²·E⁻¹); the index path never builds
    them at all.

    ``dispatch_impl`` selects the dispatch formulation (module docstring):
    ``"einsum"`` (default, the oracle) or ``"index"``. With a real (>1)
    ``expert`` mesh axis the index path runs inside an explicit
    ``shard_map``: local dispatch + local expert FFNs + ONE ``all_gather``
    of the slot outputs over ``expert`` (wire bytes = dispatched-token
    bytes); the per-block ``tensor`` reduction stays a ``psum``, and the
    batch axes stay data-manual — gradients under ``jax.grad`` transpose
    the ``all_gather`` into the matching ``psum_scatter``.
    """

    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    mlp_ratio: int = 4
    ffn_dim: int | None = None  # overrides mlp_ratio·d when set
    # "gelu": GPT-2-style single-FFN experts; "swiglu": Mixtral-style
    # gated experts (silu(x·w_gate)·(x·w_up))·w_down
    expert_act: str = "gelu"
    aux_loss_weight: float = 0.01
    num_groups: int = 0  # 0 → one group per batch row
    # "einsum" (one-hot oracle) | "index" (slot-index gather/scatter +
    # explicit expert all-to-all on a real expert axis)
    dispatch_impl: str = "einsum"
    # router z-loss weight (ST-MoE): penalizes mean(logsumexp(logits)²),
    # sowed into ``losses`` scaled. 0.0 = off (byte-inert).
    router_z_loss: float = 0.0
    # multiplicative uniform router-input jitter in [1-j, 1+j], train-only
    # (needs a 'dropout' rng and deterministic=False). 0.0 = off.
    router_jitter: float = 0.0
    dtype: Any = jnp.float32
    mesh: Any = None  # when set, activations get explicit expert shardings

    @nn.compact
    def __call__(self, x, deterministic: bool | None = None):
        b, s, d = x.shape
        E = self.num_experts
        ff = self.ffn_dim or self.mlp_ratio * d
        G = self.num_groups or b
        T = b * s
        if T % G:
            raise ValueError(f"{T} tokens not divisible into {G} groups")
        if self.dispatch_impl not in ("einsum", "index"):
            raise ValueError(
                f"dispatch_impl must be 'einsum' or 'index', got "
                f"{self.dispatch_impl!r}"
            )
        t = T // G
        tokens = x.reshape(G, t, d)

        # router in fp32 — cheap, and argmax ties/probs stay stable in bf16 runs
        wr = self.param(
            "router", nn.initializers.lecun_normal(), (d, E), jnp.float32
        )
        rin = tokens.astype(jnp.float32)
        if (self.router_jitter > 0.0 and deterministic is False
                and not self.is_initializing()):
            if not self.has_rng("dropout"):
                raise ValueError(
                    "router_jitter > 0 needs a 'dropout' rng stream at "
                    "train time (tpudist.train supplies one per step); "
                    "pass rngs={'dropout': key} or set router_jitter=0"
                )
            j = self.router_jitter
            rin = rin * jax.random.uniform(
                self.make_rng("dropout"), rin.shape, jnp.float32,
                1.0 - j, 1.0 + j,
            )
        logits = jnp.einsum("gtd,de->gte", rin, wr)
        probs = jax.nn.softmax(logits)
        if self.router_z_loss > 0.0:
            z = jax.nn.logsumexp(logits, axis=-1)  # [G, t]
            self.sow(
                "losses", "moe_router_z_loss",
                self.router_z_loss * jnp.mean(z * z),
                reduce_fn=lambda a, b: a + b,
                init_fn=lambda: jnp.zeros((), jnp.float32),
            )
        capacity = expert_capacity(
            t, E, top_k=self.top_k, capacity_factor=self.capacity_factor
        )
        idx, gates, pos, keep, aux = jax.vmap(
            lambda p: top_k_routing(p, self.top_k, capacity)
        )(probs)
        self.sow(
            "losses", "moe_aux_loss", self.aux_loss_weight * jnp.mean(aux),
            reduce_fn=lambda a, b: a + b,
            init_fn=lambda: jnp.zeros((), jnp.float32),
        )
        # router observability (docs/OBSERVABILITY.md §1): dispatched load
        # fraction per expert, dropped-choice rate, unscaled aux. Dead
        # code (DCE'd) unless the caller makes 'moe_stats' mutable.
        kept = keep.astype(jnp.float32)
        # fraction of routed (token, choice) pairs landing on each expert:
        # Σ_e load_e = 1 − dropped, perfectly balanced = 1/E per expert
        load = jnp.mean(
            jax.nn.one_hot(idx, E, dtype=jnp.float32) * kept[..., None],
            axis=(0, 1, 2),
        )
        self.sow("moe_stats", "load", load)
        self.sow("moe_stats", "dropped", 1.0 - jnp.mean(kept))
        self.sow("moe_stats", "aux", jnp.mean(aux))

        def ew(name, shape, spec):
            return self.param(
                name,
                nn.with_partitioning(nn.initializers.lecun_normal(), spec),
                shape, jnp.float32,
            )

        col = (EXPERT_AXIS, None, TENSOR_AXIS)
        row = (EXPERT_AXIS, TENSOR_AXIS, None)
        if self.expert_act == "swiglu":
            ws = (ew("w_gate", (E, d, ff), col), ew("w_up", (E, d, ff), col),
                  ew("w_down", (E, ff, d), row))
            specs = (col, col, row)
        elif self.expert_act == "gelu":
            ws = (ew("w1", (E, d, ff), col), ew("w2", (E, ff, d), row))
            specs = (col, row)
        else:
            raise ValueError(f"unknown expert_act {self.expert_act!r}")

        ep_world = (
            int(dict(self.mesh.shape).get(EXPERT_AXIS, 1))
            if self.mesh is not None else 1
        )
        # the manual lowering splits the group dim over (data, fsdp); a
        # trace whose batch can't split — single-row decode, init probes —
        # takes the local formulation below and lets GSPMD place it (the
        # dispatch/FFN math is identical, so outputs don't change)
        dp_world = (
            int(dict(self.mesh.shape).get(DATA_AXIS, 1))
            * int(dict(self.mesh.shape).get(FSDP_AXIS, 1))
            if self.mesh is not None else 1
        )
        if (self.dispatch_impl == "index" and ep_world > 1
                and tokens.shape[0] % dp_world == 0):
            y = self._sharded_index_forward(
                tokens, idx, gates, pos, keep, ws, specs, capacity, ep_world
            )
        elif self.dispatch_impl == "index":
            dest = _flat_dest(idx, pos, keep, capacity, E)
            slots = jax.vmap(
                lambda tk, de: _index_dispatch(
                    tk.astype(self.dtype), de, E, capacity
                )
            )(tokens, dest)
            out = self._expert_ffn(slots, ws)
            y = jax.vmap(
                lambda o, de, g, k: _index_combine(o, de, g, k, self.dtype)
            )(out, dest, gates, keep)
        else:
            dispatch, combine = _one_hot_dispatch(
                idx, gates, pos, keep, E, capacity, probs.dtype
            )
            # tokens (data-sharded groups) → expert slots: GSPMD turns the
            # sharding jump into the all-to-all
            slots = jnp.einsum(
                "gtec,gtd->gecd", dispatch.astype(self.dtype),
                tokens.astype(self.dtype),
            )
            slots = self._constrain(slots)
            out = self._constrain(self._expert_ffn(slots, ws))
            # expert slots → tokens (the reverse all-to-all), gate-weighted
            y = jnp.einsum(
                "gtec,gecd->gtd", combine.astype(self.dtype), out
            )
        return y.reshape(b, s, d)

    def _expert_ffn(self, slots, ws):
        """Per-expert FFN over ``[..., E_local, C, d]`` slots; ``ws`` are
        the (possibly locally-sharded) stacked expert weights."""
        if self.expert_act == "swiglu":
            wg, wu, wd = ws
            h = nn.silu(
                jnp.einsum("...ecd,edf->...ecf", slots, wg.astype(self.dtype))
            ) * jnp.einsum("...ecd,edf->...ecf", slots, wu.astype(self.dtype))
            return jnp.einsum("...ecf,efd->...ecd", h, wd.astype(self.dtype))
        w1, w2 = ws
        h = jnp.einsum("...ecd,edf->...ecf", slots, w1.astype(self.dtype))
        h = nn.gelu(h)
        return jnp.einsum("...ecf,efd->...ecd", h, w2.astype(self.dtype))

    def _sharded_index_forward(self, tokens, idx, gates, pos, keep, ws,
                               specs, capacity: int, ep_world: int):
        """The explicit expert all-to-all: index dispatch under a manual
        ``shard_map`` over the WHOLE mesh.

        Tokens ride their existing ``(data, fsdp)`` batch sharding and are
        REPLICATED over ``expert`` (that axis shards only weights), so
        dispatch needs no send at all: each expert shard scatters/gathers
        its OWN experts' slots from its local token copy and runs its
        local FFNs. The one collective is the ``all_gather`` of the slot
        OUTPUTS over ``expert`` — ``G·E·C·d`` dtype bytes, exactly the
        dispatched-token volume — after which the combine is a local
        gather. Row-parallel ``tensor`` partial sums stay a ``psum``,
        matching the metadata the einsum path hands GSPMD."""
        from jax.sharding import PartitionSpec as P

        from tpudist.utils.compat import shard_map

        E = self.num_experts
        if E % ep_world:
            raise ValueError(
                f"num_experts={E} not divisible by the mesh's "
                f"expert={ep_world} axis"
            )
        e_loc = E // ep_world
        tp_world = int(dict(self.mesh.shape).get(TENSOR_AXIS, 1))
        batch = P((DATA_AXIS, FSDP_AXIS), None, None)
        w_specs = tuple(P(*spec) for spec in specs)

        def fwd(tk, idx, gates, pos, keep, *ws_loc):
            ei = jax.lax.axis_index(EXPERT_AXIS)
            lo = ei * e_loc
            # choices landing on THIS shard's experts, re-based locally;
            # everything else collides on the local garbage slot
            mine = keep & (idx >= lo) & (idx < lo + e_loc)
            dest_l = jnp.where(
                mine, (idx - lo) * capacity + pos, e_loc * capacity
            )
            slots = jax.vmap(
                lambda tkg, de: _index_dispatch(
                    tkg.astype(self.dtype), de, e_loc, capacity
                )
            )(tk, dest_l)  # [G_loc, e_loc, C, d]
            out = self._expert_ffn(slots, ws_loc)
            if tp_world > 1:
                # row-parallel partial sums over the ffn shards
                out = jax.lax.psum(out, TENSOR_AXIS)
            # THE all-to-all's return leg: every shard needs every
            # expert's outputs for its local tokens
            outs = jax.lax.all_gather(
                out, EXPERT_AXIS, axis=1, tiled=True
            )  # [G_loc, E, C, d]
            dest = _flat_dest(idx, pos, keep, capacity, E)
            return jax.vmap(
                lambda o, de, g, k: _index_combine(o, de, g, k, self.dtype)
            )(outs, dest, gates, keep)

        routed = P((DATA_AXIS, FSDP_AXIS), None, None)
        return shard_map(
            fwd,
            mesh=self.mesh,
            in_specs=(batch, routed, routed, routed, routed, *w_specs),
            out_specs=batch,
            check_vma=False,
        )(tokens, idx, gates, pos, keep, *ws)

    def _constrain(self, slots):
        if self.mesh is None:
            return slots
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            slots,
            NamedSharding(
                self.mesh, P((DATA_AXIS, FSDP_AXIS), EXPERT_AXIS, None, None)
            ),
        )
