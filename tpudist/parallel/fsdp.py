"""Fully-sharded data parallelism (ZeRO-3 style) over the ``fsdp`` mesh axis.

No reference counterpart (SURVEY.md §2.12: the reference's only strategy is
DDP with fully-replicated params, /root/reference/main.py:83); built so the
framework trains models whose params + Adam moments exceed one chip's HBM.

TPU-native design: FSDP is *a sharding, not a wrapper*. Each parameter (and
its optimizer-state mirrors) is sharded over ``fsdp`` along its largest
divisible dimension; the train step is the ordinary compiled step from
``tpudist.train.make_train_step`` with ``state_sharding`` set to these
shardings. GSPMD then materializes each layer's params with an ICI
all-gather right before use and reduce-scatters its gradients — the
overlap/scheduling that DeepSpeed/FSDP implement by hand in C++/Python hooks
falls out of XLA's compilation of the sharded program. The batch is sharded
over ``(data, fsdp)`` jointly, so the fsdp axis also contributes data
parallelism (ZeRO semantics: sharded state, DP gradients).

Memory-discipline composition: FSDP shares its two sibling surfaces with
plain DP rather than growing private ones — the spec rule is
``tpudist.mesh.largest_divisible_spec`` (the same rule ZeRO-1
``tpudist.optim.shard_state`` applies over ``data``), and activation
rematerialization arrives through the SAME named-policy surface every
strategy uses: ``make_train_step(remat=...)`` / the models'
``remat_policy`` field (``tpudist.remat``), orthogonal to the state
shardings this module produces.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.mesh import FSDP_AXIS, largest_divisible_spec


def fsdp_spec(shape, fsdp_size: int, *, min_size: int = 1024) -> P:
    """PartitionSpec sharding the largest ``fsdp``-divisible dim of ``shape``.

    Leaves smaller than ``min_size`` elements (biases, BN scales, scalars)
    stay replicated — sharding them buys no memory and costs a collective.
    (The rule itself lives in :func:`tpudist.mesh.largest_divisible_spec`,
    shared with the ZeRO-1 optimizer-state sharding over ``data``.)
    """
    return largest_divisible_spec(shape, FSDP_AXIS, fsdp_size, min_size=min_size)


def fsdp_shardings(state, mesh: Mesh, *, min_size: int = 1024):
    """A ``state``-shaped pytree of NamedShardings sharding every leaf over
    ``fsdp``. Works on a concrete TrainState or a ``jax.eval_shape`` result;
    pass to ``make_train_step(..., state_sharding=...)``.
    """
    fsdp_size = mesh.shape[FSDP_AXIS]
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(
            mesh, fsdp_spec(np.shape(x), fsdp_size, min_size=min_size)
        ),
        state,
    )


def compose_fsdp(state, mesh: Mesh, *, min_size: int = 1024):
    """FSDP composed with existing model-parallel shardings.

    Leaves that already carry a sharding with named axes (e.g. Megatron
    ``tensor`` specs from ``nn.with_partitioning``) keep it; every
    still-replicated leaf gets an ``fsdp`` spec. This is the 3-D recipe
    (dp × fsdp × tp): TP owns the transformer kernels, FSDP shards the
    rest (embeddings, layernorms above ``min_size``) plus all the TP-less
    optimizer mirrors.

    Returns ``(placed_state, shardings)`` like :func:`shard_state`.
    """
    fsdp_size = mesh.shape[FSDP_AXIS]

    def merge(x):
        spec = getattr(getattr(x, "sharding", None), "spec", P())
        if any(s is not None for s in spec):
            return x.sharding
        return NamedSharding(
            mesh, fsdp_spec(np.shape(x), fsdp_size, min_size=min_size)
        )

    shardings = jax.tree_util.tree_map(merge, state)
    return jax.device_put(state, shardings), shardings


def shard_state(state, mesh: Mesh, *, min_size: int = 1024):
    """Re-place a (typically replicated) TrainState under FSDP shardings.

    Returns ``(sharded_state, shardings)``; feed the shardings to
    ``make_train_step`` so the step consumes and produces sharded state.

    Note: leaves whose sharding is unchanged (small replicated params, the
    step counter) are *aliased*, not copied, by ``device_put`` — after the
    (donating) train step consumes the result, the input ``state`` is dead.
    """
    shardings = fsdp_shardings(state, mesh, min_size=min_size)
    return jax.device_put(state, shardings), shardings
