"""Tensor parallelism — Megatron-style sharded layers via GSPMD.

No reference counterpart (SURVEY.md §2.12: DP is the only strategy there);
built so the framework scales models past one chip's HBM. The design is
sharding-metadata-only: layers annotate their params with
``nn.with_partitioning`` over the ``tensor`` mesh axis (see
``tpudist.models.gpt2`` for the canonical annotation: qkv/mlp_fc
column-parallel, out/mlp_proj row-parallel, vocab-sharded embedding), and
``tpudist.train.create_train_state``/``make_train_step`` turn that metadata
into NamedShardings. XLA then derives the per-block all-reduces and overlaps
them with compute — no hand-written collective, and composition with
data/sequence axes falls out of the mesh.
"""

from __future__ import annotations

from flax import linen as nn


def partitioned(init, *dim_axes):
    """Annotate a param initializer with one mesh-axis name (or None) per
    kernel dimension, e.g. ``partitioned(init, None, None, TENSOR_AXIS, None)``
    for a column-parallel qkv kernel of shape [d, 3, heads, head_dim]."""
    return nn.with_partitioning(init, dim_axes)
