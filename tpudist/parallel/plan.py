"""ParallelPlan: ONE resolver for composed (data, fsdp, pipe, tensor) runs.

No reference counterpart (SURVEY.md §2.12: the reference's only strategy is
replicated-param DDP); built so the mesh's model axes stop being three
separately-wired features and become one validated composition — the
Partitioner shape of SNIPPETS.md [3], specialized to this repo's
TrainState/mesh conventions, grounded in "Scalable Training of Language
Models using JAX pjit and TPUv4" (PAPERS.md).

What the plan resolves, from ONE walk over the model's abstract state:

- **Megatron TP** — the models' existing ``nn.Partitioned`` metadata
  (qkv/mlp_fc column-parallel, out/mlp_proj row-parallel, vocab-sharded
  embedding) is kept verbatim; the plan never re-shards a leaf that
  already names a real (>1) mesh axis.
- **Stacked-block PP** — the pipelined models' ``('pipe', ...)`` boxes are
  metadata like any other: stage placement (and the Adam mirrors') falls
  out of the same walk.
- **FSDP** — every leaf the metadata left replicated is scattered over
  ``fsdp`` along its largest divisible dim (``tpudist.mesh
  .largest_divisible_spec`` — the ONE spec rule ZeRO-1 uses over ``data``),
  optimizer mirrors included; leaves under ``min_size`` stay replicated.
- **ZeRO-1 composition** — :meth:`wrap_zero1` builds an
  ``optim.shard_state`` whose layout SKIPS every leaf the plan already
  fsdp-shards (no double-sharding: a leaf is either fsdp-scattered by the
  plan or data-sharded/padded by ZeRO-1, never flattened out from under
  its fsdp spec), and :meth:`state_shardings` overlays the two so the
  state is BORN composed inside ``create_train_state``'s one compiled
  init.
- **Batch / rng** — the batch rides the framework's ``(data, fsdp)``
  sharding (:func:`tpudist.mesh.batch_sharding`); the per-step dropout/SR
  keys are derived host-side from the step counter and replicate by
  construction, so the plan has nothing to re-place there.
- **Explicit reduction routing** — ``make_train_step(reduce=...)``'s
  pure-DP refusals become routing: :meth:`validate_reduce` allows the
  explicit/quantized reducer only when the plan has no real model axis
  (it reduces over ``data`` alone), and points at the fix otherwise;
  composed plans keep the implicit GSPMD reduction, which already
  reduce-scatters over ``fsdp`` and inserts the per-block ``tensor``
  all-reduces from the param shardings.

Threading: ``create_train_state(..., plan=)`` births the composed state,
``make_train_step(..., plan=)`` validates the composition and carries it
as ``step.plan``, ``fit(plan=...)`` does both and records the plan's axis
worlds in the checkpoint geometry meta
(``fsdp_world``/``tensor_world``/``pipe_world`` — old metas default 1,
non-data resizes refuse with a precise hint,
``tpudist.resilience.elastic``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist import mesh as mesh_lib
from tpudist.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    PIPELINE_AXIS,
    TENSOR_AXIS,
    largest_divisible_spec,
)

__all__ = ["ParallelPlan", "spec_is_sharded"]


def spec_is_sharded(spec, mesh: Mesh) -> bool:
    """True iff ``spec`` names at least one mesh axis with >1 devices —
    the ONE "is this leaf sharded for real" predicate (Megatron
    annotations on size-1 axes are replication in fact)."""
    spec = tuple(spec) if spec is not None else ()
    for part in spec:
        names = part if isinstance(part, tuple) else (part,)
        for name in names:
            if name is not None and int(mesh.shape[name]) > 1:
                return True
    return False


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """The resolved composition for one mesh.

    Construct from an existing mesh (``ParallelPlan(mesh)``) or via
    :meth:`build` from axis sizes. ``fsdp_min_size`` is the
    replicate-below threshold shared with ZeRO-1 (elements)."""

    mesh: Mesh
    fsdp_min_size: int = 1024

    # -- geometry ----------------------------------------------------------

    @classmethod
    def build(cls, *, data: int = -1, fsdp: int = 1, pipe: int = 1,
              tensor: int = 1, expert: int = 1, devices=None,
              **kw) -> "ParallelPlan":
        """Plan + mesh in one call — ``MeshConfig`` semantics (``-1`` =
        all remaining devices)."""
        mesh = mesh_lib.create_mesh(
            mesh_lib.MeshConfig(data=data, fsdp=fsdp, pipe=pipe,
                                tensor=tensor, expert=expert),
            devices=devices,
        )
        return cls(mesh, **kw)

    @property
    def data(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    @property
    def fsdp(self) -> int:
        return int(self.mesh.shape[FSDP_AXIS])

    @property
    def pipe(self) -> int:
        return int(self.mesh.shape[PIPELINE_AXIS])

    @property
    def tensor(self) -> int:
        return int(self.mesh.shape[TENSOR_AXIS])

    @property
    def expert(self) -> int:
        return int(dict(self.mesh.shape).get(EXPERT_AXIS, 1))

    @property
    def n_chips(self) -> int:
        """Every device on the mesh — the MFU denominator's chip count
        (model axes included: per-chip FLOPs is total/chips whether a chip
        holds the whole model or 1/(tensor·pipe) of it). Delegates to the
        one shared implementation (``tpudist.telemetry.flops``)."""
        from tpudist.telemetry.flops import mesh_chips

        return mesh_chips(self.mesh)

    @property
    def model_axes(self) -> dict[str, int]:
        """The real (>1) model-parallel axes of this plan."""
        return {
            name: size
            for name, size in (("fsdp", self.fsdp), ("pipe", self.pipe),
                               ("tensor", self.tensor),
                               ("expert", self.expert))
            if size > 1
        }

    def axis_worlds(self) -> dict[str, int]:
        """The geometry-meta keys a checkpoint records for this plan —
        the layouts (and placements) below are bound to these sizes, and
        ``tpudist.resilience.elastic`` default-denies resizing them."""
        return {
            "fsdp_world": self.fsdp,
            "tensor_world": self.tensor,
            "pipe_world": self.pipe,
            "expert_world": self.expert,
        }

    def describe(self) -> str:
        return (
            f"ParallelPlan(data={self.data}, fsdp={self.fsdp}, "
            f"pipe={self.pipe}, tensor={self.tensor}, expert={self.expert})"
        )

    # -- sharding resolution ----------------------------------------------

    def _leaf_sharding(self, spec, shape) -> NamedSharding:
        """Metadata-or-fsdp merge for ONE leaf: a spec naming a real axis
        is kept verbatim (TP/PP metadata — never double-sharded); anything
        else gets the fsdp largest-divisible scatter (replicated when the
        axis is 1 or the leaf is small)."""
        spec = spec if isinstance(spec, P) else P()
        if spec_is_sharded(spec, self.mesh):
            return NamedSharding(self.mesh, spec)
        return NamedSharding(
            self.mesh,
            largest_divisible_spec(
                tuple(np.shape(shape) if not hasattr(shape, "shape")
                      else shape.shape),
                FSDP_AXIS, self.fsdp, min_size=self.fsdp_min_size,
            ),
        )

    def shardings(self, tree):
        """Sharding tree for any (possibly ``nn.Partitioned``-boxed) value
        or ``eval_shape`` tree: metadata kept, replicated leaves
        fsdp-scattered. Works on params, whole TrainStates, or opt-state
        mirrors that kept their boxes."""
        specs = nn.get_partition_spec(tree)
        shapes = nn.meta.unbox(tree)
        return jax.tree_util.tree_map(
            self._leaf_sharding, specs, shapes,
            is_leaf=lambda s: isinstance(s, P),
        )

    def _zero1_skip(self, shape) -> bool:
        """ZeRO-1 skip rule: leaves the plan fsdp-scatters keep their
        natural shape and fsdp placement — ZeRO-1's pad-and-reshape over
        ``data`` must not flatten them out from under it."""
        if self.fsdp <= 1:
            return False
        spec = largest_divisible_spec(
            tuple(shape), FSDP_AXIS, self.fsdp, min_size=self.fsdp_min_size
        )
        return any(s is not None for s in spec)

    def _mirror_overlay(self, spec, ref) -> NamedSharding:
        """Sharding for an opt-state mirror ZeRO-1 SKIPPED (a leaf the
        plan fsdp-scatters): the fsdp spec, UPGRADED to shard the same
        dim over ``('fsdp', 'data')`` jointly when it divides — the
        ZeRO-1 overlay for the leaves the pad/reshape path must not
        touch. The mirror bytes shrink another ``data``× while the PARAM
        keeps its plain fsdp layout (weights are read every forward;
        mirrors only at the update, where GSPMD's reduce-scatter already
        pays the data-axis traffic). Metadata-sharded (TP/PP) mirrors
        and non-divisible or small leaves keep :meth:`_leaf_sharding`'s
        answer untouched."""
        base = self._leaf_sharding(spec, ref)
        if self.data <= 1 or self.fsdp <= 1:
            return base
        if spec_is_sharded(spec if isinstance(spec, P) else P(), self.mesh):
            return base
        shape = tuple(
            ref.shape if hasattr(ref, "shape") else np.shape(ref)
        )
        fs = largest_divisible_spec(
            shape, FSDP_AXIS, self.fsdp, min_size=self.fsdp_min_size
        )
        if FSDP_AXIS not in fs:
            return base  # small/indivisible: replicated either way
        i = list(fs).index(FSDP_AXIS)
        if shape[i] % (self.fsdp * self.data):
            return base
        new = list(fs)
        new[i] = (FSDP_AXIS, DATA_AXIS)
        return NamedSharding(self.mesh, P(*new))

    def _names_expert(self, spec) -> bool:
        """True iff ``spec`` names a real (>1) ``expert`` axis — the
        expert-parallel sibling of :func:`spec_is_sharded`."""
        if self.expert <= 1:
            return False
        for part in (tuple(spec) if spec is not None else ()):
            names = part if isinstance(part, tuple) else (part,)
            if EXPERT_AXIS in names:
                return True
        return False

    def wrap_zero1(self, tx, params=None):
        """ZeRO-1 optimizer-state sharding composed with this plan:
        ``optim.shard_state`` over ``data``, skipping the leaves the plan
        already scatters over ``fsdp`` (sharded state either way, no
        double-sharding). The returned wrapper still advertises
        ``state_shardings``; feed the wrapped tx to
        ``create_train_state(..., plan=self)``.

        ``params`` (optional, BOXED abstract or concrete tree): on an
        expert-parallel plan, ZeRO-1's pad-and-reshape over ``data`` must
        also not flatten the expert-sharded leaves out from under their
        ``('expert', ...)`` placement. The skip rule is shape-only (the
        optimizer sees unboxed leaves), so the expert leaves are
        identified here by metadata and their SHAPES join the skip set —
        their mirrors keep the expert sharding via
        :meth:`opt_state_shardings`'s metadata overlay instead."""
        from tpudist.optim import shard_state

        base_skip = self._zero1_skip if self.fsdp > 1 else None
        expert_shapes: set[tuple] = set()
        if params is not None and self.expert > 1:
            specs = nn.get_partition_spec(params)
            shapes = nn.meta.unbox(params)

            def visit(spec, ref):
                if self._names_expert(spec):
                    expert_shapes.add(
                        tuple(ref.shape if hasattr(ref, "shape")
                              else np.shape(ref))
                    )

            jax.tree_util.tree_map(
                visit, specs, shapes, is_leaf=lambda s: isinstance(s, P)
            )
        if expert_shapes:
            def skip(shape):
                if tuple(shape) in expert_shapes:
                    return True
                return bool(base_skip and base_skip(shape))
        else:
            skip = base_skip
        return shard_state(
            tx, self.mesh, min_size=self.fsdp_min_size, skip_spec=skip,
        )

    def opt_state_shardings(self, boxed_params, tx):
        """Opt-state sharding tree under this plan.

        A plain optax ``tx``: the mirrors are metadata+fsdp-sharded like
        their params (``tx.init`` traced on the BOXED params so the
        mirrors carry the same partitioning boxes). A ZeRO-1 wrapper
        (``state_shardings`` attribute — built via :meth:`wrap_zero1`):
        its data-axis layout wins for every leaf it stores
        (pad/natural-shard), and the plan's fsdp scatter covers the leaves
        it skipped.
        """
        params_shapes = nn.meta.unbox(boxed_params)
        if hasattr(tx, "state_shardings"):
            zero1 = tx.state_shardings(params_shapes)
            stored = jax.eval_shape(tx.init, params_shapes)
            # the wrapper's init unboxes the mirrors (pure shape math),
            # losing their Megatron/pipe boxes — recover the metadata by
            # tracing the INNER tx over the boxed params (same tree
            # structure; only pad-mode leaves change stored shape, and
            # ZeRO-1 owns those outright). Mirrors of tensor/pipe-sharded
            # params then stay ALIGNED with their params instead of
            # getting a shape-rule fsdp scatter the update would reshard
            # every step.
            specs = nn.get_partition_spec(
                jax.eval_shape(tx.inner.init, boxed_params)
            )
            treedef = jax.tree_util.tree_structure(zero1)
            out = [
                z if spec_is_sharded(getattr(z, "spec", P()), self.mesh)
                else self._mirror_overlay(spec, ref)
                for z, ref, spec in zip(
                    jax.tree_util.tree_leaves(zero1),
                    treedef.flatten_up_to(stored),
                    treedef.flatten_up_to(specs),
                )
            ]
            return jax.tree_util.tree_unflatten(treedef, out)
        # plain tx: trace init over the boxed params so params-shaped
        # mirrors inherit the metadata, then merge fsdp in
        return self.shardings(jax.eval_shape(tx.init, boxed_params))

    def state_shardings(self, boxed_state_fn: Callable[[], Any], tx=None):
        """TrainState-shaped sharding tree for ``boxed_state_fn`` (a
        no-arg builder of the BOXED TrainState — ``create_train_state``'s
        ``_boxed``) under this plan: params/batch-stats metadata+fsdp,
        opt-state per :meth:`opt_state_shardings` when ``tx`` is given
        (required for ZeRO-1 wrappers; a plain tx may pass ``None`` and
        take the metadata path for its mirrors)."""
        abstract = jax.eval_shape(boxed_state_fn)
        merged = self.shardings(abstract)
        if tx is not None and hasattr(tx, "state_shardings"):
            merged = merged.replace(
                opt_state=self.opt_state_shardings(abstract.params, tx)
            )
        return merged

    def place(self, state):
        """Re-place an EXISTING (concrete) TrainState under this plan —
        the post-hoc sibling of the born-sharded
        ``create_train_state(plan=)`` path. Leaves already sharded for
        real keep their placement; returns ``(placed_state, shardings)``.
        Note: unchanged leaves are aliased, not copied (same caveat as
        ``fsdp.shard_state``)."""

        def merge(x):
            spec = getattr(getattr(x, "sharding", None), "spec", P())
            if spec_is_sharded(spec, self.mesh):
                return x.sharding
            return self._leaf_sharding(P(), np.shape(x))

        shardings = jax.tree_util.tree_map(merge, state)
        return jax.device_put(state, shardings), shardings

    # -- batch -------------------------------------------------------------

    @property
    def batch_axes(self) -> tuple[str, str]:
        """Mesh axes the batch dim is split over — ``fsdp`` contributes
        data parallelism (ZeRO semantics: sharded state, DP gradients)."""
        return (DATA_AXIS, FSDP_AXIS)

    def batch_sharding(self, *, extra_dims: int = 3) -> NamedSharding:
        return mesh_lib.batch_sharding(self.mesh, extra_dims=extra_dims)

    @property
    def data_parallel_size(self) -> int:
        return mesh_lib.data_parallel_size(self.mesh)

    # -- validation --------------------------------------------------------

    def validate_reduce(self, reduce) -> None:
        """Explicit/quantized gradient reduction reduces over the
        ``data`` axis ONLY (per-replica grads inside a data-manual
        shard_map require replicated params). A composed plan routes to
        the implicit GSPMD reduction instead — and an explicit request
        must say which knob to move, not just refuse."""
        if reduce is None or reduce in ("none", "auto"):
            # "auto" resolves against the mesh's data column
            # (tpudist.parallel.dp.resolve_method) and lands on the
            # implicit path whenever the data axis stays on ICI — routing,
            # not refusal
            return
        axes = self.model_axes
        if axes:
            moved = " * ".join(f"{k}={v}" for k, v in axes.items())
            raise ValueError(
                f"reduce={reduce!r} is pure-DP (the explicit bucketed/"
                f"quantized reducer reduces over the 'data' axis only) but "
                f"this plan shards the model over {moved} — keep "
                "reduce='none' (GSPMD already reduce-scatters gradients "
                "over 'fsdp' and inserts the per-block 'tensor' "
                "all-reduces), or move those devices to the data axis "
                f"(ParallelPlan.build(data=-1) / MeshConfig(data=-1)) "
                "before asking for the explicit wire format"
            )

    def validate_state_sharding(self, state_sharding) -> None:
        """A plan-built step must consume plan-resolved shardings — a
        replicated ``state_sharding`` would silently all-gather the very
        leaves the plan scattered."""
        if state_sharding is None:
            raise ValueError(
                "make_train_step(plan=...) needs state_sharding: build "
                "the state with create_train_state(..., plan=plan) and "
                "pass state_shardings_of(state) (fit(plan=...) does both) "
                "— a replicated default would all-gather every "
                "fsdp/tensor/pipe-scattered leaf back onto each chip"
            )
