"""Data-parallel sharding rules — the DDP equivalence (SURVEY.md §2.5).

DDP = params replicated on every worker + per-step gradient all-reduce.
On the TPU mesh that is exactly: params/opt-state replicated, batch sharded
over ``data``; the all-reduce is implicit in ``jax.grad`` of a global-batch
mean under GSPMD.
"""

from __future__ import annotations

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.mesh import DATA_AXIS, FSDP_AXIS, replicated_sharding


def dp_shardings(mesh: Mesh, batch_ndims: dict[str, int]):
    """(state_sharding, batch_shardings) for a plain DP step."""
    state = replicated_sharding(mesh)
    batch = {
        k: NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS), *([None] * (nd - 1))))
        for k, nd in batch_ndims.items()
    }
    return state, batch
