"""Explicit data-parallel gradient reduction: the DDP Reducer, TPU-native.

The framework default leaves the gradient all-reduce to XLA: the train step's
loss is the mean over the *global* batch, so ``jax.grad`` produces
already-reduced gradients and GSPMD inserts (and overlaps) the psum — the
right call on an ICI-only mesh, where the compiler's scheduling beats
anything hand-rolled. On a multi-slice pod the ``data`` axis crosses DCN and
the fp32 reduction becomes the dominant step-time term (arXiv:2204.06514);
this module is the opt-in explicit path for exactly that regime
(``make_train_step(reduce=...)``):

- gradients are computed PER REPLICA inside one ``shard_map`` over the
  ``data`` axis (the loss is the local-shard mean; its cross-replica mean —
  one scalar psum — reproduces the global-batch loss exactly);
- they are flattened into fixed-size buckets (:class:`tpudist.comm
  .BucketLayout` — the DDP-bucket equivalent) and all-reduced explicitly:
  ``"bucketed"`` as fp32 psum (isolates the restructuring), ``"quantized"``
  as int8 on the wire with per-bucket scales, stochastic rounding, fp32
  master accumulation, and an error-feedback residual carried in the train
  state (:func:`tpudist.comm.ring_allreduce_quantized` — the EQuARX recipe,
  arXiv:2506.17615) so convergence tracks fp32 within tolerance;
- with ``grad_accum > 1`` the reduction is double-buffered inside the
  accumulation scan: iteration ``i`` reduces microbatch ``i-1``'s buckets
  while computing microbatch ``i``'s forward/backward — the two have no
  data dependency, so XLA's scheduler overlaps the collective with compute
  (the async-bucket overlap DDP's C++ Reducer implements with hooks). The
  first iteration reduces the zero-initialized pending buffer, which doubles
  as the residual flush; one final reduction after the scan drains the last
  microbatch — ``grad_accum + 1`` reductions per step. Configurations
  WITHOUT a residual (``"bucketed"``, or ``error_feedback=False``) have
  nothing to flush and nothing the overlap's extra bytes would buy: they
  accumulate locally and reduce once after the scan — the implicit path's
  schedule, explicit. docs/PERF.md §11 carries the honest byte math of the
  EF path's trade (int8 pays for the extra reductions; fp32 would not).

Semantics vs the implicit path: identical gradients for ``"bucketed"`` (up
to fp32 reduction order) for deterministic forwards; models with
``dropout > 0`` draw independent per-REPLICA masks (the step key folded
with ``axis_index`` — DDP's exact dropout semantics) instead of the
implicit path's one global-batch draw, so dropout trajectories are
equivalent in distribution, not bitwise. ``"quantized"`` adds zero-mean
quantization noise bounded by the per-bucket scale, compensated across
steps by the residual.
Batch-norm: inside ``shard_map`` each replica computes LOCAL batch
statistics and the updated running stats are psum-averaged — the mean of
per-shard means IS the global batch mean (equal shards), the variance is
the DDP-default within-shard variance, not SyncBN's global one. ZeRO-1
(``shard_opt_state``) composes: grads come back replicated and dequantized,
so XLA's weight-update-sharding decomposition adds only the params
all-gather ZeRO-1 already pays — no second gradient reduction.

Restrictions (enforced loudly): pure DP only — params replicated, ``fsdp``
axis size 1, no ``batch_spec`` overrides (context-parallel models keep the
implicit path), no ``"_"``-prefixed device operands (DeviceCachedLoader
rides the implicit path), and models must NOT wrap their own ``shard_map``
(pass ``mesh=None`` to the model zoo: inside the reduction's manual region
the batch is already local, which is exactly what the kernels want).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist import comm
from tpudist.mesh import DATA_AXIS, FSDP_AXIS
from tpudist.utils.compat import shard_map

METHODS = ("none", "bucketed", "quantized", "auto")


def resolve_method(method: str, mesh: Mesh) -> str:
    """``"auto"`` → ``"quantized"`` when THIS mesh's ``data`` axis crosses
    DCN, ``"none"`` otherwise — on an ICI-only reduction the implicit XLA
    psum is already bandwidth-optimal in fp32 and the quantization would
    spend quality on bytes nothing is short of. The check walks one
    data-axis column of ``mesh.devices`` (not ``jax.devices()``: a mesh
    confined to one slice of a multi-slice attach — the other slice held
    by another job, or mapped to a model axis — reduces over ICI and must
    stay on the implicit path). A mesh with one ``data`` replica has
    nothing to reduce: always ``"none"``.

    ``"auto"`` on a mesh with ANY real non-data axis also resolves
    ``"none"`` — routing, not refusal: the explicit reducer cannot run on
    such a mesh anyway, axis by axis — ``fsdp`` trips the
    replicated-params guard below, ``tensor``/``pipe``/``expert`` models
    shard params (make_train_step's state-sharding guard), and ``seq``
    (context-parallel) models require the ``batch_spec`` the explicit
    path refuses — so an "auto" that resolved ``"quantized"`` there
    would only turn bring-up into a crash. Even a DCN-crossing data axis
    keeps the implicit GSPMD reduction on composed meshes; only an
    EXPLICIT ``"bucketed"``/``"quantized"`` request refuses loudly (the
    guards name the fix)."""
    if method not in METHODS:
        raise ValueError(f"reduce must be one of {METHODS}, got {method!r}")
    if int(mesh.shape[DATA_AXIS]) <= 1:
        return "none"
    if method == "auto":
        import numpy as np

        if any(
            int(size) > 1
            for name, size in mesh.shape.items()
            if name != DATA_AXIS
        ):
            return "none"
        data_column = np.asarray(mesh.devices).reshape(
            int(mesh.shape[DATA_AXIS]), -1
        )[:, 0]
        return "quantized" if comm.multislice_dcn(data_column) else "none"
    return method


class GradReducer:
    """The explicit-reduction engine ``make_train_step(reduce=...)`` builds.

    Holds the static configuration (mesh, method, bucket size, error
    feedback, stochastic-rounding seed); the bucket layout is derived on
    demand from whatever params tree it is shown (concrete, tracer, or
    eval_shape — same shapes, same layout), so construction needs no
    params.
    """

    def __init__(
        self,
        mesh: Mesh,
        method: str,
        *,
        bucket_size: int = comm.DEFAULT_BUCKET_ELEMS,
        error_feedback: bool = True,
        seed: int = 0,
    ):
        if method not in ("bucketed", "quantized"):
            raise ValueError(
                f"GradReducer method must be 'bucketed' or 'quantized', got "
                f"{method!r} (resolve 'auto' via resolve_method first)"
            )
        if int(mesh.shape[FSDP_AXIS]) != 1:
            raise ValueError(
                "explicit gradient reduction is pure-DP: it reduces over "
                "the 'data' axis only and requires replicated params, but "
                f"the mesh has fsdp={int(mesh.shape[FSDP_AXIS])} — keep "
                "reduce='none' (GSPMD already reduce-scatters per layer "
                "over 'fsdp'), or move those devices to the data axis "
                "(MeshConfig(data=-1, fsdp=1) / ParallelPlan.build("
                "data=-1)) before asking for the explicit wire format"
            )
        self.mesh = mesh
        self.method = method
        self.bucket_size = int(bucket_size)
        # error feedback only means something when the wire is lossy
        self.error_feedback = bool(error_feedback) and method == "quantized"
        self.seed = int(seed)
        self.world = int(mesh.shape[DATA_AXIS])

    # -- layout / residual -------------------------------------------------

    def layout_for(self, params) -> comm.BucketLayout:
        return comm.BucketLayout(
            params, self.world, bucket_size=self.bucket_size
        )

    def attach_residual(self, state):
        """Return ``state`` with a zeroed error-feedback residual in
        ``comm_residual`` — ``[world, n_buckets, bucket_size]`` fp32,
        sharded over ``data`` so each replica stores only its own slice
        (the residual is PER-REPLICA local state: each replica's
        quantization error differs). Allocated sharded directly on the
        devices; the full array never exists on the host. No-op when the
        method needs no residual."""
        if not self.error_feedback:
            return state
        layout = self.layout_for(state.params)
        sh = self.residual_sharding()
        shape = (self.world, layout.n_buckets, layout.bucket_size)
        zeros = jax.jit(
            lambda: jnp.zeros(shape, jnp.float32), out_shardings=sh
        )()
        return state.replace(comm_residual=zeros)

    def residual_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(DATA_AXIS))

    # -- the in-step compute path -----------------------------------------

    def compute(self, grad_fn: Callable, params, batch_stats, rows, step,
                residual, grad_accum: int):
        """The explicit-path replacement for the train step's gradient
        block: local forward/backward per replica, explicit bucket
        reduction, replicated outputs.

        ``grad_fn``: ``(params, stats, batch, step) → ((loss, new_stats),
        grads)`` — exactly ``make_train_step``'s ``value_and_grad``.
        ``rows``: the staged batch dict (global arrays; leading dim —
        second with ``grad_accum > 1`` — sharded over ``data``). Returns
        ``(loss, grads, new_stats, new_residual)``, all replicated except
        the residual (``None`` when error feedback is off); grads are the
        cross-replica mean, dequantized — the values every downstream
        consumer (optimizer, non-finite guard, telemetry norms) sees.
        """
        layout = self.layout_for(params)
        use_ef = self.error_feedback
        if use_ef and residual is None:
            raise ValueError(
                "reduce='quantized' with error feedback needs the residual "
                "in the train state — initialize it once with "
                "step.grad_reducer.attach_residual(state) (fit() does this "
                "automatically)"
            )
        axis, method, world, seed = DATA_AXIS, self.method, self.world, self.seed

        def local(params, stats, rows, step, res):
            # res: [1, n_buckets, bucket_size] block (or a zeros dummy when
            # EF is off — kept in the signature so both variants share one
            # spec tuple)
            r = res[0] if use_ef else None
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.key(seed), step),
                jax.lax.axis_index(axis),
            )
            if grad_accum == 1:
                (loss, new_stats), g = grad_fn(params, stats, rows, step)
                mean, r = comm.reduce_buckets(
                    layout.flatten(g), r, layout, axis,
                    jax.random.fold_in(key, 0), method=method,
                )
            elif use_ef:
                zeros = jnp.zeros(
                    (layout.n_buckets, layout.bucket_size), jnp.float32
                )

                def micro(carry, xs):
                    pending, rsum, stats, lsum, r = carry
                    mb, i = xs
                    # double buffer: reduce microbatch i-1's buckets (no
                    # data dependency on this iteration's grad_fn, so XLA
                    # overlaps the collective with the forward/backward);
                    # i=0 reduces the zero init, which flushes the residual
                    reduced, r = comm.reduce_buckets(
                        pending, r, layout, axis,
                        jax.random.fold_in(key, i), method=method,
                    )
                    rsum = rsum + reduced
                    (l, stats), g = grad_fn(
                        params, stats, mb, step * grad_accum + i
                    )
                    return (layout.flatten(g), rsum, stats, lsum + l, r), None

                carry = (zeros, zeros, stats, jnp.zeros((), jnp.float32), r)
                (pending, rsum, new_stats, lsum, r), _ = jax.lax.scan(
                    micro, carry, (rows, jnp.arange(grad_accum))
                )
                # drain the last microbatch's pending buckets
                reduced, r = comm.reduce_buckets(
                    pending, r, layout, axis,
                    jax.random.fold_in(key, grad_accum), method=method,
                )
                mean = (rsum + reduced) / grad_accum
                loss = lsum / grad_accum
            else:
                # no residual to flush (bucketed, or EF off): the zeroth
                # double-buffer reduction would move a full bucket set of
                # exact zeros — accumulate locally instead and reduce ONCE
                # after the scan (the implicit path's schedule, explicit).
                # Per-micro overlap is the quantized+EF path's trade; here
                # it would only buy accum× the bytes for nothing.
                def micro(carry, xs):
                    gsum, stats, lsum = carry
                    mb, i = xs
                    (l, stats), g = grad_fn(
                        params, stats, mb, step * grad_accum + i
                    )
                    return (gsum + layout.flatten(g), stats, lsum + l), None

                zeros = jnp.zeros(
                    (layout.n_buckets, layout.bucket_size), jnp.float32
                )
                (gsum, new_stats, lsum), _ = jax.lax.scan(
                    micro, (zeros, stats, jnp.zeros((), jnp.float32)),
                    (rows, jnp.arange(grad_accum)),
                )
                mean, r = comm.reduce_buckets(
                    gsum, None, layout, axis,
                    jax.random.fold_in(key, 0), method=method,
                )
                mean = mean / grad_accum
                loss = lsum / grad_accum
            # scalar psum: the cross-replica mean of local-shard means IS
            # the global-batch mean (equal shards by construction)
            loss = jax.lax.psum(loss, axis) / world
            # running BN stats: mean-of-means is the exact global batch
            # mean; variance stays within-shard (DDP-default, not SyncBN)
            new_stats = jax.tree_util.tree_map(
                lambda s: jax.lax.psum(s, axis) / world, new_stats
            )
            res_out = r[None] if use_ef else res
            return loss, mean, new_stats, res_out

        if use_ef:
            res_in = residual
        else:
            # structural dummy so the EF-on and EF-off programs share one
            # signature; [world, 1, 1] keeps it a few bytes per replica
            res_in = jnp.zeros((world, 1, 1), jnp.float32)
        fn = shard_map(
            local, mesh=self.mesh,
            in_specs=(P(), P(), P(None, axis) if grad_accum > 1 else P(axis),
                      P(), P(axis)),
            out_specs=(P(), P(), P(), P(axis)),
            check_vma=False,
        )
        loss, mean_buckets, new_stats, res_out = fn(
            params, batch_stats, rows, step, res_in
        )
        grads = layout.unflatten(mean_buckets)
        return loss, grads, new_stats, (res_out if use_ef else None)

    # -- accounting / probing ---------------------------------------------

    def reductions_per_step(self, grad_accum: int) -> int:
        # the double-buffered EF scan reduces per microbatch plus the
        # residual flush; without a residual the step accumulates locally
        # and reduces once (no zeros-flush collective to pay for)
        if grad_accum == 1 or not self.error_feedback:
            return 1
        return grad_accum + 1

    def comm_stats(self, params, grad_accum: int = 1) -> dict[str, Any]:
        """Host-side wire accounting for one step at this configuration:
        the actual method's bytes, the same-schedule fp32 bytes (the
        apples-to-apples A/B the ≥3× compression claim is quoted against),
        and the single-AR fp32 bytes XLA's implicit path would move (the
        absolute baseline — with microbatch overlap the explicit path
        trades some of its 4× bytes win for latency hiding)."""
        layout = self.layout_for(params)
        r = self.reductions_per_step(grad_accum)
        return {
            "method": self.method,
            "world": self.world,
            "bucket_size": layout.bucket_size,
            "n_buckets": layout.n_buckets,
            "grad_elems": layout.total,
            "error_feedback": self.error_feedback,
            "reductions_per_step": r,
            "bytes_per_step": layout.wire_bytes(self.method, reductions=r),
            "fp32_bytes_per_step": layout.wire_bytes("bucketed", reductions=r),
            "implicit_fp32_bytes_per_step": layout.wire_bytes(
                "bucketed", reductions=1
            ),
        }

    def time_probe(self, params, grad_accum: int = 1, iters: int = 3) -> float:
        """Measured seconds of one step's reductions, STANDALONE: the
        reduce-only program (no model compute to overlap with) run on
        zeroed buckets, synced by value fetch. An upper bound on the
        per-step comm cost — with the double-buffered scan, part of it
        hides behind the microbatch compute. This is the ``comm`` column
        fit()'s step-time breakdown carries; one small compile, run once
        at bring-up."""
        layout = self.layout_for(params)
        axis, method, seed, use_ef = (
            DATA_AXIS, self.method, self.seed, self.error_feedback
        )

        def local(buckets, res):
            key = jax.random.fold_in(
                jax.random.key(seed), jax.lax.axis_index(axis)
            )
            mean, r = comm.reduce_buckets(
                buckets[0], res[0] if use_ef else None, layout, axis, key,
                method=method,
            )
            return mean, (r[None] if use_ef else res)

        fn = jax.jit(shard_map(
            local, mesh=self.mesh,
            in_specs=(P(axis), P(axis)), out_specs=(P(), P(axis)),
            check_vma=False,
        ))
        shape = (self.world, layout.n_buckets, layout.bucket_size)
        sh = self.residual_sharding()
        buckets = jax.jit(
            lambda: jnp.zeros(shape, jnp.float32), out_shardings=sh
        )()
        res = buckets if use_ef else jax.jit(
            lambda: jnp.zeros((self.world, 1, 1), jnp.float32),
            out_shardings=sh,
        )()
        best = float("inf")
        for _ in range(max(iters, 1) + 1):  # first run includes the compile
            t0 = time.perf_counter()
            mean, res = fn(buckets, res)
            float(mean[0, 0])  # value-fetch sync (bench.py's probe rule)
            best = min(best, time.perf_counter() - t0)
        return best * self.reductions_per_step(grad_accum)


_UINT_OF_SIZE = {1: "uint8", 2: "uint16", 4: "uint32"}


def _bit_checksum(x) -> jax.Array:
    """Order-independent uint32 wraparound sum of a block's raw BITS —
    exact, so a single flipped bit anywhere in the block changes the value
    (a float sum would hide a low-mantissa flip in a 100M-element tree
    under fp32 accumulation error). Modular uint32 arithmetic keeps the
    reduction deterministic and cheap; bool widens to uint8, 8-byte leaves
    bitcast to a trailing pair of uint32 words."""
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    size = jnp.dtype(x.dtype).itemsize
    if size == 8:
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    else:
        u = jax.lax.bitcast_convert_type(
            x, jnp.dtype(_UINT_OF_SIZE[size])
        )
    return jnp.sum(u.astype(jnp.uint32), dtype=jnp.uint32)


def make_divergence_probe(state, mesh: Mesh):
    """Compiled replica-divergence probe over the ``data`` axis — the
    in-graph detector for the silent multi-host failure mode where
    "data-parallel" replicas desync (missed collective, bit corruption,
    a host restarting from the wrong step) and the job quietly trains W
    different models (tpudist.telemetry.health drives it at a cadence).

    Built from a placed ``state`` (a :class:`~tpudist.train.TrainState`,
    or any pytree of mesh-placed arrays); the probe keys off each leaf's
    ACTUAL sharding, so it composes with every reduction path — implicit
    XLA psum, the explicit ``GradReducer`` shard_map (whose per-replica
    dropout/quantization must still produce bit-identical replicated
    params), and ZeRO-1 ``shard_opt_state``:

    - leaves whose spec does NOT touch ``data``/``fsdp`` (params, BN
      stats, replicated opt leaves — possibly TP-sharded over other axes)
      are REPLICATED across data replicas by contract: each replica's
      local copy is bit-checksummed and all-gathered over ``data``
      within its mesh column, and the WORST column's verdict is pmax'd
      across the remaining axes — ``replica_divergence`` counts replicas
      disagreeing with replica 0 (a desync in a TP column other than 0
      still surfaces in the fetched scalar; a fully-desynced replica
      counts once, not once per column — max, not sum, so the count
      stays a replica count). Any single-bit desync is visible within
      one probe; desyncs confined to DIFFERENT columns may under-count
      but never read zero.
    - leaves sharded over ``data``/``fsdp`` (ZeRO-1's ``[world, ...]``
      Adam mirrors) hold a DIFFERENT shard per replica — no redundancy to
      compare, so they contribute an all-axes-psum'd global checksum
      (``sharded_checksum``, drift-over-restarts evidence for the crash
      report) and an all-axes-psum'd non-finite element count folded into
      ``state_nonfinite`` (plus the worst device's replicated-leaf
      count), the realistic corruption signal for unreplicated state —
      counted no matter which mesh coordinate holds the poisoned shard.

    Returns ``None`` when the mesh has one ``data`` replica (nothing to
    compare), else a jitted ``probe(state) -> {"replica_divergence",
    "replica_checksum", "sharded_checksum", "state_nonfinite"}`` whose
    scalars ride ``copy_to_host_async`` like the step metrics. Cost: one
    bandwidth-bound read of the state plus scalar collectives — the bench
    leg ``gpt2_124m_health_overhead_pct`` holds probe+aggregation under
    1% of step time at its cadence.
    """
    if int(mesh.shape[DATA_AXIS]) <= 1:
        return None

    def _tree(s):
        if hasattr(s, "params"):
            return (s.params, getattr(s, "batch_stats", ()), s.opt_state)
        return s

    leaves = jax.tree_util.tree_leaves(_tree(state))
    rep_idx, sh_idx, rep_specs, sh_specs = [], [], [], []
    for i, x in enumerate(leaves):
        spec = getattr(getattr(x, "sharding", None), "spec", None)
        if spec is None:
            continue  # host scalars / unplaced leaves: nothing to probe
        names: set = set()
        for part in spec:
            names.update(part if isinstance(part, tuple) else (part,))
        names.discard(None)
        if names & {DATA_AXIS, FSDP_AXIS}:
            sh_idx.append(i)
            sh_specs.append(spec)
        else:
            rep_idx.append(i)
            rep_specs.append(spec)

    all_axes = tuple(mesh.axis_names)
    other_axes = tuple(n for n in all_axes if n != DATA_AXIS)

    def local(rep, sharded):
        cks = jnp.uint32(0)
        nonfin = jnp.int32(0)
        for x in rep:
            cks = cks + _bit_checksum(x)
            if jnp.issubdtype(x.dtype, jnp.inexact):
                nonfin = nonfin + jnp.sum(
                    ~jnp.isfinite(x), dtype=jnp.int32
                )
        # the cross-replica comparison happens WITHIN each data column
        # (devices sharing the other axes' coordinates hold the same
        # logical block); the WORST column's verdict is then pmax'd
        # across the remaining axes so every device — including the one
        # the fetched scalar comes from — reports fleet-wide detection
        # (out_specs=P() must be true, not asserted). Max, not sum: a
        # fully-desynced replica corrupts every TP column and must count
        # as ONE bad replica, not tensor-size of them (a sum would tell
        # the operator 8 replicas diverged on an 8-way-TP mesh when one
        # did); independent desyncs confined to different columns may
        # under-count, but never read zero.
        gathered = jax.lax.all_gather(cks, DATA_AXIS)
        column = jnp.sum((gathered != gathered[0]).astype(jnp.int32))
        diverged = (
            jax.lax.pmax(column, other_axes) if other_axes else column
        )
        # replica 0's checksum (uniform along data even when a replica
        # diverged), fleet-summed over the other axes — drift evidence
        rep_cks = (
            jax.lax.psum(gathered[0], other_axes)
            if other_axes else gathered[0]
        )
        scks = jnp.uint32(0)
        snf = jnp.int32(0)
        for x in sharded:
            scks = scks + _bit_checksum(x)
            if jnp.issubdtype(x.dtype, jnp.inexact):
                snf = snf + jnp.sum(~jnp.isfinite(x), dtype=jnp.int32)
        # sharded-group sums cover EVERY axis: a ZeRO-1/fsdp shard's NaN
        # must surface no matter which mesh coordinate holds it (a leaf
        # replicated along some axis gets counted once per holding device
        # — over-reporting, never missing)
        scks = jax.lax.psum(scks, all_axes)
        snf = jax.lax.psum(snf, all_axes)
        # replicated-leaf non-finites: the worst device's count (replicas
        # hold copies, so a sum would inflate world-fold; max is uniform
        # and exact on a healthy fleet)
        nonfin = jax.lax.pmax(nonfin, all_axes)
        return {
            "replica_divergence": diverged,
            "replica_checksum": rep_cks,
            "sharded_checksum": scks,
            "state_nonfinite": nonfin + snf,
        }

    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(tuple(rep_specs), tuple(sh_specs)),
        out_specs=P(),
        check_vma=False,
    ))

    def probe(state):
        leaves = jax.tree_util.tree_leaves(_tree(state))
        return fn(
            tuple(leaves[i] for i in rep_idx),
            tuple(leaves[i] for i in sh_idx),
        )

    return probe


def make_reducer(
    reduce: "str | GradReducer",
    mesh: Mesh,
    *,
    bucket_size: int = comm.DEFAULT_BUCKET_ELEMS,
    error_feedback: bool = True,
    seed: int = 0,
) -> GradReducer | None:
    """``make_train_step``'s constructor: a method name (``"none"`` /
    ``"bucketed"`` / ``"quantized"`` / ``"auto"``) or an already-built
    :class:`GradReducer` → the reducer to use, or ``None`` for the implicit
    XLA path (``"none"``, ``"auto"`` off DCN, or a 1-replica mesh)."""
    if isinstance(reduce, GradReducer):
        return reduce
    method = resolve_method(reduce, mesh)
    if method == "none":
        return None
    return GradReducer(
        mesh, method,
        bucket_size=bucket_size, error_feedback=error_feedback, seed=seed,
    )
