"""Pipeline parallelism — GPipe-style microbatch pipelining over the
``pipe`` mesh axis.

No reference counterpart (SURVEY.md §2.12: the reference's only strategy is
DDP, /root/reference/main.py:83); built so the framework scales depth past
one chip. TPU-native design (the "How to Scale Your Model" pipelining
recipe, not a torch-style stage-process scheduler):

- The model's repeated blocks are *stacked*: every param leaf carries a
  leading ``[n_layers, ...]`` dimension, sharded ``P('pipe')`` — stage ``i``
  of the mesh holds layers ``[i·L/S, (i+1)·L/S)`` in its HBM. There is no
  per-stage process or RPC; the whole pipeline is ONE jitted SPMD program.
- Inside :func:`pipeline_apply`, a ``shard_map`` over ``pipe`` runs the
  classic GPipe schedule as a ``lax.scan`` over ``num_micro + n_stages - 1``
  ticks: each tick every stage applies its local layers to the activation it
  holds, then ``lax.ppermute`` shifts activations one hop down the ring
  (stage 0 feeds in the next microbatch, the last stage banks its result).
  The hop is a neighbor exchange on ICI that XLA overlaps with the next
  tick's compute.
- Ramp-up/ramp-down ticks compute on garbage (the pipeline bubble,
  ``(S-1)/(M+S-1)`` of the schedule) — outputs are gated so garbage never
  escapes; choose ``num_micro >= 4·n_stages`` to amortize.
- Everything (``scan``, ``ppermute``, the gating ``where``) is
  differentiable, so ``jax.grad`` of a loss through :func:`pipeline_apply`
  yields the full backward pipeline, with XLA scheduling the reverse-order
  hops.

Two schedules share the forward ring (``pipeline_apply(schedule=...)``):

- ``"gpipe"`` (default, the original): plain reverse-mode through the
  forward scan. XLA's scan-backward saves EVERY tick's stage internals —
  all ``M`` microbatches' block activations are live when the backward
  begins, the classic GPipe memory profile.
- ``"1f1b"``: an explicit one-forward-one-backward backward schedule via
  ``jax.custom_vjp`` (the "Scaling Deep Learning Training with MPMD
  Pipeline Parallelism" recipe, PAPERS.md, expressed SPMD). The forward
  banks ONE tensor per (stage, microbatch) — the stage input, the remat
  floor — instead of the per-tick internals; the backward runs its own
  ``nm + S - 1``-tick scan flowing cotangents UP the ring
  (``ppermute`` with the reversed permutation), recomputing each stage's
  forward tick-by-tick via ``jax.vjp`` exactly when its cotangent
  arrives. Saved-activation memory per stage drops from ``M`` microbatches
  of full block internals (≈ ``(8+2·ffn_mult)·H`` per token,
  ``tpudist.memory``) to ``M`` stage INPUTS (``1·H`` per token) — the
  in-flight-internals profile of 1F1B — at the standard remat price of
  one extra forward inside the backward. The bubble fraction matches
  GPipe's (non-interleaved 1F1B's bubble is GPipe's; the interleave hook
  — splitting each stage's layer slice into virtual stages — is the
  schedule's natural extension and is left explicitly named here). From
  the outside the function is an ordinary differentiable apply:
  ``jax.grad`` composes, and per-block remat inside ``block_fn`` stacks
  as usual.

Composition with the other axes falls out of the mesh: the ``shard_map`` is
manual over ``pipe`` ONLY (``axis_names={'pipe'}``) — every other mesh axis
stays under GSPMD control inside the schedule. The microbatch dim rides its
``data``/``fsdp`` sharding (each stage computes on its data shard), and
stacked block params may additionally carry ``tensor`` shardings on their
trailing dims for Megatron TP-within-stage: GSPMD inserts the per-block
all-reduces from the param shardings exactly as it does for the unrolled
model, while ``ppermute`` hops activations down the ``pipe`` ring. The
``data x pipe x tensor`` composition is certified against the same-function
DP reference in ``__graft_entry__.dryrun_multichip`` and
``tests/test_pipeline.py``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from tpudist.utils import compat
from tpudist.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.mesh import DATA_AXIS, FSDP_AXIS, PIPELINE_AXIS


def stacked_param_specs(stacked_params, *, axis: str = PIPELINE_AXIS):
    """PartitionSpec tree for stacked block params: leading (layer) dim
    sharded over ``pipe``, trailing dims replicated."""
    return jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params
    )


def stacked_param_shardings(stacked_params, mesh: Mesh, *, axis: str = PIPELINE_AXIS):
    """NamedSharding tree placing stacked block params layer-wise over the
    pipeline stages."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        stacked_param_specs(stacked_params, axis=axis),
        is_leaf=lambda s: isinstance(s, P),
    )


def _pipeline_local(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    params_local,
    x_local: jax.Array,
    *,
    axis_name: str,
):
    """Per-stage GPipe schedule — runs inside the pipe-manual ``shard_map``.

    ``params_local``: this stage's layer slice, leaves ``[L/S, ...]``
    (still sharded over auto axes, e.g. ``tensor``, which GSPMD handles).
    ``x_local``: all microbatches, ``[num_micro, micro_batch, ...]``
    (replicated over ``pipe``; ``data``-sharded on the microbatch dim under
    GSPMD). Returns the pipeline output for every microbatch, same shape as
    ``x_local`` (valid on every stage — the last stage's results are
    ``psum``-broadcast over the ``pipe`` axis).
    """
    n = compat.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    nm = x_local.shape[0]
    is_first = stage == 0
    is_last = stage == n - 1
    perm = [(i, i + 1) for i in range(n - 1)]  # one hop down; stage 0 gets zeros
    stage_fn = _stage_fn(block_fn)

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (clamped past the end — garbage ticks
        # are gated below); later stages consume what the ring delivered
        mb = jax.lax.dynamic_index_in_dim(
            x_local, jnp.clip(t, 0, nm - 1), keepdims=False
        )
        inp = jnp.where(is_first, mb, buf)
        y = stage_fn(params_local, inp)
        # the last stage banks microbatch t-(n-1) once it's real
        out_idx = t - (n - 1)
        slot = jnp.clip(out_idx, 0, nm - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(is_last & (out_idx >= 0), y, prev), slot, 0
        )
        buf = jax.lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    # zero carries must match the per-shard compute's varying-manual-axes
    # type or scan rejects the carry signature (same trick as parallel/cp.py):
    # y varies over 'pipe' (axis_index feeds the gating), the zeros don't yet
    buf0 = _pcast_varying(jnp.zeros_like(x_local[0]), axis_name)
    outs0 = _pcast_varying(jnp.zeros_like(x_local), axis_name)
    (_, outs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(nm + n - 1))
    # only the last stage holds real outputs; psum broadcasts them so the
    # loss/head can run stage-replicated (zeros elsewhere contribute nothing)
    return jax.lax.psum(outs, axis_name)


def _pcast_varying(tree, axis_name: str):
    """Promote zero-initialized carries to the varying-manual-axes type on
    jax versions that track it (no-op elsewhere) — scan rejects a carry
    whose type changes between the zeros and the per-shard compute."""
    if hasattr(jax, "typeof") and hasattr(jax.typeof(
        jax.tree_util.tree_leaves(tree)[0]
    ), "vma"):
        return jax.tree_util.tree_map(
            lambda x: jax.lax.pcast(x, (axis_name,), to="varying"), tree
        )
    return tree


def _stage_fn(block_fn):
    """One stage's forward: its local layer slice as a lax.scan."""

    def stage(params_local, h):
        def layer(h, p):
            return block_fn(p, h), None

        h, _ = jax.lax.scan(layer, h, params_local)
        return h

    return stage


def _1f1b_fwd_local(
    block_fn, params_local, x_local, *, axis_name: str
):
    """1F1B forward — the same ring as the GPipe schedule, plus a bank of
    each (stage, microbatch) INPUT: the only residual the explicit
    backward needs (stage internals are recomputed tick-by-tick there).
    Returns ``(outs, banked)``; ``banked`` grows a leading stage dim so
    its out_spec can be ``P(pipe, ...)``."""
    n = compat.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    nm = x_local.shape[0]
    is_first = stage == 0
    is_last = stage == n - 1
    perm = [(i, i + 1) for i in range(n - 1)]
    stage_fn = _stage_fn(block_fn)

    def tick(carry, t):
        buf, outs, banked = carry
        mb = jax.lax.dynamic_index_in_dim(
            x_local, jnp.clip(t, 0, nm - 1), keepdims=False
        )
        inp = jnp.where(is_first, mb, buf)
        # this stage consumes microbatch t - stage this tick; bank its
        # input at that slot (garbage ticks gated — the slot keeps its
        # previous value)
        in_idx = t - stage
        in_valid = (in_idx >= 0) & (in_idx < nm)
        in_slot = jnp.clip(in_idx, 0, nm - 1)
        prev_in = jax.lax.dynamic_index_in_dim(banked, in_slot, keepdims=False)
        banked = jax.lax.dynamic_update_index_in_dim(
            banked, jnp.where(in_valid, inp, prev_in), in_slot, 0
        )
        y = stage_fn(params_local, inp)
        out_idx = t - (n - 1)
        slot = jnp.clip(out_idx, 0, nm - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(is_last & (out_idx >= 0), y, prev), slot, 0
        )
        buf = jax.lax.ppermute(y, axis_name, perm)
        return (buf, outs, banked), None

    buf0 = _pcast_varying(jnp.zeros_like(x_local[0]), axis_name)
    outs0 = _pcast_varying(jnp.zeros_like(x_local), axis_name)
    banked0 = _pcast_varying(jnp.zeros_like(x_local), axis_name)
    (_, outs, banked), _ = jax.lax.scan(
        tick, (buf0, outs0, banked0), jnp.arange(nm + n - 1)
    )
    return jax.lax.psum(outs, axis_name), banked[None]


def _1f1b_bwd_local(
    block_fn, params_local, banked, g, *, axis_name: str
):
    """1F1B backward — cotangents enter at the LAST stage and hop UP the
    ring (the reversed permutation), one microbatch per tick per stage.
    Each tick recomputes the stage's forward from its banked input
    (``jax.vjp``) exactly when the cotangent arrives — the
    one-forward-one-backward interleave, ``nm + S - 1`` ticks total —
    accumulating the stage's param grads; stage 0 banks the input
    cotangents."""
    n = compat.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    banked = banked[0]  # drop the stage dim the fwd out_spec added
    nm = g.shape[0]
    is_first = stage == 0
    is_last = stage == n - 1
    perm_up = [(i + 1, i) for i in range(n - 1)]
    stage_fn = _stage_fn(block_fn)

    def tick(carry, u):
        buf, dparams, dxs = carry
        # the cotangent for microbatch u enters the last stage at tick u
        # and reaches stage s after (n-1-s) hops
        mb = u - (n - 1 - stage)
        valid = (mb >= 0) & (mb < nm)
        slot = jnp.clip(mb, 0, nm - 1)
        g_mb = jax.lax.dynamic_index_in_dim(
            g, jnp.clip(u, 0, nm - 1), keepdims=False
        )
        ct = jnp.where(is_last, g_mb, buf)
        inp = jax.lax.dynamic_index_in_dim(banked, slot, keepdims=False)
        _, f_vjp = jax.vjp(stage_fn, params_local, inp)
        dp, dinp = f_vjp(ct)
        dparams = jax.tree_util.tree_map(
            lambda a, b: a + jnp.where(valid, b, jnp.zeros_like(b)),
            dparams, dp,
        )
        prev = jax.lax.dynamic_index_in_dim(dxs, slot, keepdims=False)
        dxs = jax.lax.dynamic_update_index_in_dim(
            dxs, jnp.where(is_first & valid, dinp, prev), slot, 0
        )
        buf = jax.lax.ppermute(dinp, axis_name, perm_up)
        return (buf, dparams, dxs), None

    buf0 = _pcast_varying(jnp.zeros_like(g[0]), axis_name)
    dparams0 = _pcast_varying(
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), params_local
        ),
        axis_name,
    )
    dxs0 = _pcast_varying(jnp.zeros_like(banked), axis_name)
    (_, dparams, dxs), _ = jax.lax.scan(
        tick, (buf0, dparams0, dxs0), jnp.arange(nm + n - 1)
    )
    # only stage 0 banked real input cotangents; psum broadcasts them so
    # dx comes back stage-replicated (zeros elsewhere contribute nothing)
    return dparams, jax.lax.psum(dxs, axis_name)


def _apply_1f1b(block_fn, stacked_params, xm, mesh, *, axis: str):
    """The custom_vjp wrapper pairing the two local schedules. Looks like
    an ordinary differentiable ``(params, x) -> out`` from the outside."""
    p_specs = stacked_param_specs(stacked_params, axis=axis)
    x_spec = P(*([None] * xm.ndim))
    banked_spec = P(axis, *([None] * xm.ndim))
    fwd_sm = shard_map(
        functools.partial(_1f1b_fwd_local, block_fn, axis_name=axis),
        mesh=mesh,
        in_specs=(p_specs, x_spec),
        out_specs=(x_spec, banked_spec),
        axis_names={axis},
    )
    bwd_sm = shard_map(
        functools.partial(_1f1b_bwd_local, block_fn, axis_name=axis),
        mesh=mesh,
        in_specs=(p_specs, banked_spec, x_spec),
        out_specs=(p_specs, x_spec),
        axis_names={axis},
    )

    @jax.custom_vjp
    def run(params, x):
        out, _ = fwd_sm(params, x)
        return out

    def run_fwd(params, x):
        out, banked = fwd_sm(params, x)
        return out, (params, banked)

    def run_bwd(res, ct):
        params, banked = res
        return bwd_sm(params, banked, ct)

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, xm)


SCHEDULES = ("gpipe", "1f1b")


def pipeline_apply(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    num_micro: int,
    axis: str = PIPELINE_AXIS,
    batch_axes=(DATA_AXIS, FSDP_AXIS),
    schedule: str = "gpipe",
):
    """Run ``x`` through the stacked blocks with GPipe pipelining.

    ``block_fn(layer_params, h) -> h`` applies ONE block (same input/output
    shape — residual blocks). ``stacked_params``: leaves ``[n_layers, ...]``;
    ``n_layers`` must divide by the mesh's ``pipe`` size. ``x``:
    ``[batch, ...]`` with ``batch`` divisible by ``num_micro`` (and the
    microbatch by the ``data`` sharding).

    The ``shard_map`` is manual over ``pipe`` only: the batch keeps its
    ``data`` sharding and the params their ``tensor`` sharding under GSPMD
    inside the schedule, so DP and Megatron-TP compose with the pipeline
    without hand-written collectives. ``batch_axes`` names the mesh axes
    the microbatch dim is constrained to (the ``with_sharding_constraint``
    below) — override it for a custom batch layout.

    ``schedule``: ``"gpipe"`` (default — reverse-mode through the forward
    scan, all ``num_micro`` microbatches' stage internals saved) or
    ``"1f1b"`` (explicit one-forward-one-backward backward ring via
    custom_vjp: forward banks only each stage's microbatch INPUTS,
    backward recomputes stage internals tick-by-tick — the module
    docstring carries the memory math). Both compute the identical
    function and gradients (an execution schedule, not a numerical
    change; ``tests/test_pipeline.py`` pins fwd+grad agreement).
    """
    if schedule not in SCHEDULES:
        raise ValueError(
            f"schedule must be one of {SCHEDULES}, got {schedule!r}"
        )
    n_stages = mesh.shape[axis]
    layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if layers % n_stages:
        raise ValueError(f"{layers} layers not divisible by {n_stages} stages")
    b = x.shape[0]
    if b % num_micro:
        raise ValueError(f"batch {b} not divisible by num_micro {num_micro}")
    xm = x.reshape(num_micro, b // num_micro, *x.shape[1:])
    # pin the microbatch dim's data sharding (GSPMD would usually propagate
    # it from the embedding output, but the constraint makes the layout
    # deterministic: microbatch rows stay on the device that computes them)
    xm = jax.lax.with_sharding_constraint(
        xm, NamedSharding(mesh, P(None, batch_axes, *([None] * (x.ndim - 1))))
    )

    if schedule == "1f1b":
        out = _apply_1f1b(block_fn, stacked_params, xm, mesh, axis=axis)
        return out.reshape(b, *out.shape[2:])

    x_spec = P(*([None] * (x.ndim + 1)))
    fn = shard_map(
        functools.partial(_pipeline_local, block_fn, axis_name=axis),
        mesh=mesh,
        in_specs=(stacked_param_specs(stacked_params, axis=axis), x_spec),
        out_specs=x_spec,
        axis_names={axis},
    )
    out = fn(stacked_params, xm)
    return out.reshape(b, *out.shape[2:])
