"""Pipeline parallelism — GPipe-style microbatch pipelining over the
``pipe`` mesh axis.

No reference counterpart (SURVEY.md §2.12: the reference's only strategy is
DDP, /root/reference/main.py:83); built so the framework scales depth past
one chip. TPU-native design (the "How to Scale Your Model" pipelining
recipe, not a torch-style stage-process scheduler):

- The model's repeated blocks are *stacked*: every param leaf carries a
  leading ``[n_layers, ...]`` dimension, sharded ``P('pipe')`` — stage ``i``
  of the mesh holds layers ``[i·L/S, (i+1)·L/S)`` in its HBM. There is no
  per-stage process or RPC; the whole pipeline is ONE jitted SPMD program.
- Inside :func:`pipeline_apply`, a ``shard_map`` over ``pipe`` runs the
  classic GPipe schedule as a ``lax.scan`` over ``num_micro + n_stages - 1``
  ticks: each tick every stage applies its local layers to the activation it
  holds, then ``lax.ppermute`` shifts activations one hop down the ring
  (stage 0 feeds in the next microbatch, the last stage banks its result).
  The hop is a neighbor exchange on ICI that XLA overlaps with the next
  tick's compute.
- Ramp-up/ramp-down ticks compute on garbage (the pipeline bubble,
  ``(S-1)/(M+S-1)`` of the schedule) — outputs are gated so garbage never
  escapes; choose ``num_micro >= 4·n_stages`` to amortize.
- Everything (``scan``, ``ppermute``, the gating ``where``) is
  differentiable, so ``jax.grad`` of a loss through :func:`pipeline_apply`
  yields the full backward pipeline, with XLA scheduling the reverse-order
  hops.

Composition with the other axes falls out of the mesh: the ``shard_map`` is
manual over ``pipe`` ONLY (``axis_names={'pipe'}``) — every other mesh axis
stays under GSPMD control inside the schedule. The microbatch dim rides its
``data``/``fsdp`` sharding (each stage computes on its data shard), and
stacked block params may additionally carry ``tensor`` shardings on their
trailing dims for Megatron TP-within-stage: GSPMD inserts the per-block
all-reduces from the param shardings exactly as it does for the unrolled
model, while ``ppermute`` hops activations down the ``pipe`` ring. The
``data x pipe x tensor`` composition is certified against the same-function
DP reference in ``__graft_entry__.dryrun_multichip`` and
``tests/test_pipeline.py``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from tpudist.utils import compat
from tpudist.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.mesh import DATA_AXIS, FSDP_AXIS, PIPELINE_AXIS


def stacked_param_specs(stacked_params, *, axis: str = PIPELINE_AXIS):
    """PartitionSpec tree for stacked block params: leading (layer) dim
    sharded over ``pipe``, trailing dims replicated."""
    return jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params
    )


def stacked_param_shardings(stacked_params, mesh: Mesh, *, axis: str = PIPELINE_AXIS):
    """NamedSharding tree placing stacked block params layer-wise over the
    pipeline stages."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        stacked_param_specs(stacked_params, axis=axis),
        is_leaf=lambda s: isinstance(s, P),
    )


def _pipeline_local(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    params_local,
    x_local: jax.Array,
    *,
    axis_name: str,
):
    """Per-stage GPipe schedule — runs inside the pipe-manual ``shard_map``.

    ``params_local``: this stage's layer slice, leaves ``[L/S, ...]``
    (still sharded over auto axes, e.g. ``tensor``, which GSPMD handles).
    ``x_local``: all microbatches, ``[num_micro, micro_batch, ...]``
    (replicated over ``pipe``; ``data``-sharded on the microbatch dim under
    GSPMD). Returns the pipeline output for every microbatch, same shape as
    ``x_local`` (valid on every stage — the last stage's results are
    ``psum``-broadcast over the ``pipe`` axis).
    """
    n = compat.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    nm = x_local.shape[0]
    is_first = stage == 0
    is_last = stage == n - 1
    perm = [(i, i + 1) for i in range(n - 1)]  # one hop down; stage 0 gets zeros

    def stage_fn(h):
        def layer(h, p):
            return block_fn(p, h), None

        h, _ = jax.lax.scan(layer, h, params_local)
        return h

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (clamped past the end — garbage ticks
        # are gated below); later stages consume what the ring delivered
        mb = jax.lax.dynamic_index_in_dim(
            x_local, jnp.clip(t, 0, nm - 1), keepdims=False
        )
        inp = jnp.where(is_first, mb, buf)
        y = stage_fn(inp)
        # the last stage banks microbatch t-(n-1) once it's real
        out_idx = t - (n - 1)
        slot = jnp.clip(out_idx, 0, nm - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(is_last & (out_idx >= 0), y, prev), slot, 0
        )
        buf = jax.lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    buf0 = jnp.zeros_like(x_local[0])
    outs0 = jnp.zeros_like(x_local)
    # zero carries must match the per-shard compute's varying-manual-axes
    # type or scan rejects the carry signature (same trick as parallel/cp.py):
    # y varies over 'pipe' (axis_index feeds the gating), the zeros don't yet
    if hasattr(jax, "typeof") and hasattr(jax.typeof(x_local), "vma"):
        buf0, outs0 = (
            jax.lax.pcast(x, (axis_name,), to="varying") for x in (buf0, outs0)
        )
    (_, outs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(nm + n - 1))
    # only the last stage holds real outputs; psum broadcasts them so the
    # loss/head can run stage-replicated (zeros elsewhere contribute nothing)
    return jax.lax.psum(outs, axis_name)


def pipeline_apply(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    num_micro: int,
    axis: str = PIPELINE_AXIS,
    batch_axes=(DATA_AXIS, FSDP_AXIS),
):
    """Run ``x`` through the stacked blocks with GPipe pipelining.

    ``block_fn(layer_params, h) -> h`` applies ONE block (same input/output
    shape — residual blocks). ``stacked_params``: leaves ``[n_layers, ...]``;
    ``n_layers`` must divide by the mesh's ``pipe`` size. ``x``:
    ``[batch, ...]`` with ``batch`` divisible by ``num_micro`` (and the
    microbatch by the ``data`` sharding).

    The ``shard_map`` is manual over ``pipe`` only: the batch keeps its
    ``data`` sharding and the params their ``tensor`` sharding under GSPMD
    inside the schedule, so DP and Megatron-TP compose with the pipeline
    without hand-written collectives. ``batch_axes`` names the mesh axes
    the microbatch dim is constrained to (the ``with_sharding_constraint``
    below) — override it for a custom batch layout.
    """
    n_stages = mesh.shape[axis]
    layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if layers % n_stages:
        raise ValueError(f"{layers} layers not divisible by {n_stages} stages")
    b = x.shape[0]
    if b % num_micro:
        raise ValueError(f"batch {b} not divisible by num_micro {num_micro}")
    xm = x.reshape(num_micro, b // num_micro, *x.shape[1:])
    # pin the microbatch dim's data sharding (GSPMD would usually propagate
    # it from the embedding output, but the constraint makes the layout
    # deterministic: microbatch rows stay on the device that computes them)
    xm = jax.lax.with_sharding_constraint(
        xm, NamedSharding(mesh, P(None, batch_axes, *([None] * (x.ndim - 1))))
    )

    x_spec = P(*([None] * (x.ndim + 1)))
    fn = shard_map(
        functools.partial(_pipeline_local, block_fn, axis_name=axis),
        mesh=mesh,
        in_specs=(stacked_param_specs(stacked_params, axis=axis), x_spec),
        out_specs=x_spec,
        axis_names={axis},
    )
    out = fn(stacked_params, xm)
    return out.reshape(b, *out.shape[2:])
