"""Context (sequence) parallelism: ring attention and Ulysses all-to-all.

No reference counterpart (SURVEY.md §5 "long-context: ABSENT" — the
reference's workload is a CNN); built because long-sequence scaling is a
first-class capability of this framework. Two schemes over the ``seq`` mesh
axis, both SPMD via ``shard_map``:

- **Ring attention** (:func:`ring_attention`): Q stays put, K/V chunks rotate
  around the ``seq`` ring with ``lax.ppermute`` (ICI neighbor exchange) while
  each step's partial attention is merged with the online-softmax rescale —
  the S×S score matrix never exists and peak memory is
  O(S_local × S_local) per device. The per-hop transfer overlaps with the
  current chunk's compute under XLA's async collectives.
- **Ulysses** (:func:`ulysses_attention`): ``lax.all_to_all`` re-shards
  [seq-sharded, all heads] → [all seq, head-sharded], runs plain (flash)
  attention per head group over the full sequence, and re-shards back.
  Cheaper collectives for moderate S; requires num_heads % seq_axis == 0.

Both are differentiable (``ppermute``/``all_to_all`` have transpose rules),
so they drop into the compiled train step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from tpudist.utils import compat
from tpudist.utils.compat import shard_map

from tpudist.mesh import DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS

NEG_INF = float(np.finfo(np.float32).min)


def _chunk_scores(q, k, *, sm_scale, causal, q_off, k_off):
    """Masked f32 attention scores of a local Q chunk vs one K chunk.

    q: [B, Sq, H, D], k: [B, Sk, H, D] → [B, H, Sq, Sk]; ``q_off``/``k_off``
    are the chunks' global sequence offsets (traced values are fine — the
    mask is data-dependent on positions, not shapes).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    return s


def _online_merge(m, l, acc, s, v):
    """Fold one chunk's scores+values into the online-softmax state.

    m,l: [B,H,Sq,1] f32; acc: [B,Sq,H,D] f32; s: [B,H,Sq,Sk]; v: [B,Sk,H,D].
    Safe when a chunk is fully masked (m stays NEG_INF, contribution 0).
    """
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # avoid NEG_INF - NEG_INF = nan: fully-masked rows get exp(·)=0 via s=NEG_INF
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    alpha = jnp.exp(jnp.where(m <= NEG_INF, NEG_INF, m - m_safe))  # [B,H,Sq,1]
    p = jnp.exp(s - m_safe)                                        # [B,H,Sq,Sk]
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    alpha_q = alpha.squeeze(-1).transpose(0, 2, 1)[..., None]      # [B,Sq,H,1]
    return m_new, l_new, acc * alpha_q + pv


def ring_attention_local(
    q, k, v, *, axis_name: str = SEQUENCE_AXIS, causal: bool = False
):
    """Per-shard ring attention body — call inside ``shard_map``.

    q, k, v: this device's sequence chunk, [B, S_local, H, D]. The K/V pair
    makes ``axis_size`` hops around the ring; hop ``t`` processes the chunk
    originally owned by device ``(idx - t) mod n``.
    """
    n = compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    sm_scale = 1.0 / float(np.sqrt(d))
    q_off = idx * s_local

    perm = [(j, (j + 1) % n) for j in range(n)]

    def hop(carry, t):
        k_cur, v_cur, m, l, acc = carry
        src = (idx - t) % n
        s = _chunk_scores(
            q, k_cur, sm_scale=sm_scale, causal=causal,
            q_off=q_off, k_off=src * s_local,
        )
        m, l, acc = _online_merge(m, l, acc, s, v_cur)
        # rotate AFTER compute; skip the final (wasted) hop via cond-free
        # trick: permuting on the last step is harmless and keeps the scan
        # body uniform — XLA overlaps it with the merge.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    # the zero-init carries must carry the same varying-manual-axes type as
    # the per-shard compute results, or scan rejects the carry signature
    # (old jax has no vma-typed avals, and no check to satisfy)
    vma = (
        tuple(getattr(jax.typeof(q), "vma", ()))
        if hasattr(jax, "typeof") else ()
    )
    if vma:
        m0, l0, acc0 = (jax.lax.pcast(x, vma, to="varying") for x in (m0, l0, acc0))
    (k, v, m, l, acc), _ = jax.lax.scan(
        hop, (k, v, m0, l0, acc0), jnp.arange(n)
    )
    l_q = l.squeeze(-1).transpose(0, 2, 1)[..., None]  # [B,Sq,H,1]
    out = acc / jnp.where(l_q == 0.0, 1.0, l_q)
    return out.astype(q.dtype)


def ring_attention(
    q, k, v, mesh: Mesh, *, causal: bool = False,
    batch_axes=(DATA_AXIS, FSDP_AXIS), seq_axis: str = SEQUENCE_AXIS,
):
    """Ring attention on global [B, S, H, D] arrays: batch over ``data``,
    sequence over ``seq``."""
    spec = P(batch_axes, seq_axis, None, None)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)


def ulysses_attention_local(
    q, k, v, *, axis_name: str = SEQUENCE_AXIS, causal: bool = False,
    attn_fn=None,
):
    """Per-shard Ulysses body — call inside ``shard_map``.

    Input [B, S/n, H, D] (sequence-sharded) → all_to_all →
    [B, S, H/n, D] (head-sharded) → full-sequence attention on the local
    head group → all_to_all back. ``attn_fn(q, k, v, causal=...)`` defaults
    to the XLA-oracle attention; pass the flash kernel for long S.
    """
    n = compat.axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(f"num_heads {q.shape[2]} not divisible by seq axis {n}")
    if attn_fn is None:
        from tpudist.ops.attention import dot_product_attention
        attn_fn = dot_product_attention

    def to_heads(x):  # [B, S/n, H, D] → [B, S, H/n, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def to_seq(x):    # [B, S, H/n, D] → [B, S/n, H, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    out = attn_fn(to_heads(q), to_heads(k), to_heads(v), causal=causal)
    return to_seq(out)


def ulysses_attention(
    q, k, v, mesh: Mesh, *, causal: bool = False,
    batch_axes=(DATA_AXIS, FSDP_AXIS), seq_axis: str = SEQUENCE_AXIS,
    attn_fn=None,
):
    """Ulysses (all-to-all) sequence-parallel attention on global
    [B, S, H, D] arrays."""
    spec = P(batch_axes, seq_axis, None, None)
    fn = shard_map(
        functools.partial(
            ulysses_attention_local, axis_name=seq_axis, causal=causal,
            attn_fn=attn_fn,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # pallas_call (the flash attn_fn) can't declare varying-manual-axes
        # on its out_shape; keep the vma safety net for the default path
        check_vma=(attn_fn is None),
    )
    return fn(q, k, v)
