"""Parallelism strategies.

The reference's only strategy is data parallelism (DDP, SURVEY.md §2.12) —
expressed here as shardings over the named mesh (tpudist.mesh +
tpudist.train). This package holds the strategy-level helpers: DP sharding
rules and grad accumulation; the mesh's extra named axes (fsdp/tensor/seq/
expert) keep the door open for further strategies beyond parity.
"""

from tpudist.parallel.dp import dp_shardings
from tpudist.parallel.ep import MoEMlp, expert_capacity, top_k_dispatch
from tpudist.parallel.fsdp import fsdp_shardings, shard_state
from tpudist.parallel.pp import pipeline_apply, stacked_param_shardings

__all__ = [
    "dp_shardings", "fsdp_shardings", "shard_state",
    "pipeline_apply", "stacked_param_shardings",
    "MoEMlp", "expert_capacity", "top_k_dispatch",
]
