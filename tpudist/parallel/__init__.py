"""Parallelism strategies.

The reference's only strategy is data parallelism (DDP, SURVEY.md §2.12).
Default DP needs no module: its shardings ARE the framework defaults —
params replicated (``tpudist.mesh.replicated_sharding``), batch split over
the ``data`` axis (``tpudist.mesh.batch_sharding``), consumed directly by
``make_train_step`` — the gradient all-reduce is implicit in ``jax.grad``
of a global-batch mean under GSPMD. ``dp`` holds the EXPLICIT reduction
path for DCN-bound meshes (bucketed / int8-quantized gradient all-reduce,
``make_train_step(reduce=...)``); the rest of the package is the
strategies BEYOND parity (tp/pp/cp/ep/fsdp) over the mesh's extra axes.
"""

from tpudist.parallel.dp import GradReducer, make_reducer, resolve_method
from tpudist.parallel.ep import MoEMlp, expert_capacity, top_k_dispatch
from tpudist.parallel.fsdp import fsdp_shardings, shard_state
from tpudist.parallel.plan import ParallelPlan, spec_is_sharded
from tpudist.parallel.pp import pipeline_apply, stacked_param_shardings

__all__ = [
    "GradReducer", "make_reducer", "resolve_method",
    "fsdp_shardings", "shard_state",
    "ParallelPlan", "spec_is_sharded",
    "pipeline_apply", "stacked_param_shardings",
    "MoEMlp", "expert_capacity", "top_k_dispatch",
]
