"""Parallelism strategies.

The reference's only strategy is data parallelism (DDP, SURVEY.md §2.12).
DP has no module here because its shardings ARE the framework defaults:
params replicated (``tpudist.mesh.replicated_sharding``), batch split over
the ``data`` axis (``tpudist.mesh.batch_sharding``), consumed directly by
``make_train_step`` — the gradient all-reduce is implicit in ``jax.grad``
of a global-batch mean under GSPMD. This package holds the strategies
BEYOND parity (tp/pp/cp/ep/fsdp) over the mesh's extra named axes.
"""

from tpudist.parallel.ep import MoEMlp, expert_capacity, top_k_dispatch
from tpudist.parallel.fsdp import fsdp_shardings, shard_state
from tpudist.parallel.pp import pipeline_apply, stacked_param_shardings

__all__ = [
    "fsdp_shardings", "shard_state",
    "pipeline_apply", "stacked_param_shardings",
    "MoEMlp", "expert_capacity", "top_k_dispatch",
]
