"""Multi-host bring-up and host-side collectives.

TPU-native replacement for the reference's L6 layer
(``dist.init_process_group(backend='nccl', init_method='env://')`` +
``torch.cuda.set_device``, /root/reference/main.py:34-37) and for the
out-of-graph ``reduce_loss`` helper (/root/reference/main.py:16-20).

The ``env://`` contract is preserved: the same environment variables the
reference's launcher sets (``MASTER_ADDR``, ``MASTER_PORT``, ``RANK``,
``WORLD_SIZE``) drive :func:`jax.distributed.initialize`, so the README's
multi-node launch recipes (/root/reference/README.md:17-35) translate 1:1 —
one tpudist process per TPU host instead of one per GPU.
"""

from __future__ import annotations

import dataclasses
import logging
import os

import jax
import numpy as np

logger = logging.getLogger(__name__)

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistributedContext:
    """World description after bring-up.

    The reference's ``global_rank``/``world_size`` (/root/reference/main.py:36-37)
    count *GPU processes*; on TPU one process drives several chips, so both
    views are exposed:

    - ``process_index``/``process_count``: host-level (launcher) ranks.
    - ``global_rank``/``world_size``: replica-level — ``world_size`` is the
      total device count (the data-parallel degree, matching the reference's
      meaning of "number of workers"), ``global_rank`` is the first replica id
      owned by this process. Rank-0 logging guards (`main.py:107,113`) map to
      ``is_chief``.
    """

    process_index: int
    process_count: int
    global_rank: int
    world_size: int
    local_device_count: int
    coordinator: str | None

    @property
    def is_chief(self) -> bool:
        return self.process_index == 0


def init_from_env(*, allow_single_process: bool = True) -> DistributedContext:
    """Form the world from the ``env://`` contract.

    Reads ``MASTER_ADDR``/``MASTER_PORT`` (coordinator), ``RANK`` (process
    rank) and ``WORLD_SIZE`` (process count) — the exact variables
    ``torch.distributed.launch`` exports for the reference
    (/root/reference/README.md:28, SURVEY.md §2.2/§2.3). With
    ``WORLD_SIZE`` ≤ 1 or absent, runs single-process (all local devices).
    """
    global _initialized
    # opt-in persistent XLA compile cache: first compile of the train step is
    # tens of seconds on TPU; restarts (and checkpoint resumes) skip it.
    # JAX's own knobs win if the user already configured them.
    # (only the dir is set — thresholds like min-compile-time stay whatever
    # the user configured via JAX's own env vars)
    cache_dir = os.environ.get("TPUDIST_COMPILE_CACHE")
    if cache_dir and not jax.config.jax_compilation_cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)

    nproc = int(os.environ.get("WORLD_SIZE", "1"))
    rank = int(os.environ.get("RANK", "0"))
    if nproc > 1:
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = os.environ.get("MASTER_PORT", "29500")
        coordinator = f"{addr}:{port}"
        if not _initialized:
            # Rank 0 hosts the coordination service — the TCPStore analogue
            # (SURVEY.md §2.3): all processes rendezvous here, then XLA forms
            # the global device topology.
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=nproc,
                process_id=rank,
            )
            _initialized = True
    else:
        coordinator = None
        if not allow_single_process:
            raise RuntimeError("WORLD_SIZE>1 required")

    local = jax.local_device_count()
    ctx = DistributedContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        global_rank=jax.process_index() * local,
        world_size=jax.device_count(),
        local_device_count=local,
        coordinator=coordinator,
    )
    logger.info("tpudist world: %s", ctx)
    return ctx


def reduce_loss(value, ctx: DistributedContext | None = None) -> float:
    """Global mean of a per-process scalar — the reference's ``reduce_loss``
    (/root/reference/main.py:16-20: ``dist.reduce(dst=0)`` then ÷ world_size).

    Under pjit the in-graph loss is *already* the global-batch mean, so the
    common caller passes it straight through; this host-level path exists for
    out-of-graph scalars (e.g. per-host timing) and for parity with the
    reference's post-step reduce. Unlike the reference (whose non-dst ranks
    hold garbage after ``dist.reduce``), every process gets the mean.
    """
    value = float(np.asarray(value))
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray(value, np.float32))
    return float(np.mean(gathered))


def verify_replicas(tree, *, atol: float = 0.0) -> None:
    """Assert every process holds identical values for ``tree`` — the
    TPU-native version of DDP's wrap-time parameter-consistency check
    (/root/reference/main.py:83 verifies ranks agree before training).

    Cheap: one float64 checksum per process is allgathered, not the params.
    Raises ``RuntimeError`` naming the divergent processes on mismatch.
    """
    if jax.process_count() == 1:
        return

    import jax.numpy as jnp

    # one jitted tree-sum (not a dispatch per leaf); works on sharded global
    # arrays — the reduction is compiled as a single program
    @jax.jit
    def _tree_checksum(t):
        leaves = [
            jnp.sum(jnp.asarray(x, jnp.float32))
            for x in jax.tree_util.tree_leaves(t)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
        ]
        return jnp.sum(jnp.stack(leaves)) if leaves else jnp.zeros(())

    checksum = float(_tree_checksum(tree))
    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(np.asarray(checksum, np.float64))
    ).reshape(-1)
    bad = [i for i, v in enumerate(gathered) if abs(v - gathered[0]) > atol]
    if bad:
        raise RuntimeError(
            f"replica init-sync check failed: processes {bad} diverge from "
            f"process 0 (checksums {gathered.tolist()}); all processes must "
            "build the initial state from the same seed"
        )


def barrier(name: str = "barrier") -> None:
    """Cross-process barrier (used e.g. by the rank-0 dataset-download guard,
    fixing the reference's download race noted in SURVEY.md §5)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
