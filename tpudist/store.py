"""Host-side rendezvous store — the c10d TCPStore equivalent.

The reference's ``init_method='env://'`` (/root/reference/main.py:34) works
by rank 0 hosting a C++ TCP key-value store at ``MASTER_ADDR:MASTER_PORT``
where all ranks meet (SURVEY.md §2.3). jax.distributed brings up the
*device* world; this store (C++ core: tpudist/csrc/tcpstore.cpp) provides
the host-side coordination that must work before/without JAX — launcher
bring-up checks, the rank-0 dataset-download guard (§5 race fix), and
generic cross-process barriers.

Falls back to a pure-Python in-process store when the native library cannot
be built (single-process runs never need the TCP path).
"""

from __future__ import annotations

import os
from typing import Optional

from tpudist import csrc

# must match kMaxValue in tpudist/csrc/tcpstore.cpp
MAX_VALUE_BYTES = 1 << 20


class TCPStore:
    """Key-value store client; rank 0 (``is_server=True``) also hosts it.

    >>> store = TCPStore("127.0.0.1", 29501, world_size=2, rank=0)   # server
    >>> store.set("k", b"v"); store.get("k")                          # b'v'
    >>> store.add("counter", 1)                                       # 1
    >>> store.barrier("epoch0")          # blocks until all ranks arrive
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        world_size: int = 1,
        rank: int = 0,
        is_server: Optional[bool] = None,
        timeout_ms: int = 60_000,
    ):
        lib = csrc.lib()
        if lib is None:
            raise RuntimeError(
                "native TCP store unavailable (no C++ toolchain); "
                "single-process runs can use tpudist.distributed.barrier"
            )
        self._lib = lib
        self.world_size = world_size
        self.rank = rank
        self.timeout_ms = timeout_ms
        self._server = None
        self._barrier_uses: dict[str, int] = {}
        if is_server is None:
            is_server = rank == 0
        if is_server:
            self._server = lib.tpd_store_server_create(port)
            if not self._server:
                raise OSError(f"cannot bind TCP store on port {port}")
            port = lib.tpd_store_server_port(self._server)
        self.port = port
        self._client = lib.tpd_client_create(
            host.encode(), port, timeout_ms
        )
        if not self._client:
            if self._server:
                lib.tpd_store_server_destroy(self._server)
                self._server = None
            raise ConnectionError(f"cannot reach TCP store at {host}:{port}")

    # -- core ops ---------------------------------------------------------
    def set(self, key: str, value: bytes | str) -> None:
        if isinstance(value, str):
            value = value.encode()
        if len(value) > MAX_VALUE_BYTES:
            # the server rejects oversized values by dropping the connection
            # (protocol-violation defense); refuse client-side instead
            raise ValueError(
                f"store value for {key!r} is {len(value)} bytes; "
                f"max is {MAX_VALUE_BYTES}"
            )
        rc = self._lib.tpd_client_set(self._client, key.encode(), value, len(value))
        if rc != 0:
            raise ConnectionError(f"store set({key!r}) failed")

    def get(self, key: str, wait: bool = True,
            timeout_ms: Optional[int] = None) -> bytes | None:
        """Value for ``key``; blocks until it is set when ``wait`` (None on
        timeout / missing key when not waiting)."""
        import ctypes

        wait_ms = (timeout_ms if timeout_ms is not None else self.timeout_ms) if wait else 0
        buf = ctypes.create_string_buffer(MAX_VALUE_BYTES)
        n = self._lib.tpd_client_get(
            self._client, key.encode(), buf, len(buf), wait_ms
        )
        if n == -1:
            return None
        if n < 0:
            raise ConnectionError(f"store get({key!r}) failed ({n})")
        return buf.raw[:n]

    def add(self, key: str, delta: int = 1) -> int:
        """Atomic fetch-add on an integer key; returns the new value."""
        n = self._lib.tpd_client_add(self._client, key.encode(), delta)
        if n == -(2**63):
            raise ConnectionError(f"store add({key!r}) failed")
        return n

    # -- derived ops ------------------------------------------------------
    def barrier(self, name: str = "default",
                timeout_ms: Optional[int] = None) -> None:
        """Block until all ``world_size`` ranks reach this barrier.

        Reusable: each use of a name is generation-scoped client-side, so
        ``barrier('epoch')`` in a loop re-synchronizes every iteration (all
        ranks must call the same barrier sequence, as with any barrier).
        """
        if self.world_size <= 1:
            return
        gen = self._barrier_uses.get(name, 0)
        self._barrier_uses[name] = gen + 1
        key = f"__barrier__/{name}/{gen}"
        arrived = self.add(f"{key}/count", 1)
        if arrived == self.world_size:
            self.set(f"{key}/done", b"1")
        if self.get(f"{key}/done", timeout_ms=timeout_ms) is None:
            raise TimeoutError(
                f"barrier {name!r} (use #{gen}): {arrived}/{self.world_size} "
                f"ranks arrived before timeout"
            )

    def broadcast(self, key: str, value: bytes | None = None) -> bytes:
        """Rank with ``value`` publishes it; everyone returns it."""
        if value is not None:
            self.set(key, value)
            return value
        out = self.get(key)
        if out is None:
            raise TimeoutError(f"broadcast key {key!r} never arrived")
        return out

    def close(self) -> None:
        if getattr(self, "_client", None):
            self._lib.tpd_client_destroy(self._client)
            self._client = None
        if getattr(self, "_server", None):
            self._lib.tpd_store_server_destroy(self._server)
            self._server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def from_env(**kw) -> TCPStore:
    """Build the store from the launcher's env:// contract — the same
    variables the reference's launcher exports (SURVEY.md §2.2)."""
    return TCPStore(
        os.environ.get("MASTER_ADDR", "127.0.0.1"),
        int(os.environ.get("MASTER_PORT", "29500")) + 1,  # +1: JAX coordinator owns the base port
        world_size=int(os.environ.get("WORLD_SIZE", "1")),
        rank=int(os.environ.get("RANK", "0")),
        **kw,
    )
