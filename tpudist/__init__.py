"""tpudist — a TPU-native distributed training framework.

Brand-new JAX/XLA implementation of the capability surface of the reference
PyTorch DDP example (Echozqn/PyTorch-Distributed-Training, see SURVEY.md):

- launcher-driven ``env://`` multi-host bring-up
  (reference: ``torch.distributed.launch`` + ``dist.init_process_group``,
  /root/reference/main.py:34, README.md:12-35)
- deterministic per-rank data sharding
  (reference: ``DistributedSampler``, /root/reference/main.py:53,93)
- data-parallel training with gradient all-reduce and cross-replica
  batch-norm statistics (reference: DDP + SyncBatchNorm,
  /root/reference/main.py:82-83,103)
- per-step throughput/loss TSV logging
  (reference: /root/reference/main.py:65-67,107-117)
- windowed profiler tracing (reference: torch.profiler,
  /root/reference/main.py:70-78,115)

The design is TPU-first rather than a port: the reference's per-op NCCL
machinery (bucketed async all-reduce, SyncBN all-gathers, pinned-memory
staging) collapses into ONE pjit-compiled SPMD step over a named device
mesh, with XLA inserting and overlapping the ICI/DCN collectives.
"""

from tpudist.mesh import MeshConfig, create_mesh, batch_sharding, replicated_sharding
from tpudist.distributed import DistributedContext, init_from_env, reduce_loss
from tpudist.data.sampler import DistributedSampler
from tpudist.store import TCPStore
from tpudist.amp import Policy, policy_for, skip_nonfinite
from tpudist.optim import fused_adamw, make_optimizer, run_schedule, warmup_cosine
from tpudist.telemetry import TelemetryConfig
from tpudist.resilience import Preempted

__version__ = "0.1.0"

__all__ = [
    "MeshConfig",
    "create_mesh",
    "batch_sharding",
    "replicated_sharding",
    "DistributedContext",
    "init_from_env",
    "reduce_loss",
    "DistributedSampler",
    "TCPStore",
    "Policy",
    "policy_for",
    "skip_nonfinite",
    "fused_adamw",
    "make_optimizer",
    "run_schedule",
    "warmup_cosine",
    "TelemetryConfig",
    "Preempted",
    "__version__",
]
