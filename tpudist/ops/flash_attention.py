"""Flash attention as a Pallas TPU kernel.

No reference counterpart (the reference's workload is a CNN, SURVEY.md §5
"long-context: ABSENT") — this is the hot op for the transformer legs of the
BASELINE ladder (ViT, GPT-2) and the building block the ring-attention
context-parallel path reuses blockwise.

Design (FlashAttention-2 style, TPU-first):

- grid ``(batch, heads, q_blocks, k_blocks)`` with the K dimension innermost,
  so the f32 VMEM scratch accumulators (running max ``m``, normalizer ``l``,
  output ``acc``) persist across the K sweep of one Q block;
- per tile: one MXU matmul ``q·kᵀ`` (f32 accumulation), online-softmax
  rescale on the VPU, one MXU matmul ``p·v`` into the accumulator — the
  S×S score matrix never exists in HBM;
- causal masking is two-level: whole K blocks strictly above the diagonal are
  predicated off with ``pl.when`` (no MXU work issued), the diagonal block is
  masked elementwise with ``broadcasted_iota``; ``kv_len`` masks right-padded
  keys the same two-level way (ragged caller shapes are padded to the
  128-tile multiple by the wrapper);
- two backward paths, both O(S·block) memory, recomputing p from the saved
  log-sum-exp: the default blockwise ``lax.scan`` in plain JAX (XLA fuses it
  well — fastest at d=64/moderate S on v5e), and opt-in Pallas FA-2 dq/dkv
  kernels (``pallas_bwd=True``) for very long sequences.

Numerics: scores/softmax in float32 regardless of input dtype (bf16 in, bf16
out). Matches ``dot_product_attention`` to ~1e-2 in bf16, ~1e-5 in f32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(np.finfo(np.float32).min)

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _interpret() -> bool:
    # CPU (tests, 8-fake-device mesh) has no Mosaic backend; interpret there.
    return jax.default_backend() != "tpu"


def _fwd_kernel(
    q_ref, k_ref, v_ref,  # [1,1,bq,d], [1,1,bk,d], [1,1,bk,d]
    o_ref, lse_ref,       # [1,1,bq,d], [1,1,bq,128] (lane-padded, see _flash_fwd)
    m_scr, l_scr, acc_scr,  # VMEM f32: [bq,128], [bq,128], [bq,d]
    *, sm_scale: float, causal: bool, block_q: int, block_k: int,
    kv_len: int | None = None,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: K blocks strictly above the diagonal contribute nothing; a
    # kv_len shorter than the padded K also retires whole blocks. Skip both
    # entirely (predicated off — no MXU work issued).
    block_relevant = True
    if causal:
        block_relevant = ki * block_k <= qi * block_q + (block_q - 1)
    if kv_len is not None:
        block_relevant &= ki * block_k < kv_len

    @pl.when(block_relevant)
    def _compute():
        q = q_ref[0, 0]  # [bq, d]
        k = k_ref[0, 0]  # [bk, d]
        v = v_ref[0, 0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale  # [bq, bk]
        if causal or kv_len is not None:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            keep = jnp.ones((block_q, block_k), bool)
            if causal:
                keep &= q_pos >= k_pos
            if kv_len is not None:
                keep &= k_pos < kv_len
            s = jnp.where(keep, s, NEG_INF)

        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        # rows with nothing unmasked yet keep m = NEG_INF; exp underflows to 0
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1] rescale of history
        p = jnp.exp(s - m_new)                     # [bq, bk]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)

        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * alpha + pv

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        # guard fully-masked rows (can't happen for causal with bq>=1, but
        # keeps the kernel total-function)
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(jnp.where(l == 0.0, 1.0, l))  # [bq, 1]
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _flash_fwd(q, k, v, *, causal, sm_scale, block_q, block_k, kv_len=None):
    """q,k,v: [B, H, S, D] → (o [B,H,S,D], lse [B,H,S] f32)."""
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    # TPU tile constraint: last-two dims of every VMEM block must align to
    # (8,128)/(16,128); requiring 128-multiples keeps the MXU fully fed.
    # Unaligned CALLER shapes are padded by flash_attention() (with kv_len
    # masking the padded keys); reaching here misaligned is a bug.
    if s_q % block_q or s_k % block_k or block_q % 128 or block_k % 128:
        raise NotImplementedError(
            f"flash attention needs 128-aligned blocks: seq_q={s_q}, "
            f"seq_k={s_k}, block_q={block_q}, block_k={block_k}"
        )
    grid = (b, h, s_q // block_q, s_k // block_k)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
    )
    # lse rides a lane-padded [b,h,s_q,128] buffer: a [*, *, bq] block would
    # put a size-1 dim in the sublane slot, which Mosaic's (8,128) tiling
    # rejects on real TPUs (interpret mode doesn't enforce it)
    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((b, h, s_q, 128), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, qi, ki: (b, h, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[..., 0]


def _recompute_p_ds(
    qi, ki, q, k, v, do, lse, delta,
    *, sm_scale: float, causal: bool, block_q: int, block_k: int,
    kv_len: int | None = None,
):
    """Shared backward recompute: scores → (p, ds) for one (Q, K) tile.

    Same masking/scaling as the forward kernel; p = exp(s − lse),
    ds = p ∘ (do·vᵀ − δ) · scale. Inlines at trace time — no runtime cost
    to sharing it between the dkv and dq kernels.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale  # [bq, bk]
    if causal or kv_len is not None:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        keep = jnp.ones((block_q, block_k), bool)
        if causal:
            keep &= q_pos >= k_pos
        if kv_len is not None:
            keep &= k_pos < kv_len
        s = jnp.where(keep, s, NEG_INF)
    p = jnp.exp(s - lse)  # [bq, bk]
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta) * sm_scale
    return p, ds


def _bwd_dkv_kernel(
    q_ref, do_ref, lse_ref, delta_ref,  # [1,1,bq,d], [1,1,bq,d], [1,1,bq,1]×2
    k_ref, v_ref,                        # [1,1,bk,d] ×2
    dk_ref, dv_ref,                      # [1,1,bk,d] ×2
    dk_scr, dv_scr,                      # VMEM f32 [bk,d]
    *, sm_scale: float, causal: bool, block_q: int, block_k: int,
    kv_len: int | None = None,
):
    """dk/dv: K/V block resident, sweep over Q blocks (grid dim 3)."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    relevant = True
    if causal:
        # K block contributes only to Q rows at or below the diagonal
        relevant = qi * block_q + (block_q - 1) >= ki * block_k
    if kv_len is not None:
        # fully-padded K blocks produce zero dk/dv (init covers them)
        relevant &= ki * block_k < kv_len

    @pl.when(relevant)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        p, ds = _recompute_p_ds(
            qi, ki, q, k_ref[0, 0], v_ref[0, 0], do,
            lse_ref[0, 0], delta_ref[0, 0],
            sm_scale=sm_scale, causal=causal, block_q=block_q, block_k=block_k,
            kv_len=kv_len,
        )
        # dv += pᵀ·do ; dk += dsᵀ·q
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_scr[...] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    k_ref, v_ref,                        # [1,1,bk,d] ×2
    q_ref, do_ref, lse_ref, delta_ref,   # [1,1,bq,d]×2, [1,1,bq,1]×2
    dq_ref,                              # [1,1,bq,d]
    dq_scr,                              # VMEM f32 [bq,d]
    *, sm_scale: float, causal: bool, block_q: int, block_k: int,
    kv_len: int | None = None,
):
    """dq: Q block resident, sweep over K blocks (grid dim 3)."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    relevant = True
    if causal:
        relevant = ki * block_k <= qi * block_q + (block_q - 1)
    if kv_len is not None:
        relevant &= ki * block_k < kv_len

    @pl.when(relevant)
    def _compute():
        k = k_ref[0, 0]
        _, ds = _recompute_p_ds(
            qi, ki, q_ref[0, 0], k, v_ref[0, 0],
            do_ref[0, 0].astype(jnp.float32),
            lse_ref[0, 0], delta_ref[0, 0],
            sm_scale=sm_scale, causal=causal, block_q=block_q, block_k=block_k,
            kv_len=kv_len,
        )
        dq_scr[...] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_pallas(res, g, *, causal, sm_scale, block_q, block_k, kv_len=None,
                interpret=None):
    """Pallas dq/dk/dv (FlashAttention-2 backward): two kernels, each
    recomputing p from the saved log-sum-exp — no S×S tensor in HBM."""
    q, k, v, o, lse = res
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    nq, nk = s_q // block_q, s_k // block_k
    if interpret is None:
        interpret = _interpret()

    do = g
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [b,h,sq,1]
    # trailing singleton conforms to Mosaic tiling because a block's last dim
    # may EQUAL the array dim (1==1) instead of being 128-divisible — unlike
    # the forward's lse OUTPUT, whose [*,*,bq] block had bq in the lane slot;
    # validated compiled on a real v5e chip (grads match the scan backward)
    lse_c = lse[..., None]  # [b,h,sq,1]

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, i, 0))
    # dkv grid: i = k block, j = q block (q innermost)
    qspec_j = pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, j, 0))
    rspec_j = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, j, 0))
    rspec_i = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_len=kv_len,
        ),
        grid=(b, h, nk, nq),
        in_specs=[qspec_j, qspec_j, rspec_j, rspec_j, kspec, kspec],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, do, lse_c, delta, k, v)

    kspec_j = pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0))
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_len=kv_len,
        ),
        grid=(b, h, nq, nk),
        in_specs=[kspec_j, kspec_j, qspec, qspec, rspec_i, rspec_i],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(k, v, q, do, lse_c, delta)
    return dq, dk, dv


def _bwd_blockwise(res, g, *, causal, sm_scale, block_k, kv_len=None):
    """Blockwise backward from saved (q,k,v,o,lse): lax.scan over K blocks.

    Standard flash backward identities with the row log-sum-exp:
      p   = exp(q·kᵀ·scale − lse)
      dv  = pᵀ·do
      dp  = do·vᵀ;  δ = rowsum(do ∘ o)
      ds  = p ∘ (dp − δ) · scale
      dq  = Σ_blocks ds·k;   dk = dsᵀ·q
    Never materializes more than [S_q, block_k] of p/ds.
    """
    q, k, v, o, lse = res
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_k = min(block_k, s_k)
    nk = s_k // block_k

    qf = q.astype(jnp.float32)
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1, keepdims=True)  # [b,h,sq,1]
    lse_e = lse[..., None]  # [b,h,sq,1]
    q_pos = jnp.arange(s_q)[:, None]

    # [nk, b, h, block_k, d] scan layout
    kb = k.astype(jnp.float32).reshape(b, h, nk, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.astype(jnp.float32).reshape(b, h, nk, block_k, d).transpose(2, 0, 1, 3, 4)

    def one_block(dq_acc, inp):
        ki, kblk, vblk = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kblk) * sm_scale
        if causal or kv_len is not None:
            k_pos = ki * block_k + jnp.arange(block_k)[None, :]
            keep = jnp.ones(s.shape[-2:], bool)
            if causal:
                keep &= q_pos >= k_pos
            if kv_len is not None:
                keep &= k_pos < kv_len
            s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse_e)                     # [b,h,sq,bk]
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, do)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vblk)
        ds = p * (dp - delta) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kblk)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((b, h, s_q, d), jnp.float32)
    dq, (dk, dv) = jax.lax.scan(one_block, dq0, (jnp.arange(nk), kb, vb))
    dk = dk.transpose(1, 2, 0, 3, 4).reshape(b, h, s_k, d)
    dv = dv.transpose(1, 2, 0, 3, 4).reshape(b, h, s_k, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, pallas_bwd, kv_len):
    o, _ = _flash_fwd(
        q, k, v, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
    )
    return o


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, pallas_bwd,
                   kv_len):
    o, lse = _flash_fwd(
        q, k, v, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
    )
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, pallas_bwd, kv_len,
                   res, g):
    if pallas_bwd and not _interpret():
        return _bwd_pallas(
            res, g, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, kv_len=kv_len,
        )
    return _bwd_blockwise(res, g, causal=causal, sm_scale=sm_scale,
                          block_k=block_k, kv_len=kv_len)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q, k, v, *, causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
    pallas_bwd: bool = False, kv_len: int | None = None,
):
    """Flash attention on [B, S, H, D] inputs (same layout as
    :func:`tpudist.ops.attention.dot_product_attention`).

    Unaligned S is padded to the 128-tile multiple: padded KEYS are masked
    inside the kernels (``kv_len`` — also passable explicitly for
    right-padded batches), padded query rows are sliced off the output.

    ``pallas_bwd`` selects the Pallas FA-2 backward kernels instead of the
    default blockwise-scan backward. Both are O(S·block) memory; measured on
    one v5e chip the scan backward is faster at d=64/S≤4096 shapes (XLA
    fuses it well) while the kernels close the gap by S=8192 — benchmark
    your shape before flipping this on. TPU-only: on other backends the
    flag is ignored and the scan backward runs.
    """
    if q.ndim != 4:
        raise NotImplementedError(f"expected [B,S,H,D], got {q.shape}")
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if kv_len is None:
        kv_len = s_k
    if causal and s_q != s_k:
        raise NotImplementedError("causal path assumes s_q == s_k")
    sm_scale = 1.0 / float(np.sqrt(d))
    # Pad ragged sequences to the 128-tile multiple; the kernels mask the
    # padded keys via kv_len and padded query rows are sliced off below.
    pad_q = -s_q % 128
    pad_k = -s_k % 128
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    # kv_len == padded length means "nothing masked": drop it so the
    # kernels skip the mask compare entirely
    eff_kv = None if kv_len == k.shape[1] else kv_len
    # Pad head_dim to the 128-lane tile. Zero-padded q/k leave scores
    # unchanged; padded v columns produce output columns sliced off below.
    d_pad = -d % 128
    if d_pad:
        pad = [(0, 0)] * 3 + [(0, d_pad)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    # [B,S,H,D] → [B,H,S,D] for contiguous per-head tiles
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _flash(qt, kt, vt, causal, sm_scale, block_q, block_k, pallas_bwd,
               eff_kv)
    return o.transpose(0, 2, 1, 3)[:, :s_q, :, :d]
