from tpudist.ops.attention import multi_head_attention, dot_product_attention

__all__ = ["multi_head_attention", "dot_product_attention"]
