"""One-pass fused AdamW update as a Pallas TPU kernel.

The optax ``adamw`` chain is a sequence of tree transforms (moment update,
bias correction, decayed weights, learning-rate scale) each of which is its
own pass over params-shaped trees, plus — in a bf16-compute run — a
separate whole-model fp32→bf16 cast of every parameter each step. On the
124M GPT-2 step those passes are part of the measured ~100 ms serial
elementwise tail (docs/PERF.md §4b): bandwidth-bound work XLA fuses only
partially.

This kernel reads ``(grad, m, v, fp32 master param)`` and writes
``(m', v', update, bf16 compute copy)`` in a single HBM sweep per leaf:
every intermediate (biased-corrected moments, the Adam direction, the
decayed-weight term, the new parameter value the copy is cast from) lives
only in VMEM. The update is returned (rather than the new param written
in place) so the surface stays optax-compatible — ``optax.apply_updates``
adds it to the master, one fusion XLA folds — and the compute copy is
``compute_dtype(p + u)``, bit-identical to casting the post-update master.

The ARITHMETIC mirrors ``optax.adamw`` exactly (division-form bias
correction, ``sqrt(v̂)+eps`` denominator, decay-then-scale order), so the
kernel path and the reference chain agree bit-for-bit in interpret mode —
the parity bar tests/test_fused_update.py pins.

Leaves below :data:`MIN_KERNEL_ELEMS` take the identical-formula XLA path
(:func:`reference_leaf_update`): a kernel launch per 4-element bias is all
overhead, and the two paths share one formula function so they cannot
drift. The optimizer-facing wrapper (``tpudist.optim.fused_adamw``) owns
the tree walk, hyperparameters, and optax ``(init, update)`` surface.

GSPMD note: ``pallas_call`` has no partitioning rule. On replicated state
(pure DP — the regime §4b measures) every chip runs the sweep on its own
copy, exactly like the optax chain. Under ZeRO-1 ``shard_state`` the
interpret path decomposes into partitionable ops (the composition tests
run there); on a real TPU the compiler may all-gather sharded operands
around the custom call — combine fused LN with ZeRO-1 freely, but measure
before combining the fused *optimizer* with it on hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# below this many elements the per-launch overhead dwarfs the sweep; the
# XLA path runs the same formula (tests pin the two paths to agreement)
MIN_KERNEL_ELEMS = 8 * 128

_LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def adamw_math(g, m, v, p, lr, b1c, b2c, *, b1, b2, eps, wd):
    """The ONE AdamW formula both paths share, optax-order arithmetic:

    ``m' = b1·m + (1−b1)·g``; ``v' = b2·v + (1−b2)·g²``;
    ``u = −lr · ( (m'/b1c) / (√(v'/b2c) + eps) + wd·p )``.

    ``b1c``/``b2c`` are the bias-correction denominators ``1 − βᵗ`` (traced
    scalars, computed once per step by the caller). Returns
    ``(m', v', u)`` in fp32.
    """
    g = g.astype(jnp.float32)
    m = m.astype(jnp.float32)
    v = v.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    m2 = b1 * m + (1.0 - b1) * g
    v2 = b2 * v + (1.0 - b2) * jnp.square(g)
    mhat = m2 / b1c
    vhat = v2 / b2c
    direction = mhat / (jnp.sqrt(vhat) + eps)
    if wd:
        direction = direction + wd * p32
    return m2, v2, direction * (-lr)


def reference_leaf_update(g, m, v, p, lr, b1c, b2c, *, b1, b2, eps, wd,
                          compute_dtype=None):
    """Plain-XLA AdamW for one leaf — the small-leaf path and the oracle
    the kernel is pinned against. Returns ``(u, m', v', copy|None)``."""
    m2, v2, u = adamw_math(g, m, v, p, lr, b1c, b2c,
                           b1=b1, b2=b2, eps=eps, wd=wd)
    copy = None
    if compute_dtype is not None:
        copy = (p.astype(jnp.float32) + u).astype(compute_dtype)
    return u.astype(p.dtype), m2.astype(m.dtype), v2.astype(v.dtype), copy


def _update_kernel(s_ref, g_ref, m_ref, v_ref, p_ref,
                   u_ref, m_out, v_out, *maybe_c,
                   b1: float, b2: float, eps: float, wd: float,
                   has_copy: bool):
    lr, b1c, b2c = s_ref[0], s_ref[1], s_ref[2]
    p = p_ref[...]
    m2, v2, u = adamw_math(
        g_ref[...], m_ref[...], v_ref[...], p, lr, b1c, b2c,
        b1=b1, b2=b2, eps=eps, wd=wd,
    )
    u_ref[...] = u.astype(u_ref.dtype)
    m_out[...] = m2.astype(m_out.dtype)
    v_out[...] = v2.astype(v_out.dtype)
    if has_copy:
        c_ref = maybe_c[0]
        c_ref[...] = (p.astype(jnp.float32) + u).astype(c_ref.dtype)


def fused_leaf_update(g, m, v, p, lr, b1c, b2c, *, b1, b2, eps, wd=0.0,
                      compute_dtype=None, block_rows: int = 512,
                      min_kernel_elems: int | None = None):
    """One-HBM-sweep AdamW for one parameter leaf.

    ``g``/``m``/``v``/``p``: same shape, any rank. ``lr``/``b1c``/``b2c``:
    traced fp32 scalars (the per-step hyperparameter vector rides SMEM).
    ``wd`` is this leaf's static decay coefficient (0.0 for masked-off
    leaves — bias/norm params under ``decay_mask``). ``compute_dtype``
    adds the cast compute copy as a fourth output written in the same
    sweep.

    Returns ``(u, m', v', copy|None)`` with ``u`` in ``p.dtype`` and the
    moments in their input dtypes. Leaves smaller than
    :data:`MIN_KERNEL_ELEMS` (override via ``min_kernel_elems``) run
    :func:`reference_leaf_update` — same formula, no launch.
    """
    limit = MIN_KERNEL_ELEMS if min_kernel_elems is None else min_kernel_elems
    if p.size < limit:
        return reference_leaf_update(
            g, m, v, p, lr, b1c, b2c, b1=b1, b2=b2, eps=eps, wd=wd,
            compute_dtype=compute_dtype,
        )

    shape = p.shape
    n = p.size
    rows = -(-n // _LANES)
    bn = max(8, min(block_rows, rows) // 8 * 8)
    rows_pad = rows + (-rows % bn)

    def prep(a):
        flat = jnp.ravel(a)
        return jnp.pad(flat, (0, rows_pad * _LANES - n)).reshape(
            rows_pad, _LANES
        )

    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(b1c, jnp.float32),
        jnp.asarray(b2c, jnp.float32),
    ])
    row_spec = pl.BlockSpec((bn, _LANES), lambda i: (i, 0))
    has_copy = compute_dtype is not None
    out_specs = [row_spec, row_spec, row_spec]
    out_shape = [
        jax.ShapeDtypeStruct((rows_pad, _LANES), p.dtype),
        jax.ShapeDtypeStruct((rows_pad, _LANES), m.dtype),
        jax.ShapeDtypeStruct((rows_pad, _LANES), v.dtype),
    ]
    if has_copy:
        out_specs.append(row_spec)
        out_shape.append(
            jax.ShapeDtypeStruct((rows_pad, _LANES), jnp.dtype(compute_dtype))
        )
    out = pl.pallas_call(
        functools.partial(
            _update_kernel, b1=float(b1), b2=float(b2), eps=float(eps),
            wd=float(wd), has_copy=has_copy,
        ),
        grid=(rows_pad // bn,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  row_spec, row_spec, row_spec, row_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(scalars, prep(g), prep(m), prep(v), prep(p))

    def unprep(a):
        return jnp.ravel(a)[:n].reshape(shape)

    u, m2, v2 = unprep(out[0]), unprep(out[1]), unprep(out[2])
    copy = unprep(out[3]) if has_copy else None
    return u, m2, v2, copy
