"""Fused residual-add + LayerNorm/RMSNorm as a Pallas TPU kernel.

docs/PERF.md §4b measured that the GPT-2 124M step's GEMMs run at 85–94% of
peak and the remaining ~100 ms (~40% of the step) is the serial elementwise
tail between them — layernorms, residual adds, casts. XLA fuses those
chains, but each ``x + y`` → ``LayerNorm`` pair still costs separate HBM
round trips for the add's result and the norm's two reduction passes. This
kernel collapses one pair into a single sweep:

- **forward**: one grid pass over row blocks computes ``r = x + y`` (the
  residual-stream update), the masked mean/variance of ``r`` over the true
  feature width, and the normalized/affine output — all while the block is
  VMEM-resident, with one HBM read of (x, y) and one write of (out, r).
  The optional output cast (bf16 models) happens in the same write instead
  of a separate cast pass.
- **backward** (``custom_vjp``): one grid pass over the SAME saved ``r``
  recomputes the row statistics in-block (cheaper than storing them:
  lane-padded stats would cost ~1/6 of the activation bytes at width 768)
  and emits ``dr`` plus ``dscale``/``dbias`` accumulated across the row
  sweep in VMEM scratch — the classic LN backward identities, one HBM read
  of (r, g), one write of dr. Because ``r = x + y`` is a plain add,
  ``dx = dy = dr (+ the residual-stream cotangent)`` and no second pass
  exists.

Numerics: statistics and the normalize are computed in float32 regardless
of input dtype (the flax modules cast the *normalize* to the compute dtype;
this kernel is the strictly-better-precision side of the fp32 tolerance the
parity tests pin). Variance is the direct ``E[(x-µ)²]`` form.

Three public compositions (all interpret-mode on CPU, like the flash/vmem
kernels, so the whole test suite exercises the real kernel code paths):

- ``fused_layernorm(x, scale, bias)`` — plain one-pass norm (a model's
  first/final LN, which has no pending residual add);
- ``fused_layernorm(x, scale, bias, residual=r)`` — pre-norm blocks:
  returns ``(normed, r + x)`` so the residual stream continues;
- ``... return_residual=False`` — post-norm blocks (BERT): the sum is
  normalized and only the normed value returns (the sum is still saved
  for backward, exactly what autodiff would have stored).

``rms=True`` selects scale-only RMS normalization (Llama/T5 convention,
flax ``nn.RMSNorm`` parity). The :class:`FusedLayerNorm` flax module
declares params under the SAME names/shapes as ``nn.LayerNorm`` /
``nn.RMSNorm`` ("scale", "bias"), so a model can flip its ``fused_ln``
knob without changing its checkpoint format.

GSPMD: like every Pallas op here, ``pallas_call`` has no partitioning
rule, so on a >1-device mesh the kernel must run per-shard inside
``shard_map`` — pass ``mesh=`` (the models thread their own ``mesh``
field); rows are batch-parallel so the wrap is exact. With ``mesh=None``
the op still partitions correctly under single-chip-per-process DP and on
the CPU interpret path (tpudist.ops.attention documents the same rule).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    # CPU (tests, 8-fake-device mesh) has no Mosaic backend; interpret there.
    return jax.default_backend() != "tpu"


@dataclasses.dataclass(frozen=True)
class _Cfg:
    """Static kernel configuration — hashable, rides custom_vjp's
    nondiff_argnums. ``d_true`` is the unpadded feature width (the mask +
    mean denominator); ``out_dtype``/``res_dtype`` are canonical dtype
    names (dtypes themselves are unhashable pre-numpy-2)."""

    eps: float
    d_true: int
    rms: bool
    out_dtype: str
    res_dtype: str
    block_rows: int


def _pick_block_rows(d_pad: int) -> int:
    # ~2 MB of f32 per VMEM buffer; sublane multiple of 8
    bn = (1 << 21) // (d_pad * 4)
    return int(max(8, min(256, bn // 8 * 8)))


def _row_stats(r, cfg: _Cfg, d_pad: int):
    """Masked per-row (mean, rstd) over the true feature width — shared
    verbatim by the forward and the recomputing backward so they cannot
    disagree bitwise."""
    if d_pad != cfg.d_true:
        mask = jax.lax.broadcasted_iota(jnp.int32, r.shape, 1) < cfg.d_true
        rm = jnp.where(mask, r, 0.0)
    else:
        mask = None
        rm = r
    inv_d = 1.0 / cfg.d_true
    if cfg.rms:
        mean = jnp.zeros((r.shape[0], 1), jnp.float32)
        var = jnp.sum(rm * rm, axis=1, keepdims=True) * inv_d
    else:
        mean = jnp.sum(rm, axis=1, keepdims=True) * inv_d
        diff = r - mean
        if mask is not None:
            diff = jnp.where(mask, diff, 0.0)
        var = jnp.sum(diff * diff, axis=1, keepdims=True) * inv_d
    rstd = jax.lax.rsqrt(var + cfg.eps)
    return mean, rstd, mask


def _fwd_kernel(x_ref, *rest, cfg: _Cfg, has_residual: bool):
    if has_residual:
        y_ref, scale_ref, bias_ref, out_ref, res_ref = rest
    else:
        y_ref, res_ref = None, None
        scale_ref, bias_ref, out_ref = rest
    r = x_ref[...].astype(jnp.float32)
    if has_residual:
        r = r + y_ref[...].astype(jnp.float32)
    mean, rstd, _ = _row_stats(r, cfg, x_ref.shape[1])
    n = (r - mean) * rstd * scale_ref[...].astype(jnp.float32)
    if not cfg.rms:
        n = n + bias_ref[...].astype(jnp.float32)
    out_ref[...] = n.astype(out_ref.dtype)
    if has_residual:
        res_ref[...] = r.astype(res_ref.dtype)


def _bwd_kernel(r_ref, g_ref, *rest, cfg: _Cfg, has_gr: bool):
    if has_gr:
        gr_ref, scale_ref, dr_ref, ds_ref, db_ref, ds_scr, db_scr = rest
    else:
        gr_ref = None
        scale_ref, dr_ref, ds_ref, db_ref, ds_scr, db_scr = rest
    i = pl.program_id(0)
    nb = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        ds_scr[...] = jnp.zeros_like(ds_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    r = r_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mean, rstd, mask = _row_stats(r, cfg, r_ref.shape[1])
    xhat = (r - mean) * rstd
    dxhat = g * scale_ref[...].astype(jnp.float32)
    if mask is not None:
        # padded feature columns carry zero cotangent by construction (the
        # wrapper's slice pads g with zeros), but xhat is garbage there —
        # keep it out of the row means and the dscale accumulator
        xhat = jnp.where(mask, xhat, 0.0)
        dxhat = jnp.where(mask, dxhat, 0.0)
    inv_d = 1.0 / cfg.d_true
    c2 = jnp.sum(dxhat * xhat, axis=1, keepdims=True) * inv_d
    dr = dxhat - xhat * c2
    if not cfg.rms:
        c1 = jnp.sum(dxhat, axis=1, keepdims=True) * inv_d
        dr = dr - c1
    dr = dr * rstd
    if has_gr:
        dr = dr + gr_ref[...].astype(jnp.float32)
    dr_ref[...] = dr.astype(dr_ref.dtype)
    # every scratch row accumulates the SAME block row-sum (the 8-row shape
    # keeps the sublane dim tile-conformant on real TPUs — a (1, D) block
    # would put 1 in the sublane slot; interpret mode doesn't enforce it,
    # the flash kernel's lse buffer documents the same dance)
    ds_scr[...] += jnp.broadcast_to(
        jnp.sum(g * xhat, axis=0, keepdims=True), ds_scr.shape
    )
    db_scr[...] += jnp.broadcast_to(
        jnp.sum(g, axis=0, keepdims=True), db_scr.shape
    )

    @pl.when(i == nb - 1)
    def _fin():
        ds_ref[...] = ds_scr[...]
        db_ref[...] = db_scr[...]


def _fwd_call(x, y, scale, bias, cfg: _Cfg):
    """x[, y]: [N, Dp] padded; scale/bias: [1, Dp]. → (n, r|None)."""
    n_rows, d_pad = x.shape
    bn = cfg.block_rows
    grid = (n_rows // bn,)
    row_spec = pl.BlockSpec((bn, d_pad), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d_pad), lambda i: (0, 0))
    has_residual = y is not None
    in_specs = [row_spec] + ([row_spec] if has_residual else []) + [vec_spec, vec_spec]
    out_specs = [row_spec] + ([row_spec] if has_residual else [])
    out_shape = [jax.ShapeDtypeStruct(x.shape, jnp.dtype(cfg.out_dtype))] + (
        [jax.ShapeDtypeStruct(x.shape, jnp.dtype(cfg.res_dtype))]
        if has_residual else []
    )
    args = (x, y, scale, bias) if has_residual else (x, scale, bias)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg=cfg, has_residual=has_residual),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(*args)
    return (out[0], out[1]) if has_residual else (out[0], None)


def _bwd_call(r, g, gr, scale, cfg: _Cfg):
    """→ (dr [N, Dp] in res dtype, dscale [1, Dp] f32, dbias [1, Dp] f32)."""
    n_rows, d_pad = r.shape
    bn = cfg.block_rows
    grid = (n_rows // bn,)
    row_spec = pl.BlockSpec((bn, d_pad), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d_pad), lambda i: (0, 0))
    has_gr = gr is not None
    in_specs = [row_spec, row_spec] + ([row_spec] if has_gr else []) + [vec_spec]
    args = (r, g, gr, scale) if has_gr else (r, g, scale)
    red_spec = pl.BlockSpec((8, d_pad), lambda i: (0, 0))
    dr, ds, db = pl.pallas_call(
        functools.partial(_bwd_kernel, cfg=cfg, has_gr=has_gr),
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, red_spec, red_spec],
        out_shape=[
            jax.ShapeDtypeStruct(r.shape, jnp.dtype(cfg.res_dtype)),
            jax.ShapeDtypeStruct((8, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((8, d_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, d_pad), jnp.float32),
            pltpu.VMEM((8, d_pad), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    # all 8 accumulator rows hold the same total; row 0 is the reduction
    return dr, ds[:1], db[:1]


# --- three custom_vjp compositions over the padded [N, Dp] core ----------
#
# The pad/slice to tile-aligned shapes lives OUTSIDE these functions (in
# fused_layernorm), so autodiff of the slice delivers zero cotangents for
# padded rows/columns automatically and the kernels never special-case them.


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_plain(x, scale, bias, cfg):
    n, _ = _fwd_call(x, None, scale, bias, cfg)
    return n


def _ln_plain_fwd(x, scale, bias, cfg):
    n, _ = _fwd_call(x, None, scale, bias, cfg)
    return n, (x, scale)


def _ln_plain_bwd(cfg, res, g):
    x, scale = res
    dr, ds, db = _bwd_call(x, g, None, scale, cfg)
    return dr, ds.astype(scale.dtype), db.astype(scale.dtype)


_ln_plain.defvjp(_ln_plain_fwd, _ln_plain_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ln_post(x, y, scale, bias, cfg):
    n, _ = _fwd_call(x, y, scale, bias, cfg)
    return n


def _ln_post_fwd(x, y, scale, bias, cfg):
    n, r = _fwd_call(x, y, scale, bias, cfg)
    return n, (r, scale)


def _ln_post_bwd(cfg, res, g):
    r, scale = res
    dr, ds, db = _bwd_call(r, g, None, scale, cfg)
    return dr, dr, ds.astype(scale.dtype), db.astype(scale.dtype)


_ln_post.defvjp(_ln_post_fwd, _ln_post_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ln_pre(x, y, scale, bias, cfg):
    return _fwd_call(x, y, scale, bias, cfg)


def _ln_pre_fwd(x, y, scale, bias, cfg):
    n, r = _fwd_call(x, y, scale, bias, cfg)
    return (n, r), (r, scale)


def _ln_pre_bwd(cfg, res, gs):
    r, scale = res
    g, gr = gs
    dr, ds, db = _bwd_call(r, g, gr, scale, cfg)
    return dr, dr, ds.astype(scale.dtype), db.astype(scale.dtype)


_ln_pre.defvjp(_ln_pre_fwd, _ln_pre_bwd)


def fused_layernorm(
    x,
    scale,
    bias=None,
    *,
    residual=None,
    eps: float = 1e-6,
    rms: bool = False,
    out_dtype=None,
    return_residual: bool | None = None,
    mesh=None,
    block_rows: int | None = None,
):
    """Fused (residual-add +) LayerNorm/RMSNorm over the last axis of ``x``.

    ``x``: ``[..., D]``; ``scale``/``bias``: ``[D]`` (``bias`` ignored when
    ``rms``). ``residual``: optional same-shape tensor; the kernel computes
    ``r = x + residual`` and normalizes ``r``. ``return_residual`` (default:
    ``residual is not None``) controls whether ``r`` is returned alongside
    the normed value — pre-norm blocks need it (the residual stream
    continues), post-norm blocks don't (one fewer HBM write).

    Returns ``normed`` or ``(normed, r)``. ``out_dtype`` defaults to
    ``x.dtype`` (pass the model's compute dtype to fold the bf16 cast into
    the kernel's write). Unaligned shapes are padded to the (8, 128) tile
    outside the kernel and masked/sliced — the mean/variance denominators
    always use the true ``D``.
    """
    if return_residual is None:
        return_residual = residual is not None
    if return_residual and residual is None:
        raise ValueError("return_residual=True needs a residual operand")
    d = x.shape[-1]
    if scale.shape != (d,):
        raise ValueError(f"scale shape {scale.shape} != ({d},)")
    if residual is not None and residual.shape != x.shape:
        raise ValueError(
            f"residual shape {residual.shape} != x shape {x.shape}"
        )
    out_dtype = jnp.dtype(out_dtype or x.dtype)

    if mesh is not None:
        from tpudist import mesh as mesh_lib
        from tpudist.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        dp = int(np.prod([
            mesh.shape[a] for a in (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
        ]))
        # rows are batch-parallel: per-shard execution is exact. Indivisible
        # shapes (the batch-1 init trace) fall through unwrapped — same
        # rule as tpudist.ops.attention.
        if dp > 1 and x.shape[0] % dp == 0:
            spec = P((mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
                     *([None] * (x.ndim - 1)))
            rep = P(None)
            has_res = residual is not None
            fn = shard_map(
                lambda xs, rs, sc, bi: fused_layernorm(
                    xs, sc, bi, residual=rs if has_res else None, eps=eps,
                    rms=rms, out_dtype=out_dtype,
                    return_residual=return_residual, block_rows=block_rows,
                ),
                mesh=mesh,
                in_specs=(spec, spec if residual is not None else rep,
                          rep, rep),
                out_specs=(spec, spec) if return_residual else spec,
                check_vma=False,
            )
            return fn(
                x,
                residual if residual is not None else jnp.zeros((1,), x.dtype),
                scale,
                bias if bias is not None else jnp.zeros((d,), scale.dtype),
            )

    # flatten rows, pad to the (block_rows, 128) tile
    lead = x.shape[:-1]
    n = int(np.prod(lead)) if lead else 1
    d_pad = d + (-d % 128)
    bn = min(block_rows or _pick_block_rows(d_pad), 256)
    bn = max(8, bn - bn % 8)
    n_pad = n + (-n % bn)

    def prep(a):
        a2 = a.reshape(n, d)
        return jnp.pad(a2, ((0, n_pad - n), (0, d_pad - d)))

    x2 = prep(x)
    y2 = prep(residual) if residual is not None else None
    scale2 = jnp.pad(scale, (0, d_pad - d)).reshape(1, d_pad)
    bias_arr = bias if (bias is not None and not rms) else jnp.zeros(
        (d,), scale.dtype
    )
    bias2 = jnp.pad(bias_arr, (0, d_pad - d)).reshape(1, d_pad)

    cfg = _Cfg(
        eps=float(eps), d_true=d, rms=bool(rms),
        out_dtype=out_dtype.name, res_dtype=jnp.dtype(x.dtype).name,
        block_rows=bn,
    )
    if residual is None:
        n_out = _ln_plain(x2, scale2, bias2, cfg)
        r_out = None
    elif return_residual:
        n_out, r_out = _ln_pre(x2, y2, scale2, bias2, cfg)
    else:
        n_out = _ln_post(x2, y2, scale2, bias2, cfg)
        r_out = None

    def unprep(a):
        return a[:n, :d].reshape(*lead, d)

    if return_residual:
        return unprep(n_out), unprep(r_out)
    return unprep(n_out)


class FusedLayerNorm(nn.Module):
    """Drop-in fused counterpart of ``nn.LayerNorm`` / ``nn.RMSNorm``
    (``rms=True``) with an optional fused residual add.

    Declares the SAME params ("scale" [D]; "bias" [D] unless ``rms``) under
    whatever ``name=`` the caller gives it, so a model toggling between the
    flax modules and this one keeps an identical parameter tree — the
    property the ``fused_ln`` model knob (and every existing checkpoint)
    relies on.

    ``__call__(x, residual=None, return_residual=None)`` mirrors
    :func:`fused_layernorm`: plain norm, post-norm (``residual=`` with the
    default ``return_residual=False`` semantics when only the normed value
    is consumed), or pre-norm (``(normed, new_residual_stream)``).
    """

    epsilon: float = 1e-6
    dtype: Any = jnp.float32
    rms: bool = False
    mesh: Any = None

    @nn.compact
    def __call__(self, x, residual=None, return_residual: bool | None = None):
        d = x.shape[-1]
        scale = self.param(
            "scale", nn.initializers.ones_init(), (d,), jnp.float32
        )
        bias = None if self.rms else self.param(
            "bias", nn.initializers.zeros_init(), (d,), jnp.float32
        )
        return fused_layernorm(
            x, scale, bias, residual=residual, eps=self.epsilon,
            rms=self.rms, out_dtype=self.dtype,
            return_residual=return_residual, mesh=self.mesh,
        )
