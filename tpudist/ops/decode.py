"""Autoregressive KV-cache machinery for decode-mode attention.

No reference counterpart (the reference trains a CNN); this serves the LM
families' generation path (:mod:`tpudist.generate`). TPU-first shape
discipline: the cache is a fixed ``[B, max_len, H, dh]`` buffer updated with
``dynamic_update_slice`` and attention masks are computed against the full
buffer — everything static-shaped, so one compiled step serves every
position and ``lax.scan`` drives the whole generation loop in-graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cached_kv(module, k, v, max_len: int, pre_update=None):
    """Append this step's K/V into the module's decode cache.

    Must be called inside a flax module's ``__call__`` (it creates
    ``cache`` collection variables). ``k``/``v``: ``[B, s, H, dh]`` for the
    current step (``s`` is 1 during sampling; larger chunks work if the
    caller masks causality within the chunk — our callers feed 1).

    ``pre_update(k, v, position) -> (k, v)`` runs before the write with the
    step's absolute position — RoPE models rotate keys here so the cache
    holds position-encoded keys.

    Returns ``(keys, values, mask, position)``: the full cache buffers, a
    ``[1, 1, s, max_len]`` attention mask over valid (already-written)
    slots, and the integer position where this step was written (for
    RoPE / learned-position lookup).
    """
    b, s, h, dh = k.shape
    # the init trace only CREATES the cache (shape/dtype); mutating there
    # would hand callers a cache already advanced past position 0
    initialized = module.has_variable("cache", "cached_key")
    ck = module.variable(
        "cache", "cached_key", jnp.zeros, (b, max_len, h, dh), k.dtype
    )
    cv = module.variable(
        "cache", "cached_value", jnp.zeros, (b, max_len, h, dh), v.dtype
    )
    ci = module.variable(
        "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
    )
    pos = ci.value
    if pre_update is not None:
        k, v = pre_update(k, v, pos)
    if initialized:
        ck.value = jax.lax.dynamic_update_slice(ck.value, k, (0, pos, 0, 0))
        cv.value = jax.lax.dynamic_update_slice(cv.value, v, (0, pos, 0, 0))
        ci.value = pos + s
    # slot t is attendable by step row i iff t <= pos + i (causal over the
    # buffer; unwritten slots are masked out entirely)
    slots = jnp.arange(max_len)[None, None, None, :]
    rows = pos + jnp.arange(s)[None, None, :, None]
    mask = slots <= rows
    return ck.value, cv.value, mask, pos
