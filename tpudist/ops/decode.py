"""Autoregressive KV-cache machinery for decode-mode attention.

No reference counterpart (the reference trains a CNN); this serves the LM
families' generation path (:mod:`tpudist.generate`). TPU-first shape
discipline: the cache is a fixed head-major ``[B, H, max_len, dh]`` buffer
updated with ``dynamic_update_slice`` and attention masks are computed
against the full buffer — everything static-shaped, so one compiled step
serves every position and ``lax.scan`` drives the whole generation loop
in-graph. Head-major layout is deliberate: each (batch, head) pair's
``[S, dh]`` cache panel is contiguous, which is exactly the tile the fused
kernel DMAs per grid step (Pallas TPU blocks must keep their trailing two
dims whole or 8/128-aligned — a seq-major layout cannot slice one head
without violating that).

Two attention paths over the cache (:func:`decode_attention` dispatches):

- ``xla``: the dense oracle — einsum scores over the full buffer with the
  slot mask; ~10 small kernels per layer per token.
- ``fused``: ONE Pallas launch per layer (:func:`_fused_decode_attention`)
  computing scores + slot mask + softmax + value mix for every head. A
  batch-8 decode step dispatches ~300 µs-scale kernels and is
  launch-bound, not bandwidth-bound (docs/PERF.md §7); collapsing the
  ~6-kernel attention chain into one launch attacks the kernel-count term
  directly. Grid is (batch,): each step DMAs the row's whole contiguous
  [H_kv, S, dh] K/V — the mandatory cache read — and loops heads
  in-kernel, so the kernel rides the byte floor with no score/prob
  intermediates in HBM and no per-head grid overhead (the per-(b, h)
  grid variant measured slower; see the function docstring).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(np.finfo(np.float32).min)

# Measured crossover (v5e, GPT-2 124M decode, interleaved A/B medians with
# the subset sampler): the fused kernel wins at batch 8 (4.81 vs 5.16
# ms/step) and loses from batch 32 up (7.60 vs 6.89 at 32; 19.95 vs 11.48
# at 128) — at serving batch XLA's batched attention GEMMs beat the
# kernel's per-row head loop, while at latency batch the kernel's single
# launch beats XLA's ~6-kernel chain. The dispatcher falls back to the
# dense path above this bound.
FUSED_MAX_BATCH = 16


def cached_kv(module, k, v, max_len: int, pre_update=None, positions=None,
              block_tables=None):
    """Append this step's K/V into the module's decode cache.

    Must be called inside a flax module's ``__call__`` (it creates
    ``cache`` collection variables). ``k``/``v``: ``[B, s, H, dh]`` for the
    current step — ``s`` is 1 during sampling; larger chunks are
    first-class (the returned mask is causal WITHIN the chunk: slot ``t``
    attendable by chunk row ``i`` iff ``t <= pos + i``), and
    ``tpudist.generate``'s bulk prefill relies on exactly that, feeding
    the whole prompt as one chunk.

    ``pre_update(k, v, position) -> (k, v)`` runs before the write with the
    step's absolute position — RoPE models rotate keys here so the cache
    holds position-encoded keys.

    ``positions`` switches to slot-pooled decode (``tpudist.serve``): a
    ``[B]`` int32 vector of PER-ROW absolute positions. Each row's K/V is
    scattered at its own cursor and the mask is per-row AND causal within
    the chunk (``slot <= pos_b + i``) — the shape discipline that lets
    requests at different sequence lengths share one compiled decode
    step. ``s > 1`` is the speculative-decoding VERIFY sweep
    (``tpudist.serve.spec``): row ``b``'s chunk entries land at
    ``pos_b .. pos_b + s - 1``, and entries past ``max_len`` self-clamp
    (their one-hot is empty — nothing is written, and the engine's
    acceptance cap guarantees such tail entries are never consumed). The
    module's scalar ``cache_index`` is neither read nor advanced (the
    engine owns per-slot lengths), but it stays declared so the cache
    tree's structure is identical in both modes — a jit'd loop can donate
    the same cache pytree through either path.

    ``block_tables`` (with ``positions``) switches to PAGED decode
    (``tpudist.serve.blocks``): the cache variables hold the SHARED block
    pool ``[n_blocks, H, block_size, dh]`` (built by
    :func:`tpudist.serve.blocks.paged_cache` and passed in — there is no
    init path for it), and ``block_tables`` is a ``[B, max_blocks]`` int32
    map from each row's logical block index to its physical pool block.
    Row ``b``'s K/V is written at
    ``(table[b, pos_b // block_size], pos_b % block_size)``, and the
    return switches to ``(k_pool, v_pool, block_tables, positions)`` for
    :func:`paged_decode_attention`. HBM then holds Σ(actual lengths)
    instead of ``B × max_len`` — the long-tail serving win (docs/SERVING.md
    "Paged memory").

    Returns ``(keys, values, mask, position)``: the full head-major
    ``[B, H, max_len, dh]`` cache buffers, a ``[1, 1, s, max_len]``
    (scalar mode) or ``[B, 1, 1, max_len]`` (per-row mode) attention mask
    over valid (already-written) slots, and the position(s) where this
    step was written (for RoPE / learned-position lookup). Feed the
    buffers to :func:`decode_attention` — they are NOT in the models'
    ``[B, S, H, dh]`` activation layout.
    """
    b, s, h, dh = k.shape
    # the init trace only CREATES the cache (shape/dtype); mutating there
    # would hand callers a cache already advanced past position 0
    initialized = module.has_variable("cache", "cached_key")
    if block_tables is not None and not initialized:
        raise ValueError(
            "paged decode has no init path: build the block pool with "
            "tpudist.serve.blocks.paged_cache and pass it in as the "
            "'cache' collection"
        )
    ck = module.variable(
        "cache", "cached_key", jnp.zeros, (b, h, max_len, dh), k.dtype
    )
    cv = module.variable(
        "cache", "cached_value", jnp.zeros, (b, h, max_len, dh), v.dtype
    )
    ci = module.variable(
        "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
    )
    if block_tables is not None:
        if positions is None:
            raise ValueError("paged decode needs per-row positions")
        pool_k, pool_v = ck.value, cv.value  # [N, H_kv, bs, dh]
        bs_blk = pool_k.shape[2]
        pos = jnp.asarray(positions, jnp.int32)
        bt = jnp.asarray(block_tables, jnp.int32)
        mb = bt.shape[1] if bt.ndim == 2 else 0
        if pos.shape != (b,):
            raise ValueError(f"positions must be [{b}], got {pos.shape}")
        if bt.ndim != 2 or bt.shape[0] != b:
            raise ValueError(
                f"block_tables must be [{b}, max_blocks], got {bt.shape}"
            )
        if pre_update is not None:
            k, v = pre_update(k, v, pos)
        kt = k.astype(pool_k.dtype).transpose(0, 2, 1, 3)  # [B, H_kv, s, dh]
        vt = v.astype(pool_v.dtype).transpose(0, 2, 1, 3)

        # B×s sequential single-(block,offset) dynamic_update_slices
        # carried through a fori_loop: each updates a [1, H, 1, dh] sliver
        # of the donated pool in place. A gather-scatter
        # (`.at[blk, :, off, :]`) would block XLA's in-place path and copy
        # the WHOLE pool per layer per step — the exact copy the paged
        # layout exists to avoid (the same measurement that shaped the
        # contiguous one-hot write). Chunk entries past the table's
        # logical extent (the speculative verify tail of a near-end row)
        # redirect to block 0 — the reserved garbage block
        # (tpudist.serve.blocks.GARBAGE_BLOCK); unmapped mid-table entries
        # are already 0 in the engine's tables.
        def write(n, pools):
            pk, pv = pools
            i, j = n // s, n % s
            p = pos[i] + j
            lb = p // bs_blk
            blk = jnp.where(lb < mb, bt[i, jnp.minimum(lb, mb - 1)], 0)
            start = (blk, 0, p % bs_blk, 0)
            sk = jax.lax.dynamic_slice_in_dim(kt[i], j, 1, axis=1)[None]
            sv = jax.lax.dynamic_slice_in_dim(vt[i], j, 1, axis=1)[None]
            pk = jax.lax.dynamic_update_slice(pk, sk, start)
            pv = jax.lax.dynamic_update_slice(pv, sv, start)
            return pk, pv

        pool_k, pool_v = jax.lax.fori_loop(0, b * s, write, (pool_k, pool_v))
        ck.value, cv.value = pool_k, pool_v
        return pool_k, pool_v, bt, pos
    if positions is not None:
        pos = jnp.asarray(positions, jnp.int32)
        if pos.shape != (b,):
            raise ValueError(f"positions must be [{b}], got {pos.shape}")
        if pre_update is not None:
            k, v = pre_update(k, v, pos)
        if initialized:
            # per-row write as a one-hot select (one per chunk entry), NOT
            # a gather-scatter (`.at[arange, :, pos, :].set`): XLA updates
            # the select in-place on the donated buffer and fuses it,
            # while the scatter blocks the in-place path and copies every
            # layer's full [B, H, max_len, dh] buffer — measured 24.6 vs
            # 8.9 ms per 4-layer step at the serving shapes on CPU. An
            # entry at pos + i >= max_len has an all-false one-hot: the
            # write self-clamps (nothing lands, nothing is clobbered).
            kt = k.transpose(0, 2, 1, 3)  # [B, H, s, dh]
            vt = v.transpose(0, 2, 1, 3)
            for i in range(s):
                onehot = (
                    jnp.arange(max_len)[None, :] == (pos + i)[:, None]
                )[:, None, :, None]  # [B, 1, max_len, 1]
                ck.value = jnp.where(onehot, kt[:, :, i : i + 1], ck.value)
                cv.value = jnp.where(onehot, vt[:, :, i : i + 1], cv.value)
        slots = jnp.arange(max_len)[None, None, None, :]
        # causal within the chunk, per-row: slot t attendable by row b's
        # chunk entry i iff t <= pos_b + i
        rows = pos[:, None, None, None] + jnp.arange(s)[None, None, :, None]
        mask = slots <= rows  # [B, 1, s, max_len]
        return ck.value, cv.value, mask, pos
    pos = ci.value
    if pre_update is not None:
        k, v = pre_update(k, v, pos)
    if initialized:
        kt = jnp.transpose(k, (0, 2, 1, 3))  # [B, H, s, dh]
        vt = jnp.transpose(v, (0, 2, 1, 3))
        ck.value = jax.lax.dynamic_update_slice(ck.value, kt, (0, 0, pos, 0))
        cv.value = jax.lax.dynamic_update_slice(cv.value, vt, (0, 0, pos, 0))
        ci.value = pos + s
    # slot t is attendable by step row i iff t <= pos + i (causal over the
    # buffer; unwritten slots are masked out entirely)
    slots = jnp.arange(max_len)[None, None, None, :]
    rows = pos + jnp.arange(s)[None, None, :, None]
    mask = slots <= rows
    return ck.value, cv.value, mask, pos


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *, sm_scale, h, ratio):
    """One grid step = one batch row: all ``h`` query heads against this
    row's whole cache block [H_kv, S, dh]; slots past the write position
    are masked. Scores and probs live only in VMEM/registers. The head
    loop is a fori_loop (one head's code compiled, per-head VMEM scratch
    reused — the grouping that kept the vmem attention kernel off the
    grid-overhead cliff applies doubly here, where per-head compute is a
    single [1, S] softmax)."""
    pos = pos_ref[0]

    def one(i, _):
        q = q_ref[i]  # [1, dh]
        k = k_ref[i // ratio]  # [S, dh]
        v = v_ref[i // ratio]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [1, S]
        kp = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kp <= pos, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[i] = (o / l).astype(o_ref.dtype)
        return 0

    jax.lax.fori_loop(0, h, one, 0)


def _fused_decode_attention(q, keys, values, pos):
    """q ``[B, 1, H, dh]`` (activation layout), keys/values
    ``[B, H_kv, S, dh]`` (the head-major cache buffers), ``pos`` scalar
    int32 → ``[B, 1, H, dh]``. GQA reads each K/V head once per query
    group straight from the grouped layout.

    Grid is (batch,): one step DMAs the row's whole [H_kv, S, dh] K/V
    (contiguous) and loops heads in-kernel. Measured against the
    per-(b, h) grid on v5e at GPT-2 124M shapes: 1536 tiny grid steps
    paid ~10 µs each at batch 128 (27.3 ms/step vs XLA's 18.0); one step
    per row with 12 in-kernel heads amortizes the grid overhead into
    DMA-sized work items.
    """
    b, s_q, h, dh = q.shape
    h_kv, s_len = keys.shape[1], keys.shape[2]
    if s_q != 1:
        raise NotImplementedError("fused decode attention is single-token")
    if b > FUSED_MAX_BATCH:
        raise NotImplementedError(
            f"batch {b} > {FUSED_MAX_BATCH}: above the measured crossover "
            "the dense path's batched GEMMs win — dispatcher falls back"
        )
    if h % h_kv:
        raise NotImplementedError(f"q heads {h} not a multiple of kv {h_kv}")
    ratio = h // h_kv
    sm_scale = 1.0 / float(np.sqrt(dh))
    # [B,1,H,dh] -> [B,H,1,dh] moves a singleton: a free reshape, no copy
    qt = q.reshape(b, h, 1, dh)
    # None squeezes the batch dim out of the kernel refs, so the blocks
    # keep their trailing [.., S|1, dh] dims whole — Mosaic-tileable
    q_spec = pl.BlockSpec((None, h, 1, dh), lambda b, *_: (b, 0, 0, 0))
    kv_spec = pl.BlockSpec((None, h_kv, s_len, dh), lambda b, *_: (b, 0, 0, 0))
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, sm_scale=sm_scale, h=h, ratio=ratio
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b,),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
        ),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=jax.default_backend() != "tpu",
    )(jnp.asarray(pos, jnp.int32).reshape(1), qt, keys, values)
    return out.reshape(b, s_q, h, dh)


def decode_attention(q, keys, values, mask, pos, *, impl: str = "fused",
                     bias=None, scale=None):
    """Single-token attention over the cache buffers from :func:`cached_kv`
    (``q`` in activation layout ``[B, s, H, dh]``, keys/values head-major
    ``[B, H_kv, max_len, dh]``).

    ``impl="fused"`` runs the one-launch Pallas kernel (falling back to the
    dense path when its constraints don't hold — multi-token chunks,
    ragged head ratios, K/V panels past the VMEM bound); ``impl="xla"``
    is the dense oracle the fused kernel is tested against. Both implement
    the same function: attention over slots ``<= pos`` (+ row offset for
    multi-token chunks, via ``mask``).

    ``bias``: optional additive score bias broadcastable to
    ``[B, H, s, max_len]`` (T5's relative position bias) — dense path
    only (the fused kernel takes none). ``scale`` overrides the default
    ``1/sqrt(dh)`` (T5 uses 1.0).
    """
    # explicit applicability predicate, not try/except NotImplementedError:
    # Pallas itself raises NotImplementedError for unsupported op/platform
    # combinations, and swallowing those would silently run the dense path
    # while the bench/docs claim the fused kernel. The VMEM bound: one
    # grid step stages a row's whole [H_kv, S, dh] K and V panels (double-
    # buffered by the pipeline), so large-cache geometries (e.g. h_kv=8,
    # S=8192, dh=128 bf16 = 32 MB K+V) must take the dense path instead
    # of failing Mosaic's VMEM check at compile time.
    kv_panel_bytes = (
        2 * keys.shape[1] * keys.shape[2] * keys.shape[3] * keys.dtype.itemsize
    )
    fused_ok = (
        bias is None
        and scale is None
        and q.shape[1] == 1
        and q.shape[0] <= FUSED_MAX_BATCH
        and q.shape[2] % keys.shape[1] == 0
        and kv_panel_bytes <= 6 * 1024 * 1024  # ×2 pipeline buffers ≤ ~12 MB
        # per-row positions (slot-pooled decode, tpudist.serve) take the
        # dense path: the kernel prefetches ONE scalar write cursor, and
        # the serving batch sits above the fused crossover anyway
        and jnp.ndim(pos) == 0
    )
    if impl == "fused" and fused_ok:
        return _fused_decode_attention(q, keys, values, pos)
    if keys.shape[1] != q.shape[2]:
        from tpudist.ops.attention import repeat_kv

        # head_axis=1: the cache is head-major (one home for the ratio math)
        keys, values = repeat_kv(q, keys, values, head_axis=1)
    # dense oracle over the head-major cache: f32 scores, slot mask, softmax
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    logits = jnp.einsum(
        "bqhd,bhkd->bhqk", q, keys, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bqhd", probs, values)


def _paged_decode_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, bs, h, ratio, sm_scale):
    """One grid step = (batch row b, logical block j): online-softmax
    accumulation over the row's block-table walk. The k/v BlockSpec index
    map already resolved logical j to the row's PHYSICAL pool block (and
    clamped past-the-cursor j to the last needed block, so trailing grid
    steps re-map the same block and the pipeline issues NO new DMA for
    them — the bytes read per row are ceil((pos+1)/bs) blocks, not
    max_blocks). Scratch (m, l, acc) persists across j within a row; the
    normalized output is (re)written at every valid j, so the last valid
    block leaves the final answer in the revisited output block."""
    b_i = pl.program_id(0)
    j = pl.program_id(1)
    s_q = q_ref.shape[1]
    pos = pos_ref[b_i]
    # the chunk's LAST query row (pos + s_q - 1) bounds the block walk —
    # for the single-token case this is the old pos // bs
    last = (pos + s_q - 1) // bs

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j <= last)
    def _block():
        def one(i, _):
            q = q_ref[i]  # [s_q, dh]
            k = k_ref[i // ratio]  # [bs, dh]
            v = v_ref[i // ratio]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale  # [s_q, bs]
            kp = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * bs
            # causal within the chunk: query row r attends slots <= pos + r
            rq = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(kp <= pos + rq, s, NEG_INF)
            m_prev = m_ref[i]  # [s_q]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            l_new = alpha * l_ref[i] + jnp.sum(p, axis=-1)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [s_q, dh]
            acc_new = alpha[:, None] * acc_ref[i] + pv
            m_ref[i], l_ref[i], acc_ref[i] = m_new, l_new, acc_new
            # block 0 has at least one unmasked slot for EVERY query row
            # (slot 0 <= pos + r always), so after the j=0 step l > 0 for
            # all rows — no guard needed. Later blocks fully masked for an
            # early row contribute exp(NEG_INF - m) = 0 and leave its
            # running stats unchanged.
            o_ref[i] = (acc_new / l_new[:, None]).astype(o_ref.dtype)
            return 0

        jax.lax.fori_loop(0, h, one, 0)


def _paged_fused_attention(q, k_pool, v_pool, block_tables, positions):
    """q ``[B, 1, H, dh]``, pools ``[n_blocks, H_kv, bs, dh]``,
    ``block_tables [B, max_blocks]``, ``positions [B]`` → ``[B, 1, H, dh]``.

    Grid is (batch, max_blocks) with the block table and positions as
    scalar prefetch: the k/v index map reads the row's table to DMA the
    right PHYSICAL block per logical step, clamping logical blocks past
    the row's cursor to its last needed block — Pallas skips the DMA when
    a revisited index maps the same block, so a row at length L reads
    ceil((L+1)/bs) blocks and the kernel's HBM traffic is Σ(actual
    lengths), the byte roofline the paged layout buys (vs the dense
    path's B × max_len gather). Per-block online softmax in VMEM scratch;
    heads loop in-kernel (the grouping that keeps grid steps DMA-sized,
    same as the contiguous fused kernel); GQA reads each K/V head once
    per query group from the grouped pool layout."""
    b, s_q, h, dh = q.shape
    h_kv, bs = k_pool.shape[1], k_pool.shape[2]
    mb = block_tables.shape[1]
    if h % h_kv:
        raise NotImplementedError(f"q heads {h} not a multiple of kv {h_kv}")
    ratio = h // h_kv
    sm_scale = 1.0 / float(np.sqrt(dh))
    # head-major for the kernel; s_q == 1 makes this a free reshape
    qt = q.reshape(b, h, 1, dh) if s_q == 1 else q.transpose(0, 2, 1, 3)

    def kv_map(b_i, j, bt, pos):
        # clamp to the chunk's last needed block AND the table's extent
        # (a verify chunk's tail past the mapped window re-walks the last
        # block; its slots are masked in-kernel)
        jc = jnp.minimum(j, (pos[b_i] + s_q - 1) // bs)
        return (bt[b_i, jnp.minimum(jc, mb - 1)], 0, 0, 0)

    q_spec = pl.BlockSpec((None, h, s_q, dh), lambda b_i, j, *_: (b_i, 0, 0, 0))
    kv_spec = pl.BlockSpec((None, h_kv, bs, dh), kv_map)
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel, bs=bs, h=h, ratio=ratio, sm_scale=sm_scale
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, mb),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((h, s_q), jnp.float32),   # running max
                pltpu.VMEM((h, s_q), jnp.float32),   # running denominator
                pltpu.VMEM((h, s_q, dh), jnp.float32),  # running numerator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=jax.default_backend() != "tpu",
    )(
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(positions, jnp.int32),
        qt, k_pool, v_pool,
    )
    return out.reshape(b, s_q, h, dh) if s_q == 1 else out.transpose(0, 2, 1, 3)


def paged_decode_attention(q, k_pool, v_pool, block_tables, positions, *,
                           impl: str = "paged", mesh=None):
    """Attention over the PAGED pool from :func:`cached_kv`'s block-table
    mode (``q [B, s, H, dh]`` activation layout, pools head-major
    ``[n_blocks, H_kv, block_size, dh]``). ``s == 1`` is the sampling
    step; ``s > 1`` is the speculative-decoding verify chunk — causal
    within the chunk (query row ``r`` attends logical slots
    ``<= pos + r``), the multi-row twin of the contiguous per-row mask.

    ``impl="paged"`` runs the one-launch-per-layer Pallas kernel
    (:func:`_paged_fused_attention`): unlike the contiguous fused kernel
    it has NO upper batch bound — at serving batch the dense alternative
    must GATHER every row's max_blocks × block_size window into a
    contiguous buffer first (B × max_len bytes through HBM), while the
    kernel walks each row's table and reads only blocks up to the cursor,
    which is what converts the paged layout's saved bytes into tok/s
    (docs/PERF.md §7c measures the A/B). ``impl="xla"`` is the
    gather-then-dense oracle the kernel is tested against (and the
    correctness path on models pinned to ``attn_impl="xla"``).

    ``mesh``: pass the serving mesh on a multi-chip tensor-sharded engine
    (``tpudist.serve.engine.ServeEngine(mesh=...)``). ``pallas_call`` has
    no GSPMD partitioning rule, so on a >1-device ``tensor`` axis the
    kernel runs per-shard inside ``shard_map``: q splits on its head dim,
    the pools on their KV-head dim (the engine shards the block pool
    ``[n_blocks, H_kv/T, block_size, dh]`` per chip), block tables and
    positions stay replicated. Softmax is complete per head, so the wrap
    is exact with no collective — each chip walks the SAME block tables
    over its own head slice of the pool. The dense oracle path needs no
    wrap (gather + einsums partition under plain GSPMD)."""
    paged_ok = (
        q.shape[2] % k_pool.shape[1] == 0
        # one block's K+V panel stays far under VMEM at any sane
        # block_size; no panel bound needed (the whole point: the DMA
        # unit is a block, not a row's full window)
    )
    if impl == "paged" and paged_ok:
        if mesh is not None:
            from tpudist import mesh as mesh_lib

            tp = int(mesh.shape[mesh_lib.TENSOR_AXIS]) \
                if mesh_lib.TENSOR_AXIS in mesh.axis_names else 1
            h, h_kv = q.shape[2], k_pool.shape[1]
            if tp > 1 and h % tp == 0 and h_kv % tp == 0:
                from jax.sharding import PartitionSpec as P

                from tpudist.utils.compat import shard_map

                fn = shard_map(
                    _paged_fused_attention,
                    mesh=mesh,
                    in_specs=(
                        P(None, None, mesh_lib.TENSOR_AXIS, None),  # q heads
                        P(None, mesh_lib.TENSOR_AXIS, None, None),  # k pool
                        P(None, mesh_lib.TENSOR_AXIS, None, None),  # v pool
                        P(None, None),  # block tables: replicated
                        P(None),        # positions: replicated
                    ),
                    out_specs=P(None, None, mesh_lib.TENSOR_AXIS, None),
                    # pallas_call can't declare varying-manual-axes on its
                    # out_shape (same caveat as ops/attention.py's wrap)
                    check_vma=False,
                )
                return fn(q, k_pool, v_pool,
                          jnp.asarray(block_tables, jnp.int32),
                          jnp.asarray(positions, jnp.int32))
        return _paged_fused_attention(q, k_pool, v_pool, block_tables,
                                      positions)
    # dense oracle: gather each row's table into a contiguous window and
    # reuse the contiguous dense path (per-row causal-within-chunk mask
    # over logical slots <= pos + row)
    b, s_q = q.shape[0], q.shape[1]
    h_kv, bs = k_pool.shape[1], k_pool.shape[2]
    mb = block_tables.shape[1]
    bt = jnp.asarray(block_tables, jnp.int32)
    pos = jnp.asarray(positions, jnp.int32)
    keys = k_pool[bt].transpose(0, 2, 1, 3, 4).reshape(b, h_kv, mb * bs, -1)
    values = v_pool[bt].transpose(0, 2, 1, 3, 4).reshape(b, h_kv, mb * bs, -1)
    slots = jnp.arange(mb * bs)[None, None, None, :]
    rows = pos[:, None, None, None] + jnp.arange(s_q)[None, None, :, None]
    mask = slots <= rows  # [B, 1, s_q, mb*bs]
    return decode_attention(q, keys, values, mask, pos, impl="xla")
