"""Whole-sequence-in-VMEM attention: the short/medium-context Pallas kernel.

No reference counterpart (the reference's workload is a CNN,
/root/reference/main.py:40) — this is the framework's hot-op for the
transformer configs at bench sequence lengths (GPT-2 S=1024, ViT S=197).

Why a third attention path exists
---------------------------------
- XLA einsum attention materializes the [S,S] f32 score tensor in HBM per
  layer per direction — the dominant byte term of the GPT-2 step
  (docs/PERF.md §4) and of ViT (§6).
- The blockwise flash kernel (``tpudist.ops.flash_attention``) eliminates
  that traffic, but pays online-softmax bookkeeping per (128,128) tile and
  a recompute-heavy backward; on v5e it only wins from S≈2048.
- At S ≤ 1024 an ENTIRE head's score matrix fits in VMEM (S=1024 → 4 MB
  f32 of ~16 MB), so this kernel runs one (batch, head) pair per grid
  step: ONE q·kᵀ MXU call, one plain (not online) softmax on the VPU, one
  p·v MXU call — scores never touch HBM and there is no per-tile loop
  overhead. Measured fwd+bwd at GPT-2 shapes (B=8, H=12, S=1024, D=64,
  bf16, interleaved repeats on one v5e): **4.2 ms vs 9.5 ms XLA** vs
  10.8/13.4 ms for the blockwise flash variants.
- The backward is a single kernel per (b, h): recompute p from the saved
  row log-sum-exp, then the four FA-2 matmuls (dv, dp, dq, dk) back to
  back on MXU with everything resident in VMEM.

Ragged / padded sequences
-------------------------
TPU tiles want 128-aligned lanes, but callers have S=197 (ViT's 196+cls).
:func:`vmem_attention` pads q/k/v up to the next 128 multiple and masks the
padded KEYS inside the kernel (``kv_len`` — one iota compare per score
tile); padded QUERY rows compute garbage that is sliced off on return.
This is what makes the kernel applicable to ViT, where the S² f32 traffic
was previously "structural" (docs/PERF.md §6).

Sizing rule: the kernel refuses S_pad > MAX_SEQ (per-(b,h) VMEM footprint
is a handful of [S,S] f32 buffers); longer sequences belong to the
blockwise flash kernel. ``tpudist.ops.attention.multi_head_attention``
routes ``impl="auto"`` accordingly.

Numerics: scores/softmax in f32 regardless of input dtype; p/ds cast to
the input dtype for the backward MXU calls (the FA-2 convention). Matches
``dot_product_attention`` to ~1e-2 in bf16, ~1e-5 in f32 (interpret mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (VMEM scratch if needed)

NEG_INF = float(np.finfo(np.float32).min)

# per-(b,h) VMEM budget: bwd keeps ~4 [S,S] f32/bf16 intermediates live;
# S=1024 → ~14 MB of ~16 MB works (measured); S=2048 would need 4×.
MAX_SEQ = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _masked_scores(q, k, sm_scale, *, causal, kv_len):
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    s_q, s_k = s.shape
    need_kv_mask = kv_len is not None and kv_len < s_k
    if causal or need_kv_mask:
        kp = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        keep = jnp.ones(s.shape, bool)
        if causal:
            qp = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
            keep = qp >= kp
        if need_kv_mask:
            keep &= kp < kv_len
        s = jnp.where(keep, s, NEG_INF)
    return s


def _loop_heads(group: int, body):
    """Run ``body(i)`` for the block's ``group`` heads. group==1 stays
    straight-line; grouped blocks use fori_loop (compiles one head's code,
    reuses the per-head VMEM scratch across iterations — measured within 2%
    of a full unroll at ViT shapes, far cheaper to compile)."""
    if group == 1:
        body(0)
    else:
        jax.lax.fori_loop(0, group, lambda i, _: (body(i), 0)[1], 0)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                *, sm_scale, causal, kv_len, group, kv_shared):
    def one(i):
        q = q_ref[0, i]  # [Sq, D]
        # GQA (kv_shared): the whole q-head block reads ONE resident K/V
        # head — grouped K/V never get repeated in HBM
        k = k_ref[0, 0] if kv_shared else k_ref[0, i]  # [Sk, D]
        v = v_ref[0, 0] if kv_shared else v_ref[0, i]
        s = _masked_scores(q, k, sm_scale, causal=causal, kv_len=kv_len)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, i] = (o / l).astype(o_ref.dtype)
        lse_ref[0, i] = m + jnp.log(l)

    _loop_heads(group, one)


def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                dq_ref, dk_ref, dv_ref,
                *, sm_scale, causal, kv_len, group, kv_shared, ratio):
    if kv_shared:
        # GQA: the ratio consecutive grid steps mapping to one K/V head
        # revisit the SAME dk/dv output block (Pallas keeps a revisited
        # block resident between consecutive steps); zero it on the first
        # visiting step, accumulate on the rest
        hg = pl.program_id(1)
        first_visit = (hg * group) % ratio == 0

        @pl.when(first_visit)
        def _init():
            dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
            dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    def one(i):
        q = q_ref[0, i]
        k = k_ref[0, 0] if kv_shared else k_ref[0, i]
        v = v_ref[0, 0] if kv_shared else v_ref[0, i]
        o = o_ref[0, i].astype(jnp.float32)
        do = do_ref[0, i].astype(jnp.float32)
        lse = lse_ref[0, i]  # [Sq, 1] f32
        s = _masked_scores(q, k, sm_scale, causal=causal, kv_len=kv_len)
        p = jnp.exp(s - lse)  # [Sq, Sk] f32; exact probs (no rescale needed)
        pb = p.astype(v.dtype)
        dob = do.astype(v.dtype)
        dv = jax.lax.dot_general(
            pb, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = jnp.sum(do * o, axis=-1, keepdims=True)  # [Sq, 1]
        ds = (p * (dp - delta) * sm_scale).astype(v.dtype)
        dq_ref[0, i] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dq_ref.dtype)
        dk = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        if kv_shared:
            # every q-head in the block feeds the one K/V head's grads
            dv_ref[0, 0] += dv.astype(dv_ref.dtype)
            dk_ref[0, 0] += dk.astype(dk_ref.dtype)
        else:
            dv_ref[0, i] = dv.astype(dv_ref.dtype)
            dk_ref[0, i] = dk.astype(dk_ref.dtype)

    _loop_heads(group, one)


def _head_group(h: int, s_pad: int) -> int:
    """Heads per grid step. Small-S shapes (ViT's 256) are overhead-bound
    at one (b, h) pair per step — 1536 near-empty grid steps for ViT-B —
    so group as many heads as the VMEM budget allows (the per-head score
    scratch is reused across the in-kernel loop; only the IO blocks scale
    with the group). Measured at ViT shapes on v5e: 5.0 ms grouped vs
    5.8 ms ungrouped vs 7.0 ms XLA (fwd+bwd). Long S keeps group=1 — the
    per-step work is already large and the [S,S] scratch leaves no room."""
    if s_pad > 512:
        return 1
    for cand in range(h, 0, -1):
        if h % cand == 0 and cand * s_pad <= 3072:
            return cand
    return 1


def _struct(shape, dtype, like):
    """``ShapeDtypeStruct`` carrying ``like``'s varying-manual-axes type:
    inside a partial-manual ``shard_map`` (e.g. the GPipe schedule's
    pipe-manual region, tpudist.parallel.pp) every pallas output must
    declare how it varies over the manual axes or the shard_map's vma
    check rejects the call."""
    # old jax has neither jax.typeof nor vma-typed avals — there the plain
    # struct is always right (no vma check exists to reject it)
    vma = (
        getattr(jax.typeof(like), "vma", None)
        if hasattr(jax, "typeof") else None
    )
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _spec(g, s, d):
    return pl.BlockSpec((1, g, s, d), lambda b, hg: (b, hg, 0, 0))


def _geometry(q, k):
    """(group, ratio, kv_shared, kv_spec) for the grid. MHA: K/V blocks
    mirror the q-head grouping. GQA (fewer K/V heads): each grid step's
    q-head block reads its ONE K/V head — the group is clamped to divide
    the q-per-kv ratio so a block never spans two K/V heads, and the K/V
    BlockSpec maps grid step hg to kv head (hg·g)/ratio."""
    import math

    b, h, s_q, d = q.shape
    h_kv, s_k = k.shape[1], k.shape[2]
    if h % h_kv:
        raise NotImplementedError(
            f"q heads {h} not a multiple of kv heads {h_kv}"
        )
    ratio = h // h_kv
    g = _head_group(h, max(s_q, s_k))
    if ratio > 1:
        g = math.gcd(g, ratio)
        kv_spec = pl.BlockSpec(
            (1, 1, s_k, d),
            lambda b, hg, _g=g, _r=ratio: (b, (hg * _g) // _r, 0, 0),
        )
        return g, ratio, True, kv_spec
    return g, 1, False, _spec(g, s_k, d)


def _vmem_fwd_raw(q, k, v, *, causal, sm_scale, kv_len):
    b, h, s_q, d = q.shape
    g, ratio, kv_shared, kv_spec = _geometry(q, k)
    kern = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, kv_len=kv_len,
        group=g, kv_shared=kv_shared,
    )
    return pl.pallas_call(
        kern,
        grid=(b, h // g),
        in_specs=[_spec(g, s_q, d), kv_spec, kv_spec],
        out_specs=[_spec(g, s_q, d), _spec(g, s_q, 1)],
        out_shape=[
            _struct(q.shape, q.dtype, q),
            _struct((b, h, s_q, 1), jnp.float32, q),
        ],
        interpret=_interpret(),
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _vmem(q, k, v, causal, sm_scale, kv_len):
    o, _ = _vmem_fwd_raw(q, k, v, causal=causal, sm_scale=sm_scale, kv_len=kv_len)
    return o


def _vmem_vjp_fwd(q, k, v, causal, sm_scale, kv_len):
    o, lse = _vmem_fwd_raw(q, k, v, causal=causal, sm_scale=sm_scale, kv_len=kv_len)
    return o, (q, k, v, o, lse)


def _vmem_vjp_bwd(causal, sm_scale, kv_len, res, g):
    q, k, v, o, lse = res
    b, h, s_q, d = q.shape
    grp, ratio, kv_shared, kv_spec = _geometry(q, k)
    kern = functools.partial(
        _bwd_kernel, sm_scale=sm_scale, causal=causal, kv_len=kv_len,
        group=grp, kv_shared=kv_shared, ratio=ratio,
    )
    # GQA: dk/dv accumulate ratio/grp revisits (plus grp in-block q-heads)
    # into the same output block — accumulate in f32, cast after
    kv_grad_dtype = jnp.float32 if kv_shared else k.dtype
    dq, dk, dv = pl.pallas_call(
        kern,
        grid=(b, h // grp),
        in_specs=[_spec(grp, s_q, d), kv_spec, kv_spec,
                  _spec(grp, s_q, d), _spec(grp, s_q, d), _spec(grp, s_q, 1)],
        out_specs=[_spec(grp, s_q, d), kv_spec, kv_spec],
        out_shape=[
            _struct(q.shape, q.dtype, q),
            _struct(k.shape, kv_grad_dtype, k),
            _struct(v.shape, kv_grad_dtype, v),
        ],
        interpret=_interpret(),
    )(q, k, v, o, g, lse)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


_vmem.defvjp(_vmem_vjp_fwd, _vmem_vjp_bwd)


def vmem_attention(q, k, v, *, causal: bool = False, kv_len: int | None = None):
    """Attention on [B, S, H, D] inputs (the models' layout, matching
    :func:`tpudist.ops.attention.dot_product_attention`).

    Unaligned S is padded to the next 128 multiple: padded keys are masked
    inside the kernel (``kv_len``), padded query rows are sliced off the
    output. ``kv_len`` may also be passed explicitly for right-padded
    batches whose true key length is shorter than S (every sequence in the
    batch shares it — a static int, not a per-row tensor).

    GQA: ``k``/``v`` may carry fewer heads than ``q`` (heads divisible).
    The kernel reads each K/V head once per query group straight from the
    grouped layout — no ``jnp.repeat`` materializes in HBM — and the
    backward accumulates the group's dk/dv in f32.

    Raises NotImplementedError for S_pad > MAX_SEQ (VMEM budget) — callers
    (``multi_head_attention(impl="auto")``) route long sequences to the
    blockwise flash kernel instead.
    """
    if q.ndim != 4:
        raise NotImplementedError(f"expected [B,S,H,D], got {q.shape}")
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if kv_len is None:
        kv_len = s_k
    pad_q = -s_q % 128
    pad_k = -s_k % 128
    if s_q + pad_q > MAX_SEQ or s_k + pad_k > MAX_SEQ:
        raise NotImplementedError(
            f"vmem attention holds whole [S,S] scores in VMEM; S_pad="
            f"{max(s_q + pad_q, s_k + pad_k)} > {MAX_SEQ} — use the "
            "blockwise flash kernel for long sequences"
        )
    if causal and s_q != s_k:
        raise NotImplementedError("causal path assumes s_q == s_k")
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    sm_scale = 1.0 / float(np.sqrt(d))
    # [B,S,H,D] → [B,H,S,D] for contiguous per-(b,h) tiles
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _vmem(qt, kt, vt, causal, sm_scale, kv_len)
    return o.transpose(0, 2, 1, 3)[:, :s_q]
