"""Attention ops for the transformer models (ViT, GPT-2, Llama, BERT).

The reference contains no attention (its workload is a CNN, SURVEY.md §5
"long-context: ABSENT") — these ops serve the BASELINE ladder's transformer
configs (ViT-B/16, GPT-2 124M). Three paths, dispatched by
:func:`multi_head_attention` (``impl="auto"`` picks by measured crossover):

- ``dot_product_attention``: plain XLA einsum attention — the correctness
  oracle, and the only path that takes arbitrary masks.
- ``tpudist.ops.vmem_attention``: whole-sequence-in-VMEM Pallas kernel for
  S ≤ 1024 — one plain softmax per (batch, head) grid step, no tile loop;
  the fastest path at bench shapes (2.3× over XLA on the GPT-2 step).
- ``tpudist.ops.flash_attention``: blockwise FA-2 Pallas kernel for long
  sequences (≥ 2048) — online softmax so the S×S scores never exist.

Both kernels pad ragged S to the 128-tile multiple and mask padded keys
in-kernel (``kv_len``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def repeat_kv(q, k, v, *, head_axis: int = 2):
    """Broadcast grouped K/V heads over their query groups ([.., H_kv, D] →
    [.., H, D]) — the GQA normalization for attention paths that need equal
    head counts. XLA fuses the repeat into the attention matmuls. One home
    for the ratio math: callers must not hand-roll the repeat.

    ``head_axis``: where K/V carry their head dim — 2 for the models'
    ``[B, S, H, D]`` activation layout (default), 1 for the decode cache's
    head-major ``[B, H, S, D]`` (q stays ``[B, s, H, D]`` either way)."""
    h, h_kv = q.shape[2], k.shape[head_axis]
    if h % h_kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {h_kv}")
    rep = h // h_kv
    if rep == 1:
        return k, v
    return (
        jnp.repeat(k, rep, axis=head_axis),
        jnp.repeat(v, rep, axis=head_axis),
    )


def kernel_attention(q, k, v, *, causal: bool = False):
    """Best fused-kernel attention for the shape — the ``attn_fn`` to hand
    composition sites (e.g. the Ulysses shard_map body, which sees the FULL
    sequence with a local head group after its all-to-all): vmem kernel at
    S ≤ 1024, blockwise flash at ≥ 2048, dense XLA between (the measured
    v5e crossovers)."""
    return multi_head_attention(q, k, v, causal=causal, impl="auto")


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          bias=None, scale=None):
    """q,k,v: [B, S, H, D] (batch, seq, heads, head_dim) → [B, S, H, D].

    ``bias``: optional additive score bias broadcastable to
    ``[B, H, Sq, Sk]`` (T5's relative position bias). ``scale`` overrides
    the default ``1/sqrt(D)`` (T5 uses 1.0 — the scale is folded into its
    init)."""
    dtype = q.dtype
    depth = q.shape[-1]
    if scale is None:
        scale = 1.0 / np.sqrt(depth).astype(np.float32)
    # compute scores in float32 for stability, cast back at the end
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool))
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def multi_head_attention(q, k, v, *, causal: bool = False, mask=None,
                         impl: str = "xla", kv_len: int | None = None,
                         mesh=None):
    """Dispatch over the three attention paths:

    - ``xla``: dense einsum attention (oracle; takes arbitrary masks);
    - ``vmem``: whole-sequence-in-VMEM Pallas kernel — fastest at S ≤ 1024
      (measured 2.3× over xla at GPT-2 shapes on v5e) and the only kernel
      that handles unaligned S (ViT's 197) by padding + in-kernel key mask;
    - ``flash``: blockwise FA-2 Pallas kernel for long sequences (S ≥ 2048,
      where whole-S scores no longer fit VMEM);
    - ``auto``: vmem when it applies, else xla below the measured flash
      crossover (~2048 on v5e), else flash.

    ``kv_len``: static true key length for contiguous right-padded K/V —
    the kernels mask padded keys in-kernel; the dense path builds the
    equivalent iota mask. Mutually exclusive with ``mask``.

    ``mesh``: pass the model's mesh on MULTI-CHIP data-parallel runs that
    want a Pallas kernel. ``pallas_call`` has no GSPMD partitioning rule,
    so on a >1-device data axis the kernel must run per-shard inside
    ``shard_map`` (attention is batch-parallel — the wrap is exact); with
    ``mesh=None`` the kernels still partition correctly under pure
    single-chip-per-process DP (one shard per program) and on the CPU
    interpret path (decomposed into partitionable jax ops).
    """
    if mask is not None and kv_len is not None:
        raise ValueError("pass mask or kv_len, not both")
    if mesh is not None and impl in ("vmem", "flash", "auto") and mask is None:
        from tpudist import mesh as mesh_lib

        dp = int(np.prod([
            mesh.shape[a] for a in (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
        ]))
        tp = mesh.shape[mesh_lib.TENSOR_AXIS]
        # indivisible shapes (e.g. the batch-1 init trace) fall through to
        # the unwrapped path — negligible work there, and shard_map would
        # refuse; a REAL training shape falling through on a multi-device
        # mesh is a misconfiguration worth a loud warning
        divisible = (
            q.shape[0] % dp == 0
            and q.shape[2] % tp == 0
            and k.shape[2] % tp == 0
        )
        multi = dp > 1 or tp > 1
        if multi and not divisible and q.shape[0] > 1:
            import warnings

            warnings.warn(
                f"pallas attention on a {dp}x dp / {tp}x tp mesh with "
                f"shapes (batch {q.shape[0]}, q heads {q.shape[2]}, kv "
                f"heads {k.shape[2]}) not divisible by the mesh axes: "
                "running UNWRAPPED (GSPMD cannot partition pallas_call — "
                "expect gathers/replication); adjust batch/head counts"
            )
        if multi and divisible:
            from tpudist.utils.compat import shard_map
            from jax.sharding import PartitionSpec as P

            # batch over data/fsdp, heads over tensor (Megatron TP keeps
            # qkv head-sharded) — attention is parallel over both, so the
            # per-shard kernel is exact with no collective
            spec = P((mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS), None,
                     mesh_lib.TENSOR_AXIS, None)
            fn = shard_map(
                lambda q, k, v: multi_head_attention(
                    q, k, v, causal=causal, impl=impl, kv_len=kv_len
                ),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                # pallas_call can't declare varying-manual-axes on its
                # out_shape (same caveat as parallel/cp.py)
                check_vma=False,
            )
            return fn(q, k, v)
    if impl in ("vmem", "auto"):
        if mask is None:
            try:
                from tpudist.ops.vmem_attention import vmem_attention

                return vmem_attention(q, k, v, causal=causal, kv_len=kv_len)
            except NotImplementedError as e:
                if impl == "vmem":
                    import warnings

                    warnings.warn(
                        f"vmem attention unavailable ({e}); trying flash/XLA"
                    )
            # measured crossover on v5e: between the vmem ceiling (1024) and
            # ~2048 the dense XLA path still beats the blockwise flash
            # kernel; from 2048 the S² HBM traffic dominates and flash wins
            impl = "flash" if max(q.shape[1], k.shape[1]) >= 2048 else "xla"
        elif impl == "vmem":
            import warnings

            warnings.warn(
                "vmem attention takes no general mask (pass kv_len for "
                "contiguous key padding); using XLA attention"
            )
            impl = "xla"
        else:
            impl = "xla"  # auto + general mask → dense path
    if k.shape[2] != q.shape[2]:
        # GQA reaching the dense/flash paths (vmem handles grouped K/V
        # natively)
        k, v = repeat_kv(q, k, v)
    if impl == "flash":
        if mask is not None:
            # no silent fallback: the caller picked flash to keep the S×S
            # scores out of HBM, and a general mask forces the dense path
            import warnings

            warnings.warn(
                "flash attention takes no general mask; falling back to XLA "
                "attention (S×S scores in HBM) — for contiguous key padding "
                "use kv_len instead"
            )
        else:
            try:
                from tpudist.ops.flash_attention import flash_attention

                return flash_attention(q, k, v, causal=causal, kv_len=kv_len)
            except (ImportError, NotImplementedError) as e:
                import warnings

                warnings.warn(f"flash attention unavailable ({e}); using XLA attention")
    if kv_len is not None and kv_len < k.shape[1]:
        # dense path: materialize the contiguous-padding key mask
        mask = (jnp.arange(k.shape[1]) < kv_len)[None, None, None, :]
    return dot_product_attention(q, k, v, causal=causal, mask=mask)
