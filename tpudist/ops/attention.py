"""Attention ops for the transformer models (ViT, GPT-2).

The reference contains no attention (its workload is a CNN, SURVEY.md §5
"long-context: ABSENT") — these ops serve the BASELINE ladder's transformer
configs (ViT-B/16, GPT-2 124M). Two paths:

- ``dot_product_attention``: plain XLA einsum attention. XLA fuses
  softmax+matmul well on TPU; this is the default and the correctness oracle.
- a Pallas flash-attention kernel (``tpudist.ops.flash_attention``) for long
  sequences, selected with ``impl="flash"`` — blockwise online-softmax so the
  S×S score matrix never materializes in HBM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None):
    """q,k,v: [B, S, H, D] (batch, seq, heads, head_dim) → [B, S, H, D]."""
    dtype = q.dtype
    depth = q.shape[-1]
    scale = 1.0 / np.sqrt(depth).astype(np.float32)
    # compute scores in float32 for stability, cast back at the end
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool))
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def multi_head_attention(q, k, v, *, causal: bool = False, mask=None, impl: str = "xla"):
    if impl == "flash":
        if mask is not None:
            # no silent fallback: the caller picked flash to keep the S×S
            # scores out of HBM, and a general mask forces the dense path
            import warnings

            warnings.warn(
                "flash attention takes no general mask; falling back to XLA "
                "attention (S×S scores in HBM) — for contiguous key padding "
                "use the kernel's kv_len instead"
            )
        else:
            try:
                from tpudist.ops.flash_attention import flash_attention

                return flash_attention(q, k, v, causal=causal)
            except (ImportError, NotImplementedError) as e:
                import warnings

                warnings.warn(f"flash attention unavailable ({e}); using XLA attention")
    return dot_product_attention(q, k, v, causal=causal, mask=mask)
