"""Elastic restart: resume a checkpoint on a DIFFERENT world size.

PR 8 made preemption survivable but world-size-bound: the geometry guard
in ``fit()`` refuses any checkpoint whose recorded world disagrees with
the live mesh, because two pieces of train state really are laid out per
world (docs/MULTIHOST.md): ZeRO-1 stores pad-and-reshape ``[world, cols]``
optimizer leaves (``tpudist.optim.shard_state``), and the quantized
reducer's error-feedback residual is ``[world, n_buckets, bucket]``
(``tpudist.parallel.dp``). On a preempted pod the hardware that comes
back is frequently NOT the hardware that left — resuming on whatever is
left is the difference between a bounded incident and a dead run.

This module turns the hard refusal into a *validated reshard*
(``fit(elastic=True)`` → ``Checkpointer.restore(reshard=True)``):

- **validation** (:func:`refusal_reason`): the saved and live geometry may
  differ ONLY in world-shaped keys (``world_size``, ``data_world``,
  ``steps_per_epoch``, ``batch_size``, ``grad_accum``); semantic keys
  (``reduce`` method, ``shard_opt_state``) must match — a quantized
  checkpoint resumed unquantized is a different run, not a resize.
- **ZeRO-1 reshard** (:func:`reshard_restore`): every leaf whose saved
  shape disagrees with the live state's is a stored-layout leaf. The
  transform is pure layout algebra — flatten, copy the logical prefix,
  re-pad with zeros, reshape to the new stored shape — exact because
  ``shard_state``'s pad regions are zeros by construction (``_store``
  re-zeroes them every step). Leaves whose ZeRO-1 *classification*
  changes across worlds (pad at 8, naturally-divisible shard at 4) fall
  out of the same math: the saved flat prefix IS the logical leaf.
- **residual flush**: the error-feedback residual is per-replica
  quantization error — world-bound by construction, not relayoutable.
  It restarts as zeros (the attached residual of the new state), which
  the EF math treats as a flushed bank: one step of uncompensated
  quantization noise, the same cost as the scheduled flush the
  double-buffered path already pays every step. The one-shot telemetry
  ``reshard`` row records the flush.
- **sampler-cursor remap** (:func:`remap_step`): ``state.step`` counts
  optimizer steps *at the saved global batch*. The data position is
  ``step / steps_per_epoch`` epochs — invariant across resizes — so the
  restored counter is rescaled by the steps-per-epoch ratio. When the
  division is inexact the counter rounds DOWN (a partial batch is
  re-consumed rather than skipped) and the ``reshard`` row says so.

Grounded in PAPERS.md: "Scalable Training of Language Models using JAX
pjit and TPUv4" (checkpoint portability across topologies) and
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (the sharded-update layouts that make resize nontrivial).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "ELASTIC_KEYS",
    "MODEL_AXIS_KEYS",
    "ElasticRefusal",
    "refusal_reason",
    "elastic_mismatch",
    "meta_matches",
    "remap_step",
    "reshard_restore",
]


class ElasticRefusal(ValueError):
    """A geometry/structure mismatch that is NOT a world resize — a
    decision, not damage: the corrupt-checkpoint fallback walk must
    propagate it instead of trying older steps (they would refuse
    identically)."""

#: geometry-meta keys a world resize is ALLOWED to change. Everything
#: else in the meta is run semantics (reduction method, ZeRO-1 on/off,
#: future keys default-deny) and still refuses loudly.
ELASTIC_KEYS = frozenset(
    {"world_size", "data_world", "steps_per_epoch", "batch_size",
     "grad_accum"}
)

#: the composed-parallelism axis worlds (tpudist.parallel.plan): every
#: placement in the checkpoint — fsdp scatter, Megatron tensor splits,
#: stacked pipe stages — is bound to these sizes, and unlike a data
#: resize there is no layout algebra here yet (ROADMAP: FSDP reshard is
#: the named follow-on), so resizing any of them is DEFAULT-DENIED with
#: a hint naming the fix. Metas written before this layer carry none of
#: the keys and mean 1 (:func:`comparable_meta`).
MODEL_AXIS_KEYS = ("fsdp_world", "tensor_world", "pipe_world",
                   "expert_world")


def refusal_reason(saved_meta: dict, run_meta: dict) -> str | None:
    """Why this meta mismatch is NOT a valid elastic resize — or ``None``
    when every differing key is world-shaped and the reshard may proceed.
    Keys missing on either side count as differing (default-deny: a
    future semantic key must refuse until this list learns about it).
    Model-axis resizes get a precise hint: which axis moved, and that
    only the ``data`` axis is elastic."""
    # the legacy-default normalization first: an old meta without the
    # appended axis keys vs a live run with all axes at 1 must not turn a
    # legitimate pure-data resize into a spurious model-axis refusal
    run_meta = comparable_meta(saved_meta, run_meta)
    bad = sorted(
        k
        for k in set(saved_meta) | set(run_meta)
        if saved_meta.get(k) != run_meta.get(k) and k not in ELASTIC_KEYS
    )
    if not bad:
        return None
    axes = [k for k in bad if k in MODEL_AXIS_KEYS]
    if axes:
        # absent = the pre-composition default of 1, so the hint reads
        # "fsdp_world 1 -> 2", not "None -> 2"
        moved = ", ".join(
            f"{k} {saved_meta.get(k, 1)} -> {run_meta.get(k, 1)}"
            for k in axes
        )
        want = ", ".join(
            f"{k.split('_')[0]}={saved_meta.get(k, 1)}"
            for k in MODEL_AXIS_KEYS
        )
        rest = [k for k in bad if k not in MODEL_AXIS_KEYS]
        more = f"; keys {rest} differ too" if rest else ""
        legacy = [k for k in axes if k not in saved_meta]
        if legacy:
            # a meta that PREDATES axis recording can only be read as
            # axes=1 — but a pre-upgrade TP/pipe run really did train
            # split, and its unchanged-geometry resume must not be
            # bricked: name the one-line adoption fix
            more += (
                f"; note {legacy} are absent from the saved meta (written "
                "before model-axis recording) — if the checkpoint really "
                "was trained under THIS run's axes, adopt it by adding "
                "the keys with this run's values to tpudist_meta.json"
            )
        return (
            f"model-parallel axes resized ({moved}): only the data axis "
            "is elastic — the fsdp/tensor/pipe placements the checkpoint "
            "was written under have no reshard path; relaunch with the "
            f"checkpoint's plan (MeshConfig({want})) or start a fresh "
            f"checkpoint_dir{more}"
        )
    return (
        f"keys {bad} differ beyond a world resize "
        f"({ {k: saved_meta.get(k) for k in bad} } != "
        f"{ {k: run_meta.get(k) for k in bad} })"
    )


def comparable_meta(saved_meta: dict, run_meta: dict) -> dict:
    """``run_meta`` as it should be COMPARED against ``saved_meta``:
    ``data_world`` was introduced by the elastic layer, so a checkpoint
    written before it carries no such key — a legacy meta that matches on
    everything else is the SAME geometry (``world_size`` already pins the
    world it knew about), not a mismatch that refuses (or, worse,
    gratuitously reshard-commits) a resume on unchanged hardware.

    The composed-parallelism axis worlds (:data:`MODEL_AXIS_KEYS`) were
    appended later still, with an explicit legacy default of 1: a key the
    saved meta lacks compares equal when the live run's value is 1 (the
    only geometry an old checkpoint can have been written under) and
    DIFFERS — default-deny, precise hint — when the live run actually
    splits that axis."""
    drop = {
        k for k in ("data_world",)
        if k in run_meta and k not in saved_meta
    }
    drop |= {
        k for k in MODEL_AXIS_KEYS
        if k in run_meta and k not in saved_meta and run_meta[k] == 1
    }
    if drop:
        return {k: v for k, v in run_meta.items() if k not in drop}
    return run_meta


def meta_matches(saved_meta: dict, run_meta: dict) -> bool:
    """Geometry equality with the legacy-``data_world`` allowance —
    the ONE comparison both ``fit()``'s guard and
    ``Checkpointer.restore(reshard=True)`` apply."""
    return saved_meta == comparable_meta(saved_meta, run_meta)


def elastic_mismatch(saved_meta: dict, run_meta: dict) -> bool:
    """True iff the metas differ AND the difference is a pure world
    resize (every differing key in :data:`ELASTIC_KEYS`)."""
    return (not meta_matches(saved_meta, run_meta)
            and refusal_reason(saved_meta, run_meta) is None)


def remap_step(step: int, saved_meta: dict, run_meta: dict) -> tuple[int, bool]:
    """Rescale a saved optimizer-step counter into the new world's step
    units, preserving the DATA position: ``step/steps_per_epoch`` is the
    epoch-fraction consumed, which is what ``fit()``'s resume math
    (``start_epoch``/``skip_batches``) derives from the counter. Returns
    ``(new_step, exact)``; inexact ratios round DOWN (re-consume the
    partial batch — never skip unseen rows)."""
    old = saved_meta.get("steps_per_epoch")
    new = run_meta.get("steps_per_epoch")
    step = int(step)
    if not old or not new or old == new:
        return step, True
    return (step * new) // old, (step * new) % old == 0


def _norm_path(path) -> tuple[str, ...]:
    """One name-space for tree paths: orbax's saved metadata comes back as
    nested dicts/lists (DictKey/SequenceKey) while the live TrainState
    flattens through attribute and named-tuple keys — normalize both to
    plain strings so leaves align by name, not by flatten order."""
    out = []
    for k in path:
        if hasattr(k, "name"):
            out.append(str(k.name))
        elif hasattr(k, "key"):
            out.append(str(k.key))
        elif hasattr(k, "idx"):
            out.append(str(k.idx))
        else:
            out.append(str(k))
    return tuple(out)


def _is_meta_leaf(x) -> bool:
    return hasattr(x, "shape") and not isinstance(x, dict)


def _old_leaf_sharding(shape, mesh: Mesh) -> NamedSharding:
    """Placement for a saved-layout leaf while it is in flight: sharded
    over ``data`` on any divisible dim (a ``[old_world, cols]`` pad leaf
    usually divides when the world shrank), replicated otherwise — the
    transform's ``out_shardings`` re-lays it either way."""
    from tpudist.mesh import DATA_AXIS, largest_divisible_spec

    world = int(mesh.shape[DATA_AXIS])
    if world > 1:
        spec = largest_divisible_spec(shape, DATA_AXIS, world, min_size=1024)
        if any(s is not None for s in spec):
            return NamedSharding(mesh, spec)
    return NamedSharding(mesh, P())


@functools.lru_cache(maxsize=512)
def _relayout_exe(new_shape: tuple, new_sharding):
    """One jitted relayout program per (target shape, target sharding) —
    NOT per leaf: mu/nu mirrors of one param share it outright, and
    jit's own signature cache reuses it across every leaf with the same
    source shape (transformer layers repeat shapes), instead of paying a
    fresh trace+compile for hundreds of tiny slice/pad programs on
    exactly the restart path this layer exists to shrink."""
    n_new = math.prod(new_shape)

    def xform(x):
        flat = jnp.ravel(x)
        if flat.size >= n_new:
            flat = jax.lax.slice_in_dim(flat, 0, n_new)
        else:
            flat = jnp.pad(flat, (0, n_new - flat.size))
        return flat.reshape(new_shape)

    return jax.jit(xform, out_shardings=new_sharding, donate_argnums=0)


def _relayout(old: jax.Array, new_shape, new_sharding) -> jax.Array:
    """Old stored layout → new stored layout, in-graph: copy the flat
    prefix, zero-(re)pad the tail. Exact for ZeRO-1 stored leaves because
    the tail beyond the logical prefix is zero padding on BOTH sides
    (``shard_state._store`` zero-pads; re-zeroing is idempotent)."""
    return _relayout_exe(tuple(new_shape), new_sharding)(old)


def reshard_restore(
    ckpt,
    like,
    step: int,
    *,
    mesh: Mesh,
    saved_meta: dict,
    run_meta: dict,
    on_event: Callable[[dict], Any] | None = None,
):
    """Restore checkpoint ``step`` onto ``like``'s (new-world) placement,
    resharding the world-bound leaves. ``ckpt`` is a
    :class:`tpudist.checkpoint.Checkpointer` (its ``restore(reshard=True)``
    mode delegates here); ``like`` supplies the new structure, shapes,
    dtypes and shardings (comm_residual already attached for quantized
    runs — its zeros ARE the flushed banks).

    Returns the placed new-world state with ``state.step`` already
    remapped (:func:`remap_step`). Emits one ``reshard`` event dict
    through ``on_event`` describing what moved, what flushed, and the
    cursor remap — ``fit()`` forwards it to telemetry as the one-shot
    ``reshard`` row.
    """
    reason = refusal_reason(saved_meta, run_meta)
    if reason is not None:
        raise ElasticRefusal(
            f"checkpoint at {ckpt.directory} cannot be elastically "
            f"resumed: {reason} — resume with the original settings or "
            "start a fresh checkpoint_dir"
        )
    saved = {
        _norm_path(p): m
        for p, m in jtu.tree_flatten_with_path(
            ckpt.saved_metadata(step), is_leaf=_is_meta_leaf
        )[0]
    }
    like_leaves, _ = jtu.tree_flatten_with_path(like)
    like_paths = {_norm_path(p) for p, _ in like_leaves}
    if set(saved) != like_paths:
        missing = sorted(like_paths - set(saved))[:3]
        extra = sorted(set(saved) - like_paths)[:3]
        raise ElasticRefusal(
            f"checkpoint at {ckpt.directory} has a different train-state "
            f"STRUCTURE than the live run (missing {missing}, extra "
            f"{extra}) — this is not a world resize; resume with the "
            "original settings"
        )

    # per-leaf plan: aligned by path name, classified by shape agreement
    repl = NamedSharding(mesh, P())
    plan, abstract = [], []
    for p, leaf in like_leaves:
        key = _norm_path(p)
        old = saved[key]
        old_shape, old_dtype = tuple(old.shape), old.dtype
        if key[0] == "comm_residual":
            # world-bound error-feedback banks: never restored — the new
            # state's zeroed residual is the flushed bank. The abstract
            # leaf still names the OLD shape so orbax's restore tree
            # matches what is on disk; the tiny read is discarded.
            plan.append(("flush", leaf))
            abstract.append(
                jax.ShapeDtypeStruct(old_shape, old_dtype, sharding=repl)
            )
        elif old_shape == tuple(leaf.shape) and old_dtype == leaf.dtype:
            # world-independent leaf (params, BN stats, naturally-divisible
            # ZeRO-1 shards): orbax places it straight onto the new mesh
            plan.append(("direct", None))
            abstract.append(
                jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                     sharding=leaf.sharding)
            )
        elif old_dtype != leaf.dtype:
            raise ElasticRefusal(
                f"leaf {'/'.join(key)} changed dtype "
                f"({old_dtype} != {leaf.dtype}) — not a world resize"
            )
        else:
            # stored-layout leaf: restore at the saved shape (explicitly
            # placed on the LIVE mesh — the checkpoint's recorded device
            # topology may no longer exist), then relayout in-graph
            plan.append(("reshard", (tuple(leaf.shape), leaf.sharding)))
            abstract.append(
                jax.ShapeDtypeStruct(
                    old_shape, old_dtype,
                    sharding=_old_leaf_sharding(old_shape, mesh),
                )
            )
    structure = jtu.tree_structure(like)
    restored = ckpt.raw_restore(
        step, jtu.tree_unflatten(structure, abstract)
    )
    restored_leaves = jtu.tree_leaves(restored)

    out, resharded, flushed = [], [], 0
    for (p, _), (mode, info), r in zip(like_leaves, plan, restored_leaves):
        if mode == "direct":
            out.append(r)
        elif mode == "flush":
            out.append(info)  # like's zeroed residual
            flushed += 1
        else:
            new_shape, new_sharding = info
            out.append(_relayout(r, new_shape, new_sharding))
            resharded.append("/".join(_norm_path(p)))
    state = jtu.tree_unflatten(structure, out)

    new_step, exact = remap_step(step, saved_meta, run_meta)
    if new_step != step:
        # keep the counter's placement (a later AOT executable checks
        # input shardings strictly — a default-device scalar would refuse)
        state = state.replace(
            step=jax.device_put(
                jnp.asarray(new_step, state.step.dtype), like.step.sharding
            )
        )
    if on_event is not None:
        on_event({
            "tag": "reshard",
            "old_world": saved_meta.get("data_world",
                                        saved_meta.get("world_size")),
            "new_world": run_meta.get("data_world",
                                      run_meta.get("world_size")),
            "step_old": int(step),
            "step_new": int(new_step),
            "cursor_exact": bool(exact),
            "resharded_leaves": len(resharded),
            "resharded": resharded[:16],
            "residual_flushed": bool(flushed),
        })
    return state
