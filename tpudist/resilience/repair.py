"""Self-healing training: the automatic rollback-and-skip repair loop.

The robustness stack *detects* trouble (PR 7: replica-divergence
bit-checksums, the NanSentry loss-spike verdict, the in-graph
``guard_nonfinite`` skip) and *survives process death* (the PR 8
supervisor, PR 11 elastic resume) — but detection used to end at a JSONL
row: a silent-data-corruption hit or a sustained loss spike either
poisoned the trajectory or needed a human to kill the job. Production
TPU training closes this loop automatically (the operational posture of
the pjit/TPUv4 experience reports, PAPERS.md): roll back to a
known-good checkpoint, skip the offending data window, and continue.
``fit(repair=...)`` wires this module in (docs/MULTIHOST.md "Recovering
from loss spikes and SDCs"); every action books honestly as a one-shot
telemetry ``repair`` row, the report's ``repairs`` history, and the
goodput ``repair_s``/``repair_replay_s`` components.

**Triggers** (the controller subscribes to the telemetry event bus and
to the per-step health metrics):

- ``sdc_divergence`` — the replica-divergence probe's verdict (a single
  flipped bit in one replica's params; ``divergence_every`` must be on
  for this trigger to exist);
- ``skip_streak`` — ``skip_streak`` CONSECUTIVE non-finite/skipped
  steps: one poisoned step is ``guard_nonfinite``'s job (skip the
  update, move on); a streak means the poison is in the data window or
  the state, and skipping updates forever is not training;
- ``loss_spike`` — ``spike_patience`` NanSentry spike verdicts within
  ``spike_window_steps`` (one spike is news; a sustained spike is
  divergence that will not heal).

**The escalation ladder** (executed in-process by ``fit()``):

1. **rollback**: restore the last-known-good ANCHOR checkpoint (below),
   re-zero the quantized reducer's error-feedback residual, and reset
   the delayed-fetch/double-buffer pipelines — the same resets
   ``elastic.py`` performs on a world resize;
2. **skip**: advance the data cursor ``skip_window`` batches PAST the
   trigger (the offending window is never replayed) and fold a
   repair-generation salt into the step RNG so dropout masks and
   stochastic-rounding draws redraw — a spike caused by one unlucky
   draw, not data, heals on the redraw alone;
3. **restart (exit 77)**: a REPEAT trigger inside the window just
   repaired means in-process state (or this host) may itself be sick —
   persist the rollback-and-skip directive (``tpudist_repair.json``
   next to the checkpoints), exit :data:`~tpudist.resilience.exitcodes
   .EXIT_REPAIR`, and let the supervisor's existing backoff/budget
   machinery relaunch; bring-up consumes the directive (restore the
   anchor, skip FURTHER);
4. **circuit-break**: a rolling budget (``max_repairs`` per
   ``budget_window_s``) turns a deterministically-poisoned run into
   :class:`RepairExhausted` — a non-restartable, non-zero exit — instead
   of a rollback loop that burns the fleet forever.

**Last-known-good anchoring**: "newest save" is NOT "known good" — a
checkpoint written while a spike was incubating is exactly the state a
rollback must avoid. A save becomes a *candidate*; only after
``anchor_clean_steps`` subsequent steps with clean health metrics is it
PROMOTED to the anchor (``Checkpointer.write_anchor`` — exempt from
``keep_last`` pruning); any unhealthy step, or any trigger, DEMOTES all
pending candidates. For SDC triggers the promotion lag must exceed the
probe's detection latency: choose ``anchor_clean_steps`` > 2 ×
``divergence_every`` or a poisoned save can promote before the delayed
probe verdict lands (the defaults respect this for the drill configs;
docs/MULTIHOST.md spells out the production numbers).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import time
from pathlib import Path
from typing import Any, Mapping

from tpudist.resilience.exitcodes import EXIT_REPAIR

__all__ = [
    "RepairPolicy",
    "RepairAction",
    "RepairController",
    "RepairRestart",
    "RepairExhausted",
    "resolve_policy",
]

#: the durable repair record, next to the checkpoints: the applied-repair
#: history (the budget's evidence across generations) plus the pending
#: rollback-and-skip directive an exit-77 restart leaves for the next
#: generation's bring-up
STATE_FILE = "tpudist_repair.json"


class RepairRestart(SystemExit):
    """Rung 3 of the ladder: a repeat trigger inside the just-repaired
    window — the directive is durable, the process asks for a fresh
    start. A :class:`SystemExit` carrying ``code == EXIT_REPAIR`` (77),
    the restartable code the supervisor relaunches promptly; ``main.py``
    and the example trainers need no handler. ``action`` carries the
    persisted directive for library callers."""

    def __init__(self, action: "RepairAction | None" = None,
                 step: int | None = None):
        super().__init__(EXIT_REPAIR)
        self.action = action
        self.step = step

    def __str__(self) -> str:
        where = f" at step {self.step}" if self.step is not None else ""
        return (
            f"repair loop hit a repeat trigger{where}; rollback-and-skip "
            f"directive persisted, exiting {EXIT_REPAIR} for a supervised "
            "relaunch"
        )


class RepairExhausted(RuntimeError):
    """Rung 4: the rolling repair budget is spent — the poison is
    deterministic (or the hardware is dying) and further rollbacks would
    spin forever. Propagates through fit's real crash path: report
    written, non-restartable non-zero exit, the supervisor's crash
    budget (not its restartable fast path) decides what happens next."""


@dataclasses.dataclass(frozen=True)
class RepairPolicy:
    """Knobs of the repair loop — ``fit(repair=True)`` runs the
    defaults; pass a policy (or a dict of overrides) to tune.

    ``skip_window``: batches skipped past the trigger step on rollback —
    the data window presumed offending. ``anchor_clean_steps``: clean
    health steps a save must outlive before promotion to the rollback
    anchor (keep it above 2x the divergence-probe cadence when SDC
    triggers matter — see the module doc). ``skip_streak``: consecutive
    non-finite/skipped steps that trigger a repair (1 poisoned step is
    the in-graph guard's job). ``spike_patience`` NanSentry spike
    verdicts within ``spike_window_steps`` trigger on sustained spikes.
    ``repeat_window``: a new trigger within this many steps of the
    previous repair's resume point means the repair DIDN'T TAKE (same
    incident, not a new one) and the ladder escalates to a restart —
    keep it above the slowest detector's latency (2 x
    ``divergence_every`` for the probe: detection of a re-poisoned
    state lands that many steps after the resume). ``max_repairs`` per
    rolling ``budget_window_s`` is the circuit breaker (0 disables —
    never circuit-break). ``salt_stride`` spaces the repair-generation
    RNG salts folded into the step's dropout/stochastic-rounding
    seed."""

    skip_window: int = 8
    anchor_clean_steps: int = 16
    skip_streak: int = 3
    spike_patience: int = 2
    spike_window_steps: int = 64
    repeat_window: int = 16
    max_repairs: int = 3
    budget_window_s: float = 3600.0
    salt_stride: int = 1_000_003

    def salted_seed(self, seed: int, salt: int) -> int:
        """The step-RNG seed for repair generation ``salt`` (0 = the
        pristine run: exactly ``seed``, so a never-repaired run's
        programs are bit-identical to a repair-less one)."""
        return int(seed) + self.salt_stride * int(salt)


def resolve_policy(repair) -> RepairPolicy | None:
    """``fit(repair=...)``'s coercion point: ``None``/``False`` → off,
    ``True`` → defaults, a dict → overrides, a policy → itself."""
    if repair is None or repair is False:
        return None
    if repair is True:
        return RepairPolicy()
    if isinstance(repair, RepairPolicy):
        return repair
    if isinstance(repair, Mapping):
        return RepairPolicy(**dict(repair))
    raise ValueError(
        f"repair={repair!r}: expected None/False/True/RepairPolicy/"
        "dict of RepairPolicy overrides"
    )


@dataclasses.dataclass
class RepairAction:
    """One planned rung of the ladder (``RepairController.plan``)."""

    kind: str  # "rollback" | "restart"
    cause: dict
    rollback_step: int
    anchored: bool
    skip_from: int
    skip_to: int
    salt: int
    discarded_steps: int
    replay_s: float
    generation: int
    t: float

    def row(self) -> dict:
        """The telemetry ``repair`` row / history entry — one honest
        record per action: cause, rollback target, skipped window, what
        was done."""
        return {
            "action": self.kind,
            "cause": dict(self.cause),
            "rollback_step": int(self.rollback_step),
            "anchored": bool(self.anchored),
            "skip_from": int(self.skip_from),
            "skip_to": int(self.skip_to),
            "salt": int(self.salt),
            "discarded_steps": int(self.discarded_steps),
            "replay_s": round(float(self.replay_s), 6),
            "generation": int(self.generation),
            "t": round(float(self.t), 3),
        }


class RepairController:
    """The policy engine ``fit()`` drives: detector subscriptions in,
    planned ladder actions out, with the anchor promotion arithmetic and
    the durable cross-generation record in between.

    Every rank constructs one; decisions are deterministic functions of
    replicated per-step scalars and the shared state file, so ranks act
    in lockstep — only rank 0 writes the file (``write_state``), the
    same discipline as the geometry meta.
    """

    #: bound on the per-step interval map that prices a rollback's
    #: discarded work — covers any plausible anchor-to-trigger span
    CUM_CAP = 8192

    def __init__(self, policy: RepairPolicy, checkpoint_dir, *,
                 generation: int = 0, clock=time.time):
        self.policy = policy
        self.directory = Path(checkpoint_dir)
        self.generation = int(generation)
        self._clock = clock
        self._ckpt = None  # bound by fit once the Checkpointer exists
        self.history: list[dict] = []
        self.pending: dict | None = None
        self._load()
        # last-known-good anchoring
        self.anchored: int | None = None
        self._candidates: list[int] = []
        # trigger state
        self._trigger: dict | None = None
        self._skip_streak = 0
        self._spikes: collections.deque[int] = collections.deque()
        # replay pricing: cumulative step-interval sums by step number
        self._cum: collections.OrderedDict[int, float] = (
            collections.OrderedDict()
        )
        self._cum_total = 0.0

    # -- durable record ----------------------------------------------------

    def _state_path(self) -> Path:
        return self.directory / STATE_FILE

    def _load(self) -> None:
        p = self._state_path()
        if not p.exists():
            return
        try:
            blob = json.loads(p.read_text())
            self.history = [e for e in blob.get("history", [])
                            if isinstance(e, dict)]
            pend = blob.get("pending")
            self.pending = pend if isinstance(pend, dict) else None
        except (ValueError, OSError):
            # a torn file must not kill bring-up; the atomic writer makes
            # this near-impossible, but accounting is never a crash source
            self.history, self.pending = [], None

    def write_state(self) -> None:
        import jax

        from tpudist.checkpoint import atomic_write_json

        if jax.process_index() == 0:
            self.directory.mkdir(parents=True, exist_ok=True)
            atomic_write_json(
                self.directory, STATE_FILE,
                {"v": 1, "history": self.history, "pending": self.pending},
            )

    def bind(self, ckpt) -> "RepairController":
        """Attach the run's :class:`tpudist.checkpoint.Checkpointer`
        (anchor persistence + rollback-target enumeration), and wire the
        retention protect hook: anchor CANDIDATES must survive
        ``keep_last`` pruning until they promote or demote, or the
        promoted anchor would name a deleted step dir. Chained like the
        chaos injector's ``bind``."""
        self._ckpt = ckpt
        self.anchored = ckpt.read_anchor()
        ckpt.protect_steps = self.protected_steps
        return self

    def protected_steps(self) -> set[int]:
        """Steps retention must not delete: the anchor plus every
        pending candidate (a save inside its clean-step promotion
        window)."""
        out = set(self._candidates)
        if self.anchored is not None:
            out.add(int(self.anchored))
        return out

    @property
    def salt(self) -> int:
        """The repair-generation RNG salt the CURRENT trajectory runs
        under: the last applied action's salt (0 on a never-repaired
        run). Persisted through the history so a post-repair trajectory
        keeps its redraw across ordinary preempt/resume cycles."""
        if self.history:
            return int(self.history[-1].get("salt", 0) or 0)
        return 0

    def consume_pending(self) -> dict | None:
        """Bring-up applied the exit-77 directive (anchor restored,
        cursor advanced): clear it durably. Returns the directive."""
        directive, self.pending = self.pending, None
        if directive is not None:
            self.write_state()
        return directive

    # -- anchoring ---------------------------------------------------------

    def on_save(self, step: int) -> None:
        """A checkpoint landed: it becomes an anchor CANDIDATE — promoted
        only after ``anchor_clean_steps`` clean steps, demoted by any
        unhealthy step or trigger in between."""
        step = int(step)
        if step not in self._candidates:
            self._candidates.append(step)

    def _promote(self, at_step: int) -> None:
        ripe = [c for c in self._candidates
                if at_step - c >= self.policy.anchor_clean_steps]
        if not ripe:
            return
        new_anchor = max(ripe)
        self._candidates = [c for c in self._candidates if c > new_anchor]
        if self.anchored is None or new_anchor > self.anchored:
            self.anchored = new_anchor
            if self._ckpt is not None:
                self._ckpt.write_anchor(new_anchor)

    def _demote(self) -> None:
        # a save taken while the incident was incubating must never
        # become the rollback target
        self._candidates.clear()

    # -- detection ---------------------------------------------------------

    def observe_step(self, step: int, metrics: Mapping[str, Any],
                     interval_s: float = 0.0) -> None:
        """One resolved step's host-side scalars (fit's delayed
        pipeline): drives the skip-streak arithmetic, the anchor
        promotion clock, and the replay-pricing bookkeeping."""
        import math

        step = int(step)
        self._cum_total += max(float(interval_s), 0.0)
        self._cum[step] = self._cum_total
        while len(self._cum) > self.CUM_CAP:
            self._cum.popitem(last=False)
        loss = metrics.get("loss")
        try:
            finite = loss is not None and math.isfinite(float(loss))
        except (TypeError, ValueError):
            finite = False
        healthy = (
            finite
            and not int(metrics.get("update_skipped", 0) or 0)
            and not int(metrics.get("nonfinite_grad_count", 0) or 0)
        )
        if healthy:
            self._skip_streak = 0
            self._promote(step)
        else:
            self._skip_streak += 1
            self._demote()
            if self._skip_streak >= self.policy.skip_streak:
                self._set_trigger({
                    "cause": "skip_streak",
                    "detector": "guard_nonfinite",
                    "step": step,
                    "streak": self._skip_streak,
                })

    def on_detection(self, ev: Mapping[str, Any]) -> None:
        """Telemetry event-bus listener (``Telemetry.add_listener``):
        divergence verdicts trigger immediately (an SDC has no benign
        reading); sentry spike verdicts accumulate toward the
        sustained-spike rule; sentry ``nonfinite`` events are left to
        the skip-streak arithmetic (a single non-finite step is the
        guard's job, and the streak sees every step, not just the
        cooldown-surviving events)."""
        det = ev.get("detector")
        if det == "divergence":
            self._set_trigger({
                "cause": "sdc_divergence",
                "detector": "divergence",
                "step": int(ev.get("step", -1)),
                "replica_divergence": ev.get("replica_divergence"),
                "state_nonfinite": ev.get("state_nonfinite"),
            })
        elif det == "sentry" and ev.get("event") == "loss_spike":
            step = int(ev.get("step", -1))
            self._spikes.append(step)
            while (self._spikes
                   and self._spikes[0] < step - self.policy.spike_window_steps):
                self._spikes.popleft()
            if len(self._spikes) >= self.policy.spike_patience:
                self._set_trigger({
                    "cause": "loss_spike",
                    "detector": "sentry",
                    "step": step,
                    "spike_events": len(self._spikes),
                    "loss": ev.get("loss"),
                })

    def _set_trigger(self, cause: dict) -> None:
        self._demote()
        if self._trigger is None:
            self._trigger = cause

    @property
    def triggered(self) -> dict | None:
        return self._trigger

    def take_trigger(self) -> dict:
        trigger, self._trigger = self._trigger, None
        self._skip_streak = 0
        self._spikes.clear()
        return trigger

    # -- the ladder --------------------------------------------------------

    def _rollback_target(self) -> tuple[int, bool]:
        if self.anchored is not None:
            return int(self.anchored), True
        # no promotion yet (run too young): the OLDEST surviving save is
        # the most conservative guess at known-good — recorded as
        # anchored=False so the row stays honest
        steps = self._ckpt.all_steps() if self._ckpt is not None else []
        if not steps:
            raise RepairExhausted(
                "repair triggered with no checkpoint to roll back to — "
                "fit(repair=...) saves an initial checkpoint at bring-up, "
                "so this means even that save is gone"
            )
        return int(steps[0]), False

    def plan(self, trigger: dict, current_step: int, *,
             max_step: int | None = None) -> RepairAction:
        """Decide the rung for ``trigger`` observed with ``current_step``
        the in-flight (to-be-discarded) step. Raises
        :class:`RepairExhausted` when the rolling budget is spent;
        otherwise returns a ``rollback`` action — or a ``restart`` when
        the trigger landed inside the window the previous repair just
        skipped (same data already skipped, salt already redrawn: the
        remaining suspects are in-process state and this host, so ask
        the supervisor for a fresh world). The caller applies the action
        and then :meth:`record`\\ s it.

        Multi-process caveat: the budget gate compares per-rank wall
        clocks against ``budget_window_s``, and each rank measures both
        the entry stamp and ``now`` on its OWN clock — so ranks agree
        unless an entry's age lands within their microsecond call-skew
        of EXACTLY the window edge. In that astronomically thin window
        one rank could raise :class:`RepairExhausted` while its peers
        enter the rollback's collective restore and block; the hang
        watchdog (``hang_timeout_s``) is the designed backstop for a
        rank dying inside a collective, there as here. A truly shared
        decision would need its own collective per trigger — not worth
        the cost for a boundary this thin."""
        now = float(self._clock())
        if self.policy.max_repairs > 0:
            recent = [
                e for e in self.history
                if now - float(e.get("t", now)) <= self.policy.budget_window_s
            ]
            if len(recent) >= self.policy.max_repairs:
                raise RepairExhausted(
                    f"repair budget exhausted: {len(recent)} repairs in "
                    f"the last {self.policy.budget_window_s:.0f}s (max "
                    f"{self.policy.max_repairs}) and another trigger "
                    f"({trigger.get('cause')}) at step {current_step} — "
                    "the poison is deterministic; giving up (see the "
                    "report's repairs history)"
                )
        rollback_step, anchored = self._rollback_target()
        current_step = int(current_step)
        skip_to = current_step + self.policy.skip_window
        if max_step is not None:
            skip_to = min(skip_to, int(max_step))
        skip_to = max(skip_to, current_step)
        last = self.history[-1] if self.history else None
        # "repeat": the new trigger landed before repeat_window steps of
        # clean progress past the previous repair's resume point — the
        # data was already skipped and the salt already redrawn, so the
        # remaining suspects are in-process state and this host
        repeat = (
            last is not None
            and current_step
            <= int(last.get("skip_to", -1))
            + max(self.policy.repeat_window, self.policy.skip_window)
        )
        replay = max(
            self._cum_total - self._cum.get(rollback_step, 0.0), 0.0
        )
        return RepairAction(
            kind="restart" if repeat else "rollback",
            cause=dict(trigger),
            rollback_step=rollback_step,
            anchored=anchored,
            skip_from=current_step,
            skip_to=skip_to,
            salt=self.salt + 1,
            discarded_steps=max(current_step - rollback_step, 0),
            replay_s=replay,
            generation=self.generation,
            t=now,
        )

    def record(self, action: RepairAction) -> dict:
        """Book an applied (or restart-persisted) action durably: it
        charges the rolling budget, carries the salt forward, and — for
        ``restart`` — becomes the pending directive the next
        generation's bring-up consumes."""
        entry = action.row()
        self.history.append(entry)
        if action.kind == "restart":
            self.pending = entry
        self.write_state()
        return entry
