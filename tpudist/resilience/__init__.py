"""Resilience: the layer that turns failures into bounded-cost events.

tpudist can *detect* sick jobs (the run-health layer) and *persist* state
(the Orbax checkpointer); this package connects detection to action so a
preemption, a hang, or a crash costs a bounded amount of work instead of
the whole run:

- :mod:`~tpudist.resilience.exitcodes` — the trainer↔supervisor exit-code
  contract (75 = preempted/resume, 76 = watchdog hang, else crash);
- :mod:`~tpudist.resilience.preempt` — SIGTERM/SIGINT trapped as a
  signal-safe flag; ``fit()`` finishes the in-flight step, writes a
  synchronous emergency checkpoint, flushes the run report with
  ``exit_reason="preempted"``, and raises :class:`Preempted` (exit 75);
- :mod:`~tpudist.resilience.supervisor` — restart policy for
  ``tpudist.launch``: restartable-code fast path, exponential backoff
  with jitter for crashes, a rolling restart-budget window, and the
  ``TPUDIST_RESTART_GENERATION`` counter;
- :mod:`~tpudist.resilience.goodput` — wall-time partitioning (productive
  step time vs compile/checkpoint/data-wait/restart overhead), aggregated
  across generations into the run report's ``goodput`` section;
- :mod:`~tpudist.resilience.chaos` — deterministic crash/hang/SIGTERM/
  checkpoint-corruption injection (``main.py --chaos``, the recovery
  tests, the bench's ``gpt2_124m_preempt_recovery_s`` leg);
- :mod:`~tpudist.resilience.repair` — the self-healing loop
  (``fit(repair=...)``): detector verdicts (replica divergence, skip
  streaks, sustained loss spikes) execute an in-process escalation
  ladder — roll back to the last-known-good ANCHORED checkpoint, skip
  the offending data window with a redrawn RNG salt, exit 77 for a
  supervised relaunch on a repeat trigger, and circuit-break a
  deterministic poison on a rolling repair budget (docs/MULTIHOST.md
  "Recovering from loss spikes and SDCs");
- :mod:`~tpudist.resilience.elastic` — cross-world-size checkpoint
  resharding (``fit(elastic=True)``): ZeRO-1 pad-and-reshape layouts
  re-laid onto the surviving mesh, error-feedback residual flushed,
  sampler cursor remapped — a preempted world resumes on whatever
  hardware is left (docs/MULTIHOST.md "Resuming on a different world
  size"). The AOT executable cache that makes the relaunch cheap lives
  in :mod:`tpudist.compile_cache`.

Operational recipe: docs/MULTIHOST.md "Surviving preemption".
"""

from tpudist.resilience.chaos import (
    ChaosCrash,
    ChaosInjector,
    ChaosSpec,
    corrupt_latest_checkpoint,
    flip_param_bit,
    make_injector,
    parse_chaos,
)
from tpudist.resilience.elastic import (
    ElasticRefusal,
    elastic_mismatch,
    remap_step,
    reshard_restore,
)
from tpudist.resilience.exitcodes import (
    EXIT_CRASH,
    EXIT_HANG,
    EXIT_HISTORY_ENV,
    EXIT_INTERRUPT,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_REPAIR,
    GENERATION_ENV,
    RESTARTABLE,
    RUN_ID_ENV,
    ensure_run_id,
    exit_history,
    is_restartable,
    restart_generation,
    run_id,
)
from tpudist.resilience.goodput import GoodputTracker
from tpudist.resilience.preempt import Preempted, PreemptionGuard
from tpudist.resilience.repair import (
    RepairController,
    RepairExhausted,
    RepairPolicy,
    RepairRestart,
    resolve_policy,
)
from tpudist.resilience.supervisor import (
    BackoffPolicy,
    RestartBudget,
    Supervisor,
    classify,
)

__all__ = [
    "EXIT_OK",
    "EXIT_CRASH",
    "EXIT_PREEMPTED",
    "EXIT_HANG",
    "EXIT_REPAIR",
    "EXIT_INTERRUPT",
    "RESTARTABLE",
    "GENERATION_ENV",
    "EXIT_HISTORY_ENV",
    "RUN_ID_ENV",
    "is_restartable",
    "restart_generation",
    "exit_history",
    "run_id",
    "ensure_run_id",
    "Preempted",
    "PreemptionGuard",
    "BackoffPolicy",
    "RestartBudget",
    "Supervisor",
    "classify",
    "GoodputTracker",
    "ChaosCrash",
    "ChaosSpec",
    "ChaosInjector",
    "make_injector",
    "parse_chaos",
    "corrupt_latest_checkpoint",
    "flip_param_bit",
    "ElasticRefusal",
    "elastic_mismatch",
    "remap_step",
    "reshard_restore",
    "RepairPolicy",
    "RepairController",
    "RepairRestart",
    "RepairExhausted",
    "resolve_policy",
]
