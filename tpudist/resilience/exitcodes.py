"""The process exit-code contract between the trainer and the supervisor.

On TPU pods the dominant interrupts are *planned* — preemptions and
maintenance events delivered as SIGTERM with a grace window — and the one
bit the dying process can reliably hand its supervisor is the exit code.
This module is the contract's single home: the trainer (``tpudist.train
.fit`` via :mod:`tpudist.resilience.preempt`, the hang watchdog via
``TelemetryConfig(hang_action="exit")``) exits with one of these codes,
and the supervisor (``tpudist.launch`` → :mod:`tpudist.resilience
.supervisor`) restarts ONLY the codes that say "resume me":

- ``EXIT_PREEMPTED`` (75, BSD's EX_TEMPFAIL): the run trapped
  SIGTERM/SIGINT, finished its in-flight step, wrote a synchronous
  emergency checkpoint, and exited on purpose — relaunch and resume.
- ``EXIT_HANG`` (76, EX_PROTOCOL): the hang watchdog tripped, the crash
  forensics are on disk, and ``hang_action="exit"`` terminated the wedged
  process — relaunch from the last checkpoint.
- ``EXIT_REPAIR`` (77, EX_NOPERM — reused: clear of every shell/signal
  convention): the in-process repair loop (``tpudist.resilience.repair``)
  hit a REPEAT trigger inside the window it had just repaired, persisted
  a rollback-and-skip directive next to the checkpoints, and asked for a
  fresh process — relaunch; bring-up consumes the directive (restore the
  anchored checkpoint, skip further past the offending window).
- ``EXIT_INTERRUPT`` (130, 128+SIGINT): operator Ctrl-C at the launcher —
  never restarted.
- anything else non-zero is a crash: restarted only within the legacy
  ``--max_restarts`` attempt budget (with backoff), never on the
  restartable fast path.

75/76/77 sit in the 64..78 sysexits range, clear of shell conventions
(126/127), signal deaths (128+N), and ordinary ``sys.exit(1)`` crashes —
a launcher that predates this contract treats them as generic failures
and still recovers via ``--max_restarts``, just without the
backoff/budget discipline.
"""

from __future__ import annotations

import os

EXIT_OK = 0
EXIT_CRASH = 1
EXIT_PREEMPTED = 75
EXIT_HANG = 76
EXIT_REPAIR = 77
EXIT_INTERRUPT = 130

#: codes whose meaning is "state is durable, relaunch me" — the trainer
#: exited deliberately after persisting what it could
RESTARTABLE = frozenset({EXIT_PREEMPTED, EXIT_HANG, EXIT_REPAIR})

#: the supervisor exports each world's generation under this name; rank
#: telemetry reads it so heartbeats/reports are attributable across the
#: lives of one logical job (0 = first launch)
GENERATION_ENV = "TPUDIST_RESTART_GENERATION"

#: the supervisor exports the exit codes of every PREVIOUS generation of
#: this job under this name (comma-separated, oldest first; unset/empty on
#: a first launch) — the run report records it, so one file reconstructs
#: the incident timeline across the lives of the job
EXIT_HISTORY_ENV = "TPUDIST_EXIT_HISTORY"

#: one stable id per logical job, minted once at launcher bring-up and
#: exported to every rank and every relaunched generation — telemetry rows
#: carry it so offline stitching (``tools/tracelens.py``) can group the
#: segments of a multi-generation incident without filename heuristics
RUN_ID_ENV = "TPUDIST_RUN_ID"


def is_restartable(rc: int) -> bool:
    """True iff ``rc`` is a deliberate checkpoint-and-exit code (signal
    deaths arrive as negative codes from ``Popen`` and are crashes)."""
    return rc in RESTARTABLE


def restart_generation(environ=None) -> int:
    """This process's restart generation (``TPUDIST_RESTART_GENERATION``,
    default 0). Tolerant of garbage values: telemetry must not die on a
    malformed environment."""
    raw = (environ or os.environ).get(GENERATION_ENV, "0")
    try:
        return int(raw)
    except (TypeError, ValueError):
        return 0


def run_id(environ=None) -> str | None:
    """The job's stable run id (``TPUDIST_RUN_ID``), or ``None`` when no
    launcher/caller exported one. Whitespace-only values count as unset —
    telemetry must not die on a malformed environment."""
    raw = (environ or os.environ).get(RUN_ID_ENV)
    if raw is None:
        return None
    raw = str(raw).strip()
    return raw or None


def ensure_run_id(environ=None) -> str:
    """Read-or-mint the job's run id and EXPORT it into ``environ`` so
    every child process (all ranks, all relaunched generations — the
    supervisor spawns children with a copy of this environment) inherits
    the same id. The launcher calls this once at bring-up; everything
    else only *reads* via :func:`run_id`."""
    import uuid

    env = environ if environ is not None else os.environ
    existing = run_id(env)
    if existing is not None:
        return existing
    minted = uuid.uuid4().hex[:12]
    env[RUN_ID_ENV] = minted
    return minted


def exit_history(environ=None) -> list[int]:
    """The exit codes of this job's previous generations
    (``TPUDIST_EXIT_HISTORY``, oldest first; ``[]`` on a first launch or
    under a supervisor that predates the variable). Garbage entries are
    dropped, not fatal — accounting, never a crash source."""
    raw = (environ or os.environ).get(EXIT_HISTORY_ENV, "")
    out = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.append(int(part))
        except ValueError:
            continue
    return out
