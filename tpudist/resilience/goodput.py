"""Goodput accounting: where did the wall time of this job's life go?

Under preemption the headline metric is not tokens/sec but **goodput** —
the fraction of wall time spent making forward progress once compile,
checkpoint save/restore, data stalls, and restart/resume overhead are
paid (the operational regime of the TPUv4 pjit experience reports:
recovery time, not peak rate, determines useful throughput at pod
scale). :class:`GoodputTracker` partitions one ``fit()`` call's wall time
into disjoint components and aggregates them ACROSS restart generations
through the ``{job}_report.json`` each generation leaves behind:

- ``bringup_s`` — fit entry → first loop iteration (state init, replica
  verification, telemetry bring-up), minus the restore below;
- ``restore_s`` — checkpoint restore (the resume read);
- ``compile_s`` — the first loop iteration wall time (jit traces and
  compiles synchronously on first call, so iteration 1 *is* the compile,
  plus one ordinary step — an upper bound, noted not subtracted). With
  the AOT path (``fit(compile_cache=...)``) compilation happens at
  bring-up instead and is added explicitly; :meth:`GoodputTracker
  .set_precompiled` then keeps iteration 1 an ordinary step;
- ``cache_load_s`` — seconds bring-up BLOCKED on the AOT executable
  deserialization (``tpudist.compile_cache``): a warm start's analogue
  of compile time. The load runs on a side thread overlapped with the
  restore, so only the non-hidden join wait is booked — the partition
  stays disjoint (the full thread duration rides the telemetry
  ``compile_cache`` row as ``load_s``). Kept as its own component so a
  cache-hit first iteration is never mislabeled ``compile_s`` —
  ``restart_overhead_s`` still counts it (it is restart cost), but the
  cold-vs-warm A/B stays readable;
- ``data_wait_s`` — seconds the loop blocked on the batch iterator
  (steady-state iterations only; iteration 1's wait is inside
  ``compile_s``);
- ``checkpoint_s`` — seconds blocked in checkpoint saves, including the
  synchronous emergency save (also reported separately as
  ``emergency_save_s``, a subset of ``checkpoint_s``);
- ``repair_s`` — seconds spent executing in-process repairs
  (``tpudist.resilience.repair``: the anchored-checkpoint restore, the
  residual flush, the cursor jump);
- ``repair_replay_s`` — the wall seconds of STEP WORK a repair's rollback
  discarded (measured step intervals of the rolled-back span). Those
  seconds were counted productive while they ran; booking them here
  reclassifies them out of the productive residual, which is the honest
  price of a repair — the repaired run re-earns that progress on clean
  data. A second-order overlap with ``data_wait_s`` (the discarded
  steps' input waits are in both) is accepted: the residual clamps at
  zero and the repair legs read this component, not the residual;
- ``productive_step_s`` — the residual: total minus everything above.
  Computing productive time as the residual is what makes the components
  sum to the generation's wall time *exactly* (the report's acceptance
  contract), and it is the honest definition — any second not spent on
  an identified overhead was available to the step pipeline.

Cross-generation: each generation's summary carries a ``generations``
list (its own entry appended to the predecessors' — loaded from the
previous report via :meth:`GoodputTracker.load_previous`) and a
``cumulative`` block whose ``restart_overhead_s`` prices recovery: the
inter-generation wall gaps (supervisor backoff + process spawn) plus
every resumed generation's bring-up/restore/compile plus every emergency
save. That number is what the bench leg
``gpt2_124m_preempt_recovery_s`` records.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Callable

__all__ = ["GoodputTracker"]

# the disjoint partition of one generation's wall time; productive is the
# residual so the sum is exact by construction
COMPONENTS = (
    "bringup_s",
    "restore_s",
    "compile_s",
    "cache_load_s",
    "data_wait_s",
    "checkpoint_s",
    "repair_s",
    "repair_replay_s",
)


class GoodputTracker:
    def __init__(self, *, generation: int = 0,
                 clock: Callable[[], float] = time.perf_counter,
                 wall: Callable[[], float] = time.time):
        self.generation = int(generation)
        self._clock = clock
        self._wall = wall
        self._t0 = clock()
        self.start_wall = wall()
        self._parts = {k: 0.0 for k in COMPONENTS}
        self.emergency_save_s = 0.0
        self.repairs = 0
        self.steps = 0
        self._loop_t: float | None = None
        self._first_step_done = False
        self._precompiled = False
        self._warm = False
        self._prior: list[dict] = []

    # -- wiring ------------------------------------------------------------

    def load_previous(self, report_path: str | Path) -> None:
        """Carry forward the previous generations' entries from the report
        the last life of this job wrote (same job_id, same log_dir — the
        sink's append-mode precedent). Malformed/absent files are ignored:
        goodput is accounting, never a crash source."""
        try:
            report = json.loads(Path(report_path).read_text())
            gens = report["goodput"]["generations"]
            self._prior = [dict(g) for g in gens if isinstance(g, dict)]
        except Exception:
            self._prior = []

    def add(self, component: str, seconds: float) -> None:
        self._parts[component] += max(float(seconds), 0.0)

    def add_emergency_save(self, seconds: float) -> None:
        """The preemption path's synchronous save: counted inside
        ``checkpoint_s`` (the partition stays disjoint) and surfaced
        separately — it is the per-incident recovery cost."""
        self.add("checkpoint_s", seconds)
        self.emergency_save_s += max(float(seconds), 0.0)

    def add_repair(self, overhead_s: float, replay_s: float = 0.0) -> None:
        """One executed repair (``tpudist.resilience.repair``):
        ``overhead_s`` is the machinery (restore + flush + cursor jump),
        ``replay_s`` the discarded step work the rollback threw away —
        both reclassified out of the productive residual."""
        self.add("repair_s", overhead_s)
        self.add("repair_replay_s", replay_s)
        self.repairs += 1

    def set_precompiled(self, warm: bool = False) -> None:
        """The step executable exists BEFORE the loop (AOT path:
        ``tpudist.compile_cache`` compiled it at bring-up on a miss, or
        deserialized it on a hit): iteration 1 is an ordinary step and
        must not be attributed to ``compile_s``. ``warm`` marks a cache
        hit — the entry's ``warm_start`` field, what the bench's
        cold-vs-warm A/B keys on."""
        self._precompiled = True
        self._warm = bool(warm)

    def clear_precompiled(self) -> None:
        """The precompiled executable was REJECTED at first call (the
        AOT wrapper fell back to tracing): iteration 1 will pay a real
        trace+compile after all, so the attribution reverts to the cold
        contract — and the generation stops claiming a warm start (the
        cache load it did pay stays booked in ``cache_load_s``)."""
        self._precompiled = False
        self._warm = False

    def loop_started(self) -> None:
        """The epoch loop is about to run: everything so far that isn't
        already attributed (restore, compile/cache work on the AOT path,
        early checkpoint work) is bring-up."""
        self._loop_t = self._clock()
        self._parts["bringup_s"] = max(
            (self._loop_t - self._t0)
            - self._parts["restore_s"] - self._parts["checkpoint_s"]
            - self._parts["compile_s"] - self._parts["cache_load_s"],
            0.0,
        )

    def step_boundary(self, data_wait_s: float = 0.0) -> None:
        """Called once per completed loop iteration. The first iteration
        is attributed whole to ``compile_s`` (jit compiles synchronously
        inside it) — UNLESS the executable was precompiled/cache-loaded
        at bring-up (:meth:`set_precompiled`), in which case iteration 1
        is an ordinary step and contributes its measured data wait like
        any other; later iterations contribute their measured data
        wait."""
        self.steps += 1
        now = self._clock()
        if not self._first_step_done:
            self._first_step_done = True
            if not self._precompiled:
                base = self._loop_t if self._loop_t is not None else self._t0
                self._parts["compile_s"] = max(now - base, 0.0)
                return
        self.add("data_wait_s", data_wait_s)

    # -- report ------------------------------------------------------------

    def _entry(self, exit_reason: str) -> dict:
        total = self._clock() - self._t0
        overhead = sum(self._parts.values())
        entry = {
            "generation": self.generation,
            "exit_reason": exit_reason,
            "total_s": round(total, 6),
            "productive_step_s": round(max(total - overhead, 0.0), 6),
            **{k: round(v, 6) for k, v in self._parts.items()},
            "emergency_save_s": round(self.emergency_save_s, 6),
            "warm_start": bool(self._warm),
            "repairs": self.repairs,
            "steps": self.steps,
            "start_wall": round(self.start_wall, 3),
            "end_wall": round(self._wall(), 3),
        }
        return entry

    def summary(self, exit_reason: str = "completed") -> dict:
        """The report's ``goodput`` section. Safe to call repeatedly (the
        watchdog snapshots mid-run, finish() writes the final one): each
        call recomputes from live counters without mutating history."""
        entry = self._entry(exit_reason)
        gens = self._prior + [entry]
        gaps = [
            max(b.get("start_wall", 0.0) - a.get("end_wall", 0.0), 0.0)
            for a, b in zip(gens, gens[1:])
        ]
        resumed = gens[1:]
        restart_overhead = (
            sum(gaps)
            + sum(g.get("bringup_s", 0.0) + g.get("restore_s", 0.0)
                  + g.get("compile_s", 0.0) + g.get("cache_load_s", 0.0)
                  for g in resumed)
            + sum(g.get("emergency_save_s", 0.0) for g in gens)
        )
        total = sum(g.get("total_s", 0.0) for g in gens) + sum(gaps)
        productive = sum(g.get("productive_step_s", 0.0) for g in gens)
        out = dict(entry)
        out["productive_frac"] = round(
            entry["productive_step_s"] / max(entry["total_s"], 1e-9), 6
        )
        out["generations"] = gens
        out["cumulative"] = {
            "wall_s": round(total, 6),
            "productive_step_s": round(productive, 6),
            "restart_gap_s": round(sum(gaps), 6),
            "restart_overhead_s": round(restart_overhead, 6),
            "repair_overhead_s": round(
                sum(g.get("repair_s", 0.0) + g.get("repair_replay_s", 0.0)
                    for g in gens), 6
            ),
            "repairs": sum(int(g.get("repairs", 0) or 0) for g in gens),
            "productive_frac": round(
                productive / total if total > 0 else 0.0, 6
            ),
        }
        return out
