"""Supervised restart: backoff, restart budget, generation accounting.

``tpudist.launch --max_restarts`` used to restart instantly on ANY
non-zero exit — no backoff, no budget, and no way to tell "preempted,
resume me" from "crashing deterministically, stop". This module is the
policy half of the upgraded launcher (the spawn/reap half stays in
``tpudist/launch.py``):

- **restartable fast path**: exit codes in :data:`~tpudist.resilience
  .exitcodes.RESTARTABLE` (75 preempted, 76 watchdog hang, 77
  repair-restart) mean the trainer persisted its state and *asked* to be
  relaunched — they restart promptly regardless of ``--max_restarts``,
  bounded only by the budget window below.
- **crash path**: any other non-zero exit restarts only while the legacy
  ``max_restarts`` attempt counter allows, with exponential backoff +
  jitter between attempts (a crashing fleet must not hammer the
  coordinator port / checkpoint dir in lockstep).
- **restart budget**: at most N restarts (of either kind) per rolling
  window of M seconds — the circuit breaker that makes a
  deterministically-crashing (or instantly-re-preempted) job exhaust its
  budget and exit non-zero instead of spinning forever.
- **generation counter**: each world launched gets ``generation = n``
  exported as ``TPUDIST_RESTART_GENERATION``, so heartbeats, telemetry
  segments and run reports are attributable across the lives of one job.

Pure policy objects (:class:`BackoffPolicy`, :class:`RestartBudget`,
:func:`classify`) are deterministic/injectable for unit tests; the
:class:`Supervisor` loop takes the world-runner as a callable.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import sys
import time
from typing import Callable

from tpudist.resilience.exitcodes import (
    EXIT_HISTORY_ENV,
    EXIT_INTERRUPT,
    EXIT_OK,
    is_restartable,
)

__all__ = [
    "BackoffPolicy",
    "RestartBudget",
    "Supervisor",
    "classify",
]


def classify(rc: int) -> str:
    """``"ok"`` | ``"stop"`` (operator interrupt) | ``"restartable"``
    (deliberate checkpoint-and-exit) | ``"crash"`` (everything else,
    including signal deaths, which ``Popen`` reports as negative)."""
    if rc == EXIT_OK:
        return "ok"
    if rc == EXIT_INTERRUPT:
        return "stop"
    if is_restartable(rc):
        return "restartable"
    return "crash"


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """``base · 2^(attempt-1)`` capped at ``max_s``, with ±``jitter``
    multiplicative noise (a fleet of launchers restarting in lockstep
    would otherwise stampede the rendezvous port every cycle)."""

    base_s: float = 1.0
    max_s: float = 60.0
    jitter: float = 0.5

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        """Seconds to sleep before crash-restart number ``attempt``
        (1-based); 0 for attempt <= 0."""
        if attempt <= 0 or self.base_s <= 0:
            return 0.0
        d = min(self.base_s * (2.0 ** (attempt - 1)), self.max_s)
        return d * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


class RestartBudget:
    """At most ``max_restarts`` restarts per rolling ``window_s`` seconds.

    ``allow()`` prunes expired entries and answers; ``record()`` charges
    one restart. ``max_restarts <= 0`` or ``window_s <= 0`` disables the
    budget (always allowed) — the launcher's legacy behavior."""

    def __init__(self, max_restarts: int, window_s: float, *,
                 clock: Callable[[], float] = time.monotonic):
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self._clock = clock
        self._stamps: collections.deque[float] = collections.deque()

    def _prune(self) -> None:
        now = self._clock()
        while self._stamps and now - self._stamps[0] > self.window_s:
            self._stamps.popleft()

    def allow(self) -> bool:
        if self.max_restarts <= 0 or self.window_s <= 0:
            return True
        self._prune()
        return len(self._stamps) < self.max_restarts

    def record(self) -> None:
        self._stamps.append(self._clock())

    def used(self) -> int:
        self._prune()
        return len(self._stamps)


class Supervisor:
    """Drive ``run_world(generation) -> rc`` until done.

    ``stop`` is polled between generations (the launcher's SIGTERM flag):
    an operator stop returns the last rc without restarting, whatever the
    code said. ``sleep``/``rng`` are injectable for tests; ``log`` writes
    one line per decision (stderr by default — the launcher's channel).
    """

    def __init__(
        self,
        run_world: Callable[[int], int],
        *,
        max_restarts: int = 0,
        budget: RestartBudget | None = None,
        backoff: BackoffPolicy | None = None,
        stop: Callable[[], bool] | None = None,
        first_generation: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        rng: random.Random | None = None,
        log: Callable[[str], None] | None = None,
        environ=None,
    ):
        self._run_world = run_world
        self.max_restarts = int(max_restarts)
        self.budget = budget or RestartBudget(0, 0.0)
        self.backoff = backoff or BackoffPolicy()
        self._stop = stop or (lambda: False)
        self.generation = int(first_generation)
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._log = log or (
            lambda m: print(m, file=sys.stderr, flush=True)
        )
        # the per-generation exit-code record, oldest first — exported to
        # every RELAUNCHED world as TPUDIST_EXIT_HISTORY so the run
        # report can reconstruct the incident timeline in one file
        import os

        self._environ = os.environ if environ is None else environ
        self.exit_history: list[int] = []

    def run(self) -> int:
        crash_attempt = 0
        while True:
            rc = self._run_world(self.generation)
            self.exit_history.append(int(rc))
            kind = classify(rc)
            if kind in ("ok", "stop") or self._stop():
                return rc
            if not self.budget.allow():
                self._log(
                    f"tpudist.launch: restart budget exhausted "
                    f"({self.budget.used()} restarts in the last "
                    f"{self.budget.window_s:.0f}s window); giving up rc={rc}"
                )
                return rc
            if kind == "restartable":
                # the trainer persisted state and asked to come back: no
                # backoff (real preemptions are minutes apart; a tight
                # 75-loop is what the budget window is for), and the
                # crash streak resets — a clean preempt is not a crash
                crash_attempt = 0
                delay = 0.0
                self._log(
                    f"tpudist.launch: world exited rc={rc} (restartable); "
                    f"restarting generation {self.generation + 1}"
                )
            else:  # crash
                if crash_attempt >= self.max_restarts:
                    return rc
                crash_attempt += 1
                delay = self.backoff.delay_s(crash_attempt, self._rng)
                # message shape predates this module — keep it: operators
                # (and tests) grep for "restarting (a/N)"
                self._log(
                    f"tpudist.launch: world exited rc={rc}; restarting "
                    f"({crash_attempt}/{self.max_restarts})"
                    + (f" after {delay:.1f}s backoff" if delay else "")
                )
            self.budget.record()
            if delay > 0:
                self._sleep(delay)
            if self._stop():
                # an operator stop that landed during the backoff sleep
                # must win over the pending restart
                return rc
            self.generation += 1
            # export the record BEFORE the relaunch: _run_world copies
            # the environment into each child, so the next generation's
            # run report sees every predecessor's exit code
            self._environ[EXIT_HISTORY_ENV] = ",".join(
                str(c) for c in self.exit_history
            )
