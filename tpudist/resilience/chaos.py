"""Fault injection: deterministic crash / hang / SIGTERM at a chosen step.

The recovery path deserves the same adversarial testing the detection
path got (PR 7's simulated hangs and injected stragglers): this harness
injects the three failure shapes the resilience layer exists for, at an
exact step boundary, identically from unit tests, the 2-process emulated
world, ``main.py --chaos``, and the bench's recovery leg.

Spec grammar (``ChaosSpec.parse``)::

    <kind>[:<seconds>]@<step>[@<generation>]

    crash@12        raise ChaosCrash after step 12 completes (gen 0 only)
    sigterm@12      SIGTERM self after step 12 (the preemption drill)
    hang:600@12     block the loop 600 s after step 12 (watchdog food)
    corrupt@12      truncate the NEWEST checkpoint's files after step 12,
                    then crash — the die-mid-write drill that the
                    corrupt-checkpoint fallback (``Checkpointer.restore``
                    walking back to the previous step) must absorb
    crash@5@*       crash at step 5 in EVERY generation — the
                    deterministic-crash loop that must exhaust the
                    supervisor's restart budget, not spin

The generation field defaults to ``0``: an injected incident happens once,
in the first life of the job, and the relaunched generation — which
resumes AT the trigger step — must not re-fire it. ``*`` fires in every
generation (deterministic bugs don't go away on restart). ``fit()`` calls
:meth:`ChaosInjector.maybe_fire` with the number of COMPLETED steps at
each loop boundary, before dispatching the next step — so ``sigterm@k``
yields an emergency checkpoint at exactly step ``k`` and a resume at
``k+1``.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time

from tpudist.resilience.exitcodes import restart_generation

__all__ = ["ChaosCrash", "ChaosSpec", "ChaosInjector", "make_injector",
           "corrupt_latest_checkpoint"]

KINDS = ("crash", "hang", "sigterm", "corrupt")
DEFAULT_HANG_S = 3600.0


class ChaosCrash(RuntimeError):
    """The injected deterministic crash — a real exception through the
    real crash path (fit's handler, the run report's ``crashed:`` status,
    the launcher's non-restartable exit)."""


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    kind: str
    step: int
    duration_s: float = DEFAULT_HANG_S
    generation: int | None = 0  # None = every generation ("*")

    @classmethod
    def parse(cls, spec: str) -> "ChaosSpec":
        parts = str(spec).strip().split("@")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"chaos spec {spec!r} is not '<kind>[:<seconds>]@<step>"
                f"[@<generation>|@*]'"
            )
        head, step_s = parts[0], parts[1]
        kind, _, dur = head.partition(":")
        if kind not in KINDS:
            raise ValueError(
                f"chaos kind {kind!r} not in {KINDS} (spec {spec!r})"
            )
        duration = float(dur) if dur else DEFAULT_HANG_S
        if dur and kind != "hang":
            raise ValueError(
                f"only 'hang' takes a duration (spec {spec!r})"
            )
        gen: int | None = 0
        if len(parts) == 3:
            gen = None if parts[2] == "*" else int(parts[2])
        return cls(kind=kind, step=int(step_s), duration_s=duration,
                   generation=gen)


class ChaosInjector:
    """One-shot trigger bound to this process's restart generation."""

    def __init__(self, spec: ChaosSpec, *, generation: int | None = None,
                 sleep=time.sleep, kill=os.kill):
        self.spec = spec
        self.generation = (
            restart_generation() if generation is None else int(generation)
        )
        self.fired = False
        self._sleep = sleep
        self._kill = kill
        # the corrupt drill's target; fit() binds its checkpoint_dir
        self.checkpoint_dir = None
        self._wait = None

    def bind(self, checkpoint_dir, wait=None) -> "ChaosInjector":
        """Attach the run's checkpoint dir (the ``corrupt`` kind's
        target) and optionally the checkpointer's ``wait`` (so the drill
        corrupts a DETERMINISTIC step: the newest save is made durable
        before the truncation, instead of racing the async commit);
        chained so ``make_injector(...).bind(dir)`` reads naturally.
        No-op for the other kinds."""
        self.checkpoint_dir = checkpoint_dir
        self._wait = wait
        return self

    def maybe_fire(self, completed_step: int) -> bool:
        """Fire once when ``completed_step`` reaches the spec's step in an
        armed generation. Returns True if it fired (crash raises
        instead)."""
        if self.fired or completed_step < self.spec.step:
            return False
        if (self.spec.generation is not None
                and self.generation != self.spec.generation):
            return False
        self.fired = True
        if self.spec.kind == "crash":
            raise ChaosCrash(
                f"chaos: injected crash after step {completed_step} "
                f"(generation {self.generation})"
            )
        if self.spec.kind == "hang":
            self._sleep(self.spec.duration_s)
            return True
        if self.spec.kind == "corrupt":
            if self._wait is not None:
                self._wait()  # settle async saves: corrupt a committed step
            corrupt_latest_checkpoint(self.checkpoint_dir)
            # then die the way a real mid-write preemption does: a hard
            # crash, so the supervisor's relaunch exercises the fallback
            # walk end to end
            raise ChaosCrash(
                f"chaos: corrupted newest checkpoint after step "
                f"{completed_step} (generation {self.generation})"
            )
        # sigterm: the preemption drill — the signal lands on this very
        # process; with fit()'s PreemptionGuard installed the flag is set
        # before the next step dispatches
        self._kill(os.getpid(), signal.SIGTERM)
        return True


def corrupt_latest_checkpoint(checkpoint_dir) -> int:
    """Truncate every file of the NEWEST step dir under
    ``checkpoint_dir`` to half its size — the torn state a preemption
    landing mid-checkpoint-write leaves behind. The dir itself survives
    (so ``latest_step`` still points at it: exactly the poisoned-resume
    scenario the fallback walk exists for). Returns the corrupted step."""
    from pathlib import Path

    from tpudist.checkpoint import latest_step

    if checkpoint_dir is None:
        raise ChaosCrash(
            "chaos: corrupt@step needs a checkpoint_dir (fit binds it; "
            "standalone injectors use .bind(dir))"
        )
    step = latest_step(checkpoint_dir)
    if step is None:
        raise ChaosCrash(
            f"chaos: corrupt@step found no checkpoint under "
            f"{checkpoint_dir} to corrupt — schedule it after the first "
            "save (checkpoint_every)"
        )
    step_dir = Path(checkpoint_dir) / str(step)
    for f in sorted(p for p in step_dir.rglob("*") if p.is_file()):
        size = f.stat().st_size
        with open(f, "r+b") as fh:
            fh.truncate(size // 2)
    return step


def make_injector(chaos) -> ChaosInjector | None:
    """``fit()``'s coercion point: None | spec string | ChaosSpec |
    ready-made ChaosInjector."""
    if chaos is None:
        return None
    if isinstance(chaos, ChaosInjector):
        return chaos
    if isinstance(chaos, ChaosSpec):
        return ChaosInjector(chaos)
    return ChaosInjector(ChaosSpec.parse(chaos))
