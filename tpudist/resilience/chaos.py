"""Fault injection: deterministic crash / hang / SIGTERM / SDC at a step.

The recovery path deserves the same adversarial testing the detection
path got (PR 7's simulated hangs and injected stragglers): this harness
injects the failure shapes the resilience layer exists for, at an exact
step boundary, identically from unit tests, the 2-process emulated world,
``main.py --chaos``, and the bench's recovery legs.

Spec grammar (``ChaosSpec.parse``; ``parse_chaos`` accepts a
comma-separated list so one drill can compose, e.g., an SDC with a later
spike — ``"bitflip@10,nanburst:3@20"``)::

    <kind>[:<n>]@<step>[@<generation>]

    crash@12        raise ChaosCrash after step 12 completes (gen 0 only)
    sigterm@12      SIGTERM self after step 12 (the preemption drill)
    hang:600@12     block the loop 600 s after step 12 (watchdog food)
    corrupt@12      truncate the NEWEST checkpoint's files after step 12,
                    then crash — the die-mid-write drill that the
                    corrupt-checkpoint fallback (``Checkpointer.restore``
                    walking back to the previous step) must absorb
    bitflip@12      flip ONE low mantissa bit of one element of one
                    data-replica's copy of a replicated param leaf after
                    step 12 — the silent-data-corruption signature the
                    replica-divergence probe (and the repair loop riding
                    it) exists to catch; training continues numerically
                    almost unchanged, which is exactly the danger
    nanburst:3@12   poison the input batches of steps 13..15 with NaNs —
                    THREE consecutive non-finite steps, defeating the
                    single-step ``guard_nonfinite`` skip (the repair
                    loop's skip-streak trigger); ``:n`` defaults to 1
    crash@5@*       crash at step 5 in EVERY generation — the
                    deterministic-crash loop that must exhaust the
                    supervisor's restart budget, not spin

The generation field defaults to ``0``: an injected incident happens once,
in the first life of the job, and the relaunched generation — which
resumes AT the trigger step — must not re-fire it. ``*`` fires in every
generation (deterministic bugs don't go away on restart) — and, for the
repair drills, :meth:`ChaosInjector.rearm` re-arms ``@*`` specs after an
in-process repair too, because a deterministic bug doesn't go away on a
rollback either. ``fit()`` calls :meth:`ChaosInjector.maybe_fire` (and
:meth:`maybe_flip` for ``bitflip``) with the number of COMPLETED steps at
each loop boundary, before dispatching the next step — so ``sigterm@k``
yields an emergency checkpoint at exactly step ``k`` and a resume at
``k+1``; ``nanburst`` rides :meth:`wrap_batches` around the input stream
instead (it poisons data, not a boundary).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import time

from tpudist.resilience.exitcodes import restart_generation

__all__ = ["ChaosCrash", "ChaosSpec", "ChaosInjector", "make_injector",
           "parse_chaos", "corrupt_latest_checkpoint", "flip_param_bit"]

KINDS = ("crash", "hang", "sigterm", "corrupt", "bitflip", "nanburst")
#: kinds that fire at a step boundary through maybe_fire (bitflip has its
#: own state-mutating hook, nanburst wraps the input stream)
BOUNDARY_KINDS = ("crash", "hang", "sigterm", "corrupt")
DEFAULT_HANG_S = 3600.0


class ChaosCrash(RuntimeError):
    """The injected deterministic crash — a real exception through the
    real crash path (fit's handler, the run report's ``crashed:`` status,
    the launcher's non-restartable exit)."""


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    kind: str
    step: int
    duration_s: float = DEFAULT_HANG_S
    generation: int | None = 0  # None = every generation ("*")
    count: int = 1  # nanburst only: consecutive poisoned steps

    @classmethod
    def parse(cls, spec: str) -> "ChaosSpec":
        parts = str(spec).strip().split("@")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"chaos spec {spec!r} is not '<kind>[:<n>]@<step>"
                f"[@<generation>|@*]'"
            )
        head, step_s = parts[0], parts[1]
        kind, _, dur = head.partition(":")
        if kind not in KINDS:
            raise ValueError(
                f"chaos kind {kind!r} not in {KINDS} (spec {spec!r})"
            )
        duration = float(dur) if dur and kind == "hang" else DEFAULT_HANG_S
        count = 1
        if kind == "nanburst" and dur:
            count = int(dur)
            if count < 1:
                raise ValueError(
                    f"nanburst count must be >= 1 (spec {spec!r})"
                )
        if dur and kind not in ("hang", "nanburst"):
            raise ValueError(
                f"only 'hang' (seconds) and 'nanburst' (step count) take "
                f"a ':<n>' field (spec {spec!r})"
            )
        gen: int | None = 0
        if len(parts) == 3:
            gen = None if parts[2] == "*" else int(parts[2])
        return cls(kind=kind, step=int(step_s), duration_s=duration,
                   generation=gen, count=count)


def parse_chaos(spec: str) -> list[ChaosSpec]:
    """One ``--chaos`` string → specs. Single-spec strings parse exactly
    as before (byte-compatible grammar); commas compose several injections
    into one drill."""
    out = [ChaosSpec.parse(p) for p in str(spec).split(",") if p.strip()]
    if not out:
        raise ValueError(f"chaos spec {spec!r} names no injection")
    return out


class ChaosInjector:
    """One-shot triggers bound to this process's restart generation.

    Accepts one spec or a list (``parse_chaos``); each spec fires at most
    once per arming (``rearm`` re-arms the ``@*`` deterministic-bug specs
    after an in-process repair). ``spec`` keeps the single-spec view for
    the common case; ``fired`` is True once every spec has fired.
    """

    def __init__(self, spec, *, generation: int | None = None,
                 sleep=time.sleep, kill=os.kill):
        specs = [spec] if isinstance(spec, ChaosSpec) else list(spec)
        if not specs:
            raise ValueError("ChaosInjector needs at least one spec")
        self.specs: list[ChaosSpec] = specs
        self.spec = specs[0]
        self._fired = [False] * len(specs)
        self.generation = (
            restart_generation() if generation is None else int(generation)
        )
        self._sleep = sleep
        self._kill = kill
        # the corrupt drill's target; fit() binds its checkpoint_dir
        self.checkpoint_dir = None
        self._wait = None

    @property
    def fired(self) -> bool:
        return all(self._fired)

    def _armed(self, sp: ChaosSpec) -> bool:
        return sp.generation is None or self.generation == sp.generation

    def rearm(self) -> None:
        """Re-arm the ``@*`` (every-generation) specs — called by fit()'s
        repair handler: a deterministic bug doesn't go away on a rollback
        any more than on a restart, so the drill must keep biting until
        the repair budget circuit-breaks. Generation-pinned specs stay
        one-shot (a transient incident repaired is an incident gone)."""
        for i, sp in enumerate(self.specs):
            if sp.generation is None:
                self._fired[i] = False

    def bind(self, checkpoint_dir, wait=None) -> "ChaosInjector":
        """Attach the run's checkpoint dir (the ``corrupt`` kind's
        target) and optionally the checkpointer's ``wait`` (so the drill
        corrupts a DETERMINISTIC step: the newest save is made durable
        before the truncation, instead of racing the async commit);
        chained so ``make_injector(...).bind(dir)`` reads naturally.
        No-op for the other kinds."""
        self.checkpoint_dir = checkpoint_dir
        self._wait = wait
        return self

    def maybe_fire(self, completed_step: int) -> bool:
        """Fire due boundary-kind specs once ``completed_step`` reaches
        their step in an armed generation. Returns True if any fired
        (crash/corrupt raise instead)."""
        fired_any = False
        for i, sp in enumerate(self.specs):
            if sp.kind not in BOUNDARY_KINDS:
                continue
            if (self._fired[i] or completed_step < sp.step
                    or not self._armed(sp)):
                continue
            self._fired[i] = True
            fired_any = True
            if sp.kind == "crash":
                raise ChaosCrash(
                    f"chaos: injected crash after step {completed_step} "
                    f"(generation {self.generation})"
                )
            if sp.kind == "hang":
                self._sleep(sp.duration_s)
                continue
            if sp.kind == "corrupt":
                if self._wait is not None:
                    # settle async saves: corrupt a committed step
                    self._wait()
                corrupt_latest_checkpoint(self.checkpoint_dir)
                # then die the way a real mid-write preemption does: a
                # hard crash, so the supervisor's relaunch exercises the
                # fallback walk end to end
                raise ChaosCrash(
                    f"chaos: corrupted newest checkpoint after step "
                    f"{completed_step} (generation {self.generation})"
                )
            # sigterm: the preemption drill — the signal lands on this
            # very process; with fit()'s PreemptionGuard installed the
            # flag is set before the next step dispatches
            self._kill(os.getpid(), signal.SIGTERM)
        return fired_any

    def maybe_flip(self, completed_step: int, state, mesh=None):
        """The ``bitflip`` drill: at its step boundary, return ``state``
        with one mantissa bit flipped in ONE data-replica's copy of a
        replicated param leaf (:func:`flip_param_bit`). No-op (state
        returned unchanged) for other kinds / unarmed generations."""
        for i, sp in enumerate(self.specs):
            if sp.kind != "bitflip":
                continue
            if (self._fired[i] or completed_step < sp.step
                    or not self._armed(sp)):
                continue
            self._fired[i] = True
            state, info = flip_param_bit(state, mesh=mesh)
            print(
                f"chaos: bitflip after step {completed_step} — {info}",
                file=sys.stderr, flush=True,
            )
        return state

    def wrap_batches(self, batches, first_step: int):
        """The ``nanburst`` drill: wrap an epoch's batch iterator so the
        batches feeding steps ``(spec.step, spec.step + count]`` carry a
        NaN in their first float leaf — ``count`` CONSECUTIVE non-finite
        steps, which a single-step ``guard_nonfinite`` skip absorbs one
        at a time but never escapes (the repair loop's skip-streak
        trigger exists for exactly this shape). ``first_step`` is the
        step the iterator's first batch will train (fit passes
        ``global_step + 1`` when it builds each epoch's stream; prefetch
        consuming ahead is fine — the mapping is positional)."""
        bursts = [
            i for i, sp in enumerate(self.specs)
            if sp.kind == "nanburst" and self._armed(sp)
        ]
        if not bursts:
            return batches

        def _gen():
            for j, batch in enumerate(batches):
                s = first_step + j  # the step this batch trains
                for i in bursts:
                    sp = self.specs[i]
                    if sp.step < s <= sp.step + sp.count:
                        self._fired[i] = True
                        batch = _poison_batch(batch, s)
                yield batch

        return _gen()


def _poison_batch(batch, step: int):
    """One NaN in the first float leaf — enough to make the loss (and the
    whole backward) non-finite. Copies the poisoned leaf only."""
    import numpy as np

    out = dict(batch)
    for k, v in batch.items():
        if k.startswith("_"):
            continue
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            arr = np.array(arr, copy=True)
            arr.reshape(-1)[:1] = np.nan
            out[k] = arr
            return out
    raise ChaosCrash(
        f"chaos: nanburst at step {step} found no float batch leaf to "
        "poison (integer-token batches have no NaN representation — "
        "drill spikes on a float-input model, or use bitflip for SDCs)"
    )


def flip_param_bit(state, mesh=None, *, bit: int = 0):
    """Flip one mantissa bit of element 0 of ONE data-replica's copy of
    the first replicated float param leaf — the SDC signature: every
    replica still *claims* the same (replicated) array, but one device's
    buffer now disagrees by a single bit, which only the bit-exact
    replica-divergence probe (``tpudist.parallel.dp
    .make_divergence_probe``) can see. Returns ``(new_state, info)``.

    The corrupted replica is the LAST device of the mesh (or of the
    leaf's device set) — never replica 0, which the probe compares
    against. Raises :class:`ChaosCrash` when no data-replicated float
    leaf exists (a fully TP/FSDP-sharded state has no redundant copy to
    corrupt — the drill would be meaningless).
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    import jax.tree_util as jtu

    leaves = jtu.tree_flatten_with_path(state.params)[0]
    target_leaf = None
    elt = 0
    for path, leaf in leaves:
        if not isinstance(leaf, jax.Array):
            continue
        if not jnp.issubdtype(leaf.dtype, jnp.floating) or leaf.size < 1:
            continue
        if not leaf.sharding.is_fully_replicated:
            continue
        if target_leaf is None:
            target_leaf = (path, leaf)
        # prefer a NONZERO element: flipping a mantissa bit of 0.0 makes
        # a denormal (~1e-45) that the next optimizer add absorbs by
        # rounding — the "SDC" would silently self-heal before any probe
        # cadence, which is not how a flipped weight bit behaves
        nz = np.flatnonzero(np.asarray(leaf.addressable_shards[0].data))
        if nz.size:
            target_leaf = (path, leaf)
            elt = int(nz[0])
            break
    if target_leaf is None:
        raise ChaosCrash(
            "chaos: bitflip found no fully-replicated float param leaf — "
            "nothing redundant to corrupt (TP/FSDP-sharded states keep "
            "one copy; use nanburst or corrupt instead)"
        )
    path, leaf = target_leaf
    if mesh is not None:
        target_dev = mesh.devices.flat[-1]
    else:
        target_dev = sorted(leaf.sharding.device_set, key=lambda d: d.id)[-1]
    itemsize = np.dtype(leaf.dtype).itemsize
    uview = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[itemsize]
    bufs, flipped = [], False
    for sh in leaf.addressable_shards:
        data = np.array(sh.data)  # a full copy: the leaf is replicated
        if sh.device == target_dev:
            u = data.view(uview)
            u.reshape(-1)[elt] ^= np.asarray(1 << bit, uview)
            flipped = True
        bufs.append(jax.device_put(data, sh.device))
    new_leaf = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs
    )
    flat, treedef = jtu.tree_flatten(state.params)
    for i, old in enumerate(flat):
        if old is leaf:
            flat[i] = new_leaf
            break
    info = {
        "leaf": "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                         for k in path),
        "device": str(target_dev),
        "element": int(elt),
        "bit": int(bit),
        # multi-process: only the process owning target_dev flips; the
        # others rebuild identical buffers (the flip is still global —
        # the array IS that device's buffer on that device)
        "flipped_locally": bool(flipped),
    }
    return state.replace(params=jtu.tree_unflatten(treedef, flat)), info


def corrupt_latest_checkpoint(checkpoint_dir) -> int:
    """Truncate every file of the NEWEST step dir under
    ``checkpoint_dir`` to half its size — the torn state a preemption
    landing mid-checkpoint-write leaves behind. The dir itself survives
    (so ``latest_step`` still points at it: exactly the poisoned-resume
    scenario the fallback walk exists for). Returns the corrupted step."""
    from pathlib import Path

    from tpudist.checkpoint import latest_step

    if checkpoint_dir is None:
        raise ChaosCrash(
            "chaos: corrupt@step needs a checkpoint_dir (fit binds it; "
            "standalone injectors use .bind(dir))"
        )
    step = latest_step(checkpoint_dir)
    if step is None:
        raise ChaosCrash(
            f"chaos: corrupt@step found no checkpoint under "
            f"{checkpoint_dir} to corrupt — schedule it after the first "
            "save (checkpoint_every)"
        )
    step_dir = Path(checkpoint_dir) / str(step)
    for f in sorted(p for p in step_dir.rglob("*") if p.is_file()):
        size = f.stat().st_size
        with open(f, "r+b") as fh:
            fh.truncate(size // 2)
    return step


def make_injector(chaos) -> ChaosInjector | None:
    """``fit()``'s coercion point: None | spec string (single or
    comma-separated) | ChaosSpec | list of ChaosSpecs | ready-made
    ChaosInjector."""
    if chaos is None:
        return None
    if isinstance(chaos, ChaosInjector):
        return chaos
    if isinstance(chaos, ChaosSpec):
        return ChaosInjector(chaos)
    if isinstance(chaos, (list, tuple)):
        return ChaosInjector(list(chaos))
    return ChaosInjector(parse_chaos(chaos))
