"""Graceful preemption: trap SIGTERM/SIGINT as a signal-safe flag.

A TPU preemption or maintenance event delivers SIGTERM with a grace
window; the default disposition kills the world mid-step and loses
everything since the last cadence checkpoint. :class:`PreemptionGuard`
turns the signal into a flag that ``fit()`` checks at step boundaries: the
in-flight step finishes, a *synchronous* emergency checkpoint is written,
telemetry and the run report flush with ``exit_reason="preempted"``, and
the process exits with :data:`~tpudist.resilience.exitcodes
.EXIT_PREEMPTED` (75) — the code the supervisor restarts on.

Signal-safety: the handler does nothing but assign one attribute (an
atomic bytecode under the GIL, safe in a signal context — no locks, no
allocation-heavy work, no I/O). Everything expensive happens later, on
the main thread, at a step boundary.

Escalation: repeated signals while the graceful path runs are absorbed up
to :data:`PreemptionGuard.MAX_ABSORBED`; past that the original
disposition is restored and re-raised, so an operator (or the scheduler's
grace-expiry SIGKILL escalation) can always kill a wedged shutdown —
"graceful" must never mean "unkillable".
"""

from __future__ import annotations

import signal
import threading

from tpudist.resilience.exitcodes import EXIT_PREEMPTED


class Preempted(SystemExit):
    """Raised by ``fit()`` after a completed graceful-preemption shutdown
    (emergency checkpoint durable, telemetry flushed).

    A :class:`SystemExit` subclass carrying ``code == EXIT_PREEMPTED``:
    un-caught, the interpreter exits 75 and the supervisor resumes the
    job — ``main.py`` and the example trainers need no handler at all.
    Library callers catch it explicitly: ``state`` and ``losses`` carry
    the final train state and per-step loss history (what ``fit`` would
    have returned), so a notebook run interrupted WITHOUT a
    ``checkpoint_dir`` still hands the trained state back instead of
    losing it with the exception.
    """

    def __init__(self, signum: int | None = None, step: int | None = None,
                 *, state=None, losses=None):
        super().__init__(EXIT_PREEMPTED)
        self.signum = signum
        self.step = step
        self.state = state
        self.losses = losses

    def __str__(self) -> str:
        name = (
            signal.Signals(self.signum).name
            if self.signum is not None else "signal"
        )
        return (
            f"preempted by {name} at step {self.step}; emergency state "
            f"persisted, exiting {EXIT_PREEMPTED} for a supervised resume"
        )


class PreemptionGuard:
    """Context manager installing the flag-setting handlers.

    ``signals`` defaults to (SIGTERM, SIGINT) — the scheduler's preemption
    notice and the operator's Ctrl-C both mean "stop cleanly, keep the
    work". Installation only succeeds on the main thread (CPython's
    constraint); elsewhere — or with ``enabled=False`` — the guard is
    inert and :attr:`tripped` stays ``None`` forever, so ``fit()`` can
    hold one code path for both cases.
    """

    MAX_ABSORBED = 3

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT), *,
                 enabled: bool = True):
        self._signals = tuple(signals)
        self._enabled = enabled
        self._previous: dict[int, object] = {}
        self._hits = 0
        self.tripped: int | None = None
        self.active = False

    # the handler body: one attribute store each — async-signal-safe by
    # construction (no locks, no allocation beyond the int boxing)
    def _handle(self, signum, frame):
        self._hits += 1
        if self.tripped is None:
            self.tripped = signum
            return
        if self._hits > self.MAX_ABSORBED:
            # a wedged graceful path must stay killable: restore whatever
            # disposition we displaced and re-deliver
            old = self._previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, old)
            if callable(old):
                old(signum, frame)
            else:
                signal.raise_signal(signum)

    def __enter__(self) -> "PreemptionGuard":
        if not self._enabled:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal would raise ValueError; stay inert
        try:
            for s in self._signals:
                self._previous[s] = signal.signal(s, self._handle)
        except (ValueError, OSError):
            # partially installed (embedded interpreter, exotic platform):
            # roll back what went in and stay inert
            self._restore()
            return self
        self.active = True
        return self

    def _restore(self) -> None:
        for s, old in self._previous.items():
            try:
                signal.signal(s, old)
            except (ValueError, OSError):
                pass
        self._previous.clear()
        self.active = False

    def __exit__(self, *exc) -> None:
        self._restore()
