"""Device mesh construction and sharding helpers.

TPU-native replacement for the reference's NCCL communicator / process group
(/root/reference/main.py:34): instead of a flat rank set with explicit
collectives, tpudist arranges all devices into a named
:class:`jax.sharding.Mesh` and expresses parallelism as shardings over named
axes. The reference only has data parallelism (SURVEY.md §2.12), so the
default mesh is 1-D over axis ``"data"`` — but the mesh is N-D-ready so that
tensor/pipeline/sequence axes can be added without reshaping the framework.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

# Canonical axis names, in mesh order. Data-parallel is the outermost axis so
# that gradient all-reduce rides the largest ring.
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
PIPELINE_AXIS = "pipe"
TENSOR_AXIS = "tensor"
SEQUENCE_AXIS = "seq"
EXPERT_AXIS = "expert"

_AXIS_ORDER = (
    DATA_AXIS, FSDP_AXIS, PIPELINE_AXIS, EXPERT_AXIS, SEQUENCE_AXIS, TENSOR_AXIS
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``-1`` on an axis means "all remaining devices".

    Default is pure data parallelism over every visible device — the exact
    capability of the reference's DDP world (/root/reference/main.py:83).
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def axis_sizes(self, n_devices: int) -> dict[str, int]:
        sizes = {
            DATA_AXIS: self.data,
            FSDP_AXIS: self.fsdp,
            PIPELINE_AXIS: self.pipe,
            EXPERT_AXIS: self.expert,
            SEQUENCE_AXIS: self.seq,
            TENSOR_AXIS: self.tensor,
        }
        fixed = math.prod(v for v in sizes.values() if v != -1)
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wild}")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} wants {fixed} devices but {n_devices} are visible"
            )
        return sizes


def create_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the named device mesh.

    Axes of size 1 are kept (named, size-1) so sharding specs can always
    mention every canonical axis; XLA elides trivial collectives.

    With ``devices`` unset, placement is topology-aware: ``mesh_utils``
    orders chips so neighboring mesh coordinates are ICI neighbors (the
    collectives ride ICI rings, not arbitrary hops), and on multi-slice
    pods the ``data`` axis is laid across slices so only the gradient
    all-reduce crosses DCN while the model axes stay inside a slice.
    An explicit ``devices`` list keeps the caller's ordering verbatim.
    """
    config = config or MeshConfig()
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    sizes = config.axis_sizes(len(devices))
    shape = tuple(sizes[a] for a in _AXIS_ORDER)
    dev_array = None
    if not explicit:
        dev_array = _topology_mesh(shape, devices)
    if dev_array is None:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, _AXIS_ORDER)


def _topology_mesh(shape: tuple[int, ...], devices) -> np.ndarray | None:
    """ICI/DCN-aware device array, or None to fall back to plain reshape."""
    try:
        from jax.experimental import mesh_utils

        slices = {getattr(d, "slice_index", 0) for d in devices}
        n_slices = len(slices)
        data = shape[0]
        if n_slices > 1 and data % n_slices == 0:
            # DCN carries only the outer slice-count factor of 'data'; every
            # other axis (and the intra-slice share of 'data') stays on ICI
            dcn = (n_slices,) + (1,) * (len(shape) - 1)
            per_slice = (data // n_slices,) + shape[1:]
            return mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=devices
            )
        return mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception as e:  # unusual topologies: ordering is only a perf hint
        logger.info("topology-aware mesh unavailable (%s); using device order", e)
        return None


def batch_sharding(mesh: Mesh, *, extra_dims: int = 3) -> NamedSharding:
    """Sharding for a training batch: leading (batch) dim split over ``data``
    (and ``fsdp`` when present), remaining dims replicated.

    This is the TPU-native form of DistributedSampler's per-rank shard
    (/root/reference/main.py:53): the global batch is one logical array whose
    rows live on the device that will compute them.
    """
    spec = P((DATA_AXIS, FSDP_AXIS), *([None] * extra_dims))
    return NamedSharding(mesh, spec)


def largest_divisible_spec(
    shape, axis: str, size: int, *, min_size: int = 1024
) -> P:
    """PartitionSpec sharding the largest ``size``-divisible dim of
    ``shape`` over mesh axis ``axis`` — the ONE spec rule shared by
    ZeRO-style state sharding over ``data`` (``tpudist.optim.shard_state``)
    and ZeRO-3 param sharding over ``fsdp``
    (``tpudist.parallel.fsdp.fsdp_spec``).

    Leaves smaller than ``min_size`` elements (biases, norm scales,
    scalars) stay replicated — sharding them buys no memory and costs a
    collective. Returns ``P()`` when nothing qualifies (the caller decides
    whether to fall back to replication or to pad-and-reshape).
    """
    if size <= 1 or math.prod(shape) < min_size:
        return P()
    candidates = [(d, i) for i, d in enumerate(shape) if d % size == 0]
    if not candidates:
        return P()
    _, dim = max(candidates)
    spec = [None] * len(shape)
    spec[dim] = axis
    return P(*spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding — used for model parameters in plain DP,
    mirroring DDP's replicate-everywhere model (/root/reference/main.py:83).
    """
    return NamedSharding(mesh, P())


def data_parallel_size(mesh: Mesh) -> int:
    """Number of data-parallel replicas (the reference's ``world_size``,
    /root/reference/main.py:37, where one GPU = one replica)."""
    return mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]


def global_batch_sizes(
    global_batch: int, mesh: Mesh
) -> tuple[int, int]:
    """(per-replica batch, per-process batch) for a given global batch."""
    n = data_parallel_size(mesh)
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {n} replicas")
    per_replica = global_batch // n
    per_process = global_batch // jax.process_count()
    return per_replica, per_process


def put_sharded(x: np.ndarray, sharding: NamedSharding) -> jax.Array:
    """Stage one host-local array under ``sharding``.

    Single-process: a plain sharded ``device_put``. Multi-process: this
    process contributes its local shard and the result is the global logical
    array — the TPU-native equivalent of every DDP rank holding its own
    minibatch.
    """
    x = np.asarray(x)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, x)


def check_reserved_device_keys(batch) -> None:
    """Enforce the ``"_"``-prefix contract: reserved keys are per-step
    DEVICE operands (the DeviceCachedLoader's ``"_cache"``), so a host
    value under the prefix — a foreign loader using underscores for
    ordinary metadata — would silently bypass staging/padding; refuse it
    loudly instead. One home for the check used by ``shard_batch``,
    ``make_train_step.stage`` and the eval padding path."""
    if not isinstance(batch, dict):
        return
    bad = {
        k for k, v in batch.items()
        if k.startswith("_") and not isinstance(v, jax.Array)
    }
    if bad:
        raise TypeError(
            f"batch keys {sorted(bad)} start with '_' (the reserved "
            "device-operand prefix) but hold host values, which would "
            "bypass staging and padding — rename them, or device_put "
            "them if they really are per-step device operands"
        )


def shard_batch(batch, mesh: Mesh):
    """Place a host-local batch (numpy pytree) onto the mesh, sharded over
    the batch dimension.

    Dict keys starting with ``"_"`` are per-step device-resident operands
    (the DeviceCachedLoader's ``"_cache"`` contract — see
    ``tpudist.train._apply_input_transform``), not row data: they pass
    through untouched. Without the exemption, ``np.asarray`` would fetch
    the whole HBM cache to host and re-upload it batch-sharded on every
    batch. The exemption is for device-resident values ONLY
    (:func:`check_reserved_device_keys` refuses host values under the
    prefix)."""
    check_reserved_device_keys(batch)
    if isinstance(batch, dict):
        passthrough = {k: v for k, v in batch.items() if k.startswith("_")}
        rows = {k: v for k, v in batch.items() if k not in passthrough}
    else:
        passthrough, rows = {}, batch
    out = jax.tree_util.tree_map(
        lambda x: put_sharded(
            np.asarray(x), batch_sharding(mesh, extra_dims=np.ndim(x) - 1)
        ),
        rows,
    )
    if passthrough:
        out = {**out, **passthrough}
    return out
