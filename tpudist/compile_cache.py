"""AOT executable cache: a restarted world should not re-pay the trace.

PR 8's goodput accounting prices every restart as bringup + restore +
compile, and compile is the dominant recurring term (the reason the chaos
drill's recovery bound is set in minutes, not seconds): every relaunch of
the SAME program on the SAME hardware re-traces and re-compiles the train
step from scratch. XLA executables are serializable
(``jax.experimental.serialize_executable`` — the AOT-lowering workflow of
the TPUv4 pjit experience reports, PAPERS.md), so generation N can leave
its compiled step on disk and generation N+1 can load it while the
checkpoint restore is still streaming — tracing skipped entirely.

The cache is CONTENT-KEYED (:func:`step_key`): a SHA-256 over

- the device topology (platform/kind per device, process count, mesh
  axis names and sizes) — an executable is placement-specific;
- the program geometry: every train-state and staged-batch leaf's path,
  shape, dtype, and partition spec;
- the step configuration (``make_train_step``'s knobs: reduce method,
  fused set, telemetry/guard, grad_accum, remat, loss/model identity);
- the jax/jaxlib versions (an executable is not portable across them).

Anything the key cannot see but that changes GEOMETRY (a foreign loader
whose batches disagree with its probe, a topology the key hashed
differently) is handled by the contract, not the hash: the cached
executable is validated on first call and any input mismatch falls
through to the ordinary jit path with a telemetry ``warning``. The key
folds the model/loss IDENTITY (type + repr / qualname) precisely so
config-level changes move it — but a pure CODE edit with identical
geometry and identical identity (editing a loss function's body, or a
model whose repr doesn't expose the changed knob) is invisible to both
the key and the call-time check: bump the cache directory (or
``step_key``'s ``salt``) after such edits. When the model's repr is the
default address-bearing one the key degrades to type-only and ``fit``
emits a ``compile_cache_weak_key`` warning row saying exactly this. ``fit(compile_cache=dir)`` wires it up
(overlapping the deserialization with checkpoint restore) and the
one-shot ``compile_cache`` telemetry row records hit/miss/bytes/load_s
(docs/OBSERVABILITY.md); ``tpudist.resilience.goodput`` attributes a warm
first iteration to ``cache_load_s`` instead of mislabeling it
``compile_s``.

The serving engine reuses this store for its program inventory
(``ServeEngine(compile_cache=dir)``, docs/SERVING.md §5) with its own
fingerprint discipline: the engine's key covers the model identity,
params geometry, every scheduler knob — and, on a tensor-sharded engine
(``mesh=``, docs/SERVING.md §7), the mesh axis names/shape and the
tensor world, for the same reason ``step_key`` hashes the topology: an
executable lowered with committed ``NamedSharding`` arguments is
placement-specific, and a single-chip artifact must never warm-start a
sharded engine (or vice versa, or across different tensor worlds).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Callable

import jax
import numpy as np

__all__ = ["CompileCache", "model_identity", "step_key", "staged_example",
           "wrap_step"]

#: bump to invalidate every existing cache entry on a format change
SCHEMA = 1


def _leaf_rows(tree) -> list[list]:
    import jax.tree_util as jtu

    rows = []
    for path, leaf in jtu.tree_flatten_with_path(tree)[0]:
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        rows.append([
            jtu.keystr(path),
            list(getattr(leaf, "shape", ())),
            str(getattr(leaf, "dtype", type(leaf).__name__)),
            str(spec),
        ])
    return rows


def step_key(*, mesh, state, batch, config: dict, salt: str = "") -> str:
    """Content hash identifying one compiled train step on one topology.
    ``state``/``batch`` contribute shapes/dtypes/shardings only (values
    never matter to the executable); ``config`` is the step-builder's knob
    dict; ``salt`` lets a caller segregate entries it knows the key can't
    distinguish (e.g. two custom ``forward_loss`` closures with identical
    geometry)."""
    devices = [
        [d.platform, getattr(d, "device_kind", ""), int(d.process_index)]
        for d in mesh.devices.flat
    ]
    doc = {
        "schema": SCHEMA,
        "jax": jax.__version__,
        "jaxlib": getattr(
            __import__("jaxlib"), "__version__", "?"
        ),
        "topology": {
            "devices": devices,
            "process_count": int(jax.process_count()),
            "mesh_axes": list(mesh.axis_names),
            "mesh_shape": [int(mesh.shape[a]) for a in mesh.axis_names],
        },
        "state": _leaf_rows(state),
        "batch": _leaf_rows(batch),
        "config": {k: config[k] for k in sorted(config)},
        "salt": salt,
    }
    blob = json.dumps(doc, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:32]


def model_identity(model) -> str:
    """A process-stable identity for the model in the cache key: type
    qualname plus its repr — UNLESS the repr is the default
    address-bearing ``<X object at 0x...>``, which differs in every
    relaunched generation and would turn every lookup into a silent miss
    (unbounded orphan entries, the feature defeated with no warning).
    Flax modules and dataclasses print their config stably; anything
    else contributes its type only (callers who need finer distinction
    have ``step_key``'s ``salt``)."""
    ident = f"{type(model).__module__}.{type(model).__qualname__}"
    r = repr(model)
    if re.search(r" at 0x[0-9a-fA-F]+", r):
        return ident
    return f"{ident}:{r}"


def staged_example(step, loader):
    """A zeros-filled staged batch with exactly the shapes/shardings the
    real training batches will have (``step.stage`` applies the whole
    staging contract, grad-accumulation folding included) — what
    :meth:`CompileCache` keys and lowers against. ``None`` when the
    loader cannot be probed or stages device-resident operands (``"_"``
    keys ride outside the host batch and are not reconstructable from
    shapes) — the caller then skips the cache rather than guessing."""
    try:
        if hasattr(loader, "probe"):
            sample = loader.probe()
        else:
            it = iter(loader)
            if it is loader:
                # a single-shot iterator: pulling a sample here would
                # silently EAT the first training batch — decline the
                # cache instead of corrupting the data order
                return None
            sample = next(it)
        rows = int(loader.batch_size)
    except Exception:
        return None
    if any(str(k).startswith("_") for k in sample):
        return None
    if callable(getattr(loader, "input_transform", None)):
        # the device-cache loader family (tpudist.data.device_cache):
        # every REAL batch carries the HBM cache as a "_cache" operand,
        # but the probe deliberately describes the post-gather image row
        # (fit's init contract) — keying/lowering from it would fail on
        # the first real batch every generation. The in-graph-gather
        # contract IS the input_transform method; decline cleanly.
        return None
    fake = {
        k: np.zeros((rows,) + tuple(np.asarray(v).shape[1:]),
                    np.asarray(v).dtype)
        for k, v in sample.items()
    }
    try:
        return step.stage(fake)
    except Exception:
        return None


class _LoadHandle:
    """An in-flight background deserialization — started BEFORE the
    checkpoint restore so the two overlap; ``result()`` joins."""

    def __init__(self, fn: Callable[[], Any]):
        self.value = None
        self.error: Exception | None = None
        self.seconds = 0.0

        def run():
            t0 = time.perf_counter()
            try:
                self.value = fn()
            except Exception as exc:  # any failure = miss
                self.error = exc
            self.seconds = time.perf_counter() - t0

        self._thread = threading.Thread(
            target=run, name="tpudist-compile-cache-load", daemon=True
        )
        self._thread.start()

    def result(self):
        self._thread.join()
        return self.value


class CompileCache:
    """A directory of serialized step executables, one file per key
    (``<key>.aot`` payload + ``<key>.json`` human-readable sidecar).
    Every operation is fail-soft: a corrupt/alien/mismatched entry is a
    miss, a failed store is a warning — the cache may only ever cost
    time, never correctness."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.last_load_error: str | None = None

    def path_for(self, key: str) -> Path:
        return self.directory / f"{key}.aot"

    # -- load --------------------------------------------------------------

    def load(self, key: str):
        """Deserialize the executable stored under ``key`` or return
        ``None`` (miss/corrupt/version-mismatch — all fail-soft; the
        failure, if any, lands in ``last_load_error``)."""
        from jax.experimental import serialize_executable

        self.last_load_error = None
        p = self.path_for(key)
        if not p.exists():
            return None
        try:
            blob = pickle.loads(p.read_bytes())
            if blob.get("schema") != SCHEMA:
                return None
            return serialize_executable.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"]
            )
        except Exception as exc:
            self.last_load_error = f"{type(exc).__name__}: {exc}"[:300]
            return None

    def begin_load(self, key: str) -> _LoadHandle:
        """Start the deserialization on a side thread — fit() calls this
        before the checkpoint restore so the two IO-and-deserialize legs
        overlap instead of serializing."""
        return _LoadHandle(lambda: self.load(key))

    # -- store -------------------------------------------------------------

    def store(self, key: str, compiled, meta: dict | None = None) -> int:
        """Serialize ``compiled`` under ``key`` (atomic tmp+replace, one
        writer wins). Returns the payload size in bytes, 0 on any
        failure. Rank 0 only — serialization of a large step is real CPU
        and memory, and N-1 ranks would discard the blob (the telemetry
        row that reports the byte count is rank-0-only too)."""
        from jax.experimental import serialize_executable

        if jax.process_index() != 0:
            return 0
        try:
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            blob = pickle.dumps({
                "schema": SCHEMA,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
            fd, tmp = tempfile.mkstemp(
                dir=self.directory, prefix=f".{key}.", suffix=".tmp"
            )
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, self.path_for(key))
            self.path_for(key).with_suffix(".json").write_text(
                json.dumps({
                    "key": key,
                    "bytes": len(blob),
                    "jax": jax.__version__,
                    "created": time.time(),
                    **(meta or {}),
                })
            )
            return len(blob)
        except Exception:
            return 0

    # -- the whole bring-up path ------------------------------------------

    def finish(self, handle: _LoadHandle | None, step, state, staged,
               key: str, meta: dict | None = None):
        """Join the background load; on a miss, AOT-compile the step NOW
        (bring-up, where goodput attributes it honestly) and store it.
        Returns ``(executable_or_None, info)`` where ``info`` is the
        telemetry ``compile_cache`` row's payload."""
        info: dict[str, Any] = {"key": key, "hit": False, "bytes": 0,
                                "load_s": 0.0, "load_wait_s": 0.0,
                                "compile_s": 0.0, "store_s": 0.0}
        t_join = time.perf_counter()
        exe = handle.result() if handle is not None else None
        if handle is not None:
            # load_s: the deserialization's own duration (what the cache
            # actually cost in CPU terms); load_wait_s: how long THIS
            # thread blocked joining it — the part NOT hidden behind the
            # overlapped checkpoint restore, i.e. the load's contribution
            # to wall time. Goodput books the wait (its partition must
            # stay disjoint from restore_s); the telemetry row carries
            # both. The wait clamps to the load itself: an immediate join
            # also measures thread-startup/epilogue lag the load never
            # contained, and "wait <= load" is the row's invariant.
            info["load_s"] = round(handle.seconds, 6)
            info["load_wait_s"] = round(
                min(time.perf_counter() - t_join, handle.seconds), 6
            )
            if handle.error is not None:
                info["error"] = (
                    f"{type(handle.error).__name__}: {handle.error}"[:300]
                )
            elif self.last_load_error is not None:
                info["error"] = self.last_load_error
        if exe is not None:
            info["hit"] = True
            try:
                info["bytes"] = self.path_for(key).stat().st_size
            except OSError:
                pass
            return exe, info
        try:
            t0 = time.perf_counter()
            compiled = step.jitted.lower(state, staged).compile()
            info["compile_s"] = round(time.perf_counter() - t0, 6)
            t0 = time.perf_counter()
            info["bytes"] = self.store(key, compiled, meta)
            info["store_s"] = round(time.perf_counter() - t0, 6)
            return compiled, info
        except Exception as exc:
            # lowering/compiling outside the jit fast path failed (exotic
            # step configuration): fall through to ordinary tracing
            info["error"] = f"{type(exc).__name__}: {exc}"[:300]
            return None, info


def launder_restored(state):
    """Compat shim for a jax 0.4.x XLA:CPU wart (the same family as
    tests/conftest.py's persistent-cache notes): an AOT-DESERIALIZED
    executable donating orbax-restored buffers corrupts the heap
    (reproduced: segfault/"corrupted double-linked list" on the first
    step of a warm restart; 8 clean steps after this shim). Routing the
    restored state through a jitted identity replaces the orbax-created
    arrays with jit-produced ones, which the executable digests fine.
    One state copy at bring-up, and ONLY on the wart platform — real
    TPU/GPU attaches and current jax return the state untouched."""
    version = tuple(int(p) for p in jax.__version__.split(".")[:2])
    if version >= (0, 5) or jax.default_backend() != "cpu":
        return state
    return jax.jit(lambda s: s)(state)


def wrap_step(step, executable, on_fallback: Callable | None = None,
              expected_batch=None):
    """The AOT-warmed step: same calling convention and attributes as
    ``make_train_step``'s product, but dispatching through ``executable``
    (cache-loaded or freshly AOT-compiled). The FIRST call validates it —
    an input mismatch (a geometry the content key could not distinguish)
    raises before execution, and the wrapper permanently falls back to
    the ordinary ``step.jitted`` path, reporting through ``on_fallback``;
    after one successful call the executable is trusted for that
    geometry. ``expected_batch`` (the staged example the executable was
    keyed/compiled against) additionally routes any OFF-SHAPE batch —
    e.g. a ``drop_remainder=False`` loader's ragged tail, which the jit
    path absorbs by recompiling — to ``step.jitted`` per call instead of
    letting a post-validation shape mismatch kill the run."""
    holder = {"exe": executable, "validated": False, "noted_cold": False}
    expected = None
    if expected_batch is not None:
        expected = {
            k: (tuple(v.shape), v.dtype) for k, v in expected_batch.items()
        }

    def _on_shape(staged) -> bool:
        if expected is None:
            return True
        return set(staged) == set(expected) and all(
            (tuple(v.shape), v.dtype) == expected[k]
            for k, v in staged.items()
        )

    def cached(state, batch):
        staged = step.stage(batch)
        exe = holder["exe"]
        if exe is None or not _on_shape(staged):
            if (exe is not None and not holder["validated"]
                    and not holder["noted_cold"]):
                # the FIRST call is already off-shape (e.g. every batch
                # ragged because the dataset is smaller than batch_size,
                # or a loader whose batch_size attribute lied): this
                # iteration traces on the jit path — report it so
                # goodput reverts its warm-start accounting instead of
                # booking a real cold compile as productive time. The
                # executable stays: later on-shape batches may use it.
                holder["noted_cold"] = True
                if on_fallback is not None:
                    on_fallback(RuntimeError(
                        "first batch off-shape vs the staged example — "
                        "iteration 1 traces on the jit path"
                    ))
            return step.jitted(state, staged)
        if holder["validated"]:
            return exe(state, staged)
        try:
            out = exe(state, staged)
        except Exception as exc:
            holder["exe"] = None
            if on_fallback is not None:
                on_fallback(exc)
            return step.jitted(state, staged)
        holder["validated"] = True
        return out

    for attr in ("jitted", "stage", "grad_reducer", "comm_stats",
                 "fused", "fused_info"):
        setattr(cached, attr, getattr(step, attr))
    cached.aot = holder
    return cached
