"""Lazy in-tree build of the native library.

The reference ships no build system (its native machinery lives in upstream
torch); tpudist compiles its own C++ core on first use with the toolchain on
the host and caches the shared object next to the sources, keyed by a hash
of their content so edits trigger a rebuild and stale objects are never
loaded. Concurrent builders (multi-process launch) race benignly: each
builds to a unique temp file and the final ``os.replace`` is atomic.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile
from pathlib import Path

_CSRC = Path(__file__).resolve().parent

CXX = os.environ.get("TPUDIST_CXX", "g++")
CXXFLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread", "-Wall"]


def sources() -> list[Path]:
    return sorted(_CSRC.glob("*.cpp"))


def build(force: bool = False) -> Path:
    """Compile (if needed) and return the path of the shared library."""
    srcs = sources()
    if not srcs:
        raise FileNotFoundError(f"no C++ sources under {_CSRC}")
    h = hashlib.sha256()
    for s in srcs:
        h.update(s.name.encode())
        h.update(s.read_bytes())
    out = _CSRC / f"libtpudist_{h.hexdigest()[:16]}.so"
    if out.exists() and not force:
        return out
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_CSRC)
    os.close(fd)
    try:
        cmd = [CXX, *CXXFLAGS, "-o", tmp, *map(str, srcs)]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{proc.stderr}"
            )
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    # retire caches of older source versions
    for old in _CSRC.glob("libtpudist_*.so"):
        if old != out:
            try:
                old.unlink()
            except OSError:
                pass
    return out
