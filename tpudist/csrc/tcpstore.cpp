// Native TCP key-value store — tpudist's equivalent of c10d's C++ TCPStore,
// the rendezvous mechanism behind the reference's
// `dist.init_process_group(init_method='env://')` (/root/reference/main.py:34,
// SURVEY.md §2.3): rank 0 hosts a TCP store at MASTER_ADDR:MASTER_PORT and
// every rank connects to exchange bootstrap state and synchronize.
//
// jax.distributed owns the *device* bring-up; this store covers host-side
// coordination that must work before/outside JAX: launcher health checks,
// the rank-0 dataset-download guard (SURVEY.md §5 race fix), and generic
// cross-process barriers (built in Python on SET/GET/ADD).
//
// Protocol (little-endian, one request/response per message):
//   SET(1): u32 klen, key, u64 vlen, val          → u8 status
//   GET(2): u32 klen, key, i32 wait_ms            → u8 status, u64 vlen, val
//   ADD(3): u32 klen, key, i64 delta              → u8 status, i64 new_value
// ADD stores the value as a decimal string so SET/GET interoperate.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint8_t kSet = 1, kGet = 2, kAdd = 3;
constexpr int64_t kMaxValue = 1 << 20;  // 1 MiB cap on stored values

bool ReadFull(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class StoreServer {
 public:
  explicit StoreServer(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        ::listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~StoreServer() {
    {
      std::lock_guard<std::mutex> l(m_);
      stop_ = true;
      cv_.notify_all();
      if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
      for (int fd : client_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    // handlers are detached; wait for the last one to finish touching
    // member state before tearing it down
    {
      std::unique_lock<std::mutex> l(m_);
      cv_.wait(l, [this] { return active_handlers_ == 0; });
    }
    if (listen_fd_ >= 0) ::close(listen_fd_);
  }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

 private:
  void AcceptLoop() {
    for (;;) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      std::lock_guard<std::mutex> l(m_);
      if (stop_) {
        if (fd >= 0) ::close(fd);
        return;
      }
      if (fd < 0) continue;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      client_fds_.insert(fd);
      ++active_handlers_;
      // detached so short-lived connections don't accumulate joinable
      // zombies on a long-lived server; ~StoreServer waits on the count
      std::thread([this, fd] { Handle(fd); }).detach();
    }
  }

  void Handle(int fd) {
    for (;;) {
      uint8_t op;
      if (!ReadFull(fd, &op, 1)) break;
      uint32_t klen;
      if (!ReadFull(fd, &klen, 4) || klen > (1u << 16)) break;
      std::string key(klen, '\0');
      if (!ReadFull(fd, key.data(), klen)) break;
      if (op == kSet) {
        uint64_t vlen;
        if (!ReadFull(fd, &vlen, 8) || vlen > kMaxValue) break;
        std::string val(vlen, '\0');
        if (!ReadFull(fd, val.data(), vlen)) break;
        {
          std::lock_guard<std::mutex> l(m_);
          data_[key] = std::move(val);
          cv_.notify_all();
        }
        uint8_t status = 0;
        if (!WriteFull(fd, &status, 1)) break;
      } else if (op == kGet) {
        int32_t wait_ms;
        if (!ReadFull(fd, &wait_ms, 4)) break;
        std::string val;
        uint8_t status = Get(key, wait_ms, &val);
        uint64_t vlen = val.size();
        if (!WriteFull(fd, &status, 1) || !WriteFull(fd, &vlen, 8) ||
            !WriteFull(fd, val.data(), vlen))
          break;
      } else if (op == kAdd) {
        int64_t delta;
        if (!ReadFull(fd, &delta, 8)) break;
        int64_t now;
        {
          std::lock_guard<std::mutex> l(m_);
          int64_t cur = 0;
          auto it = data_.find(key);
          if (it != data_.end()) cur = std::strtoll(it->second.c_str(), nullptr, 10);
          now = cur + delta;
          data_[key] = std::to_string(now);
          cv_.notify_all();
        }
        uint8_t status = 0;
        if (!WriteFull(fd, &status, 1) || !WriteFull(fd, &now, 8)) break;
      } else {
        break;
      }
    }
    std::lock_guard<std::mutex> l(m_);
    client_fds_.erase(fd);
    ::close(fd);
    --active_handlers_;
    cv_.notify_all();
  }

  uint8_t Get(const std::string& key, int32_t wait_ms, std::string* out) {
    std::unique_lock<std::mutex> l(m_);
    auto found = [&] { return data_.count(key) > 0; };
    if (!found() && wait_ms != 0) {
      if (wait_ms < 0) {
        cv_.wait(l, [&] { return stop_ || found(); });
      } else {
        cv_.wait_for(l, std::chrono::milliseconds(wait_ms),
                     [&] { return stop_ || found(); });
      }
    }
    if (!found()) return 1;  // not found / timeout
    *out = data_[key];
    return 0;
  }

  int listen_fd_ = -1;
  int port_ = 0;
  std::thread accept_thread_;
  int active_handlers_ = 0;  // guarded by m_
  std::set<int> client_fds_;
  std::map<std::string, std::string> data_;
  std::mutex m_;
  std::condition_variable cv_;
  bool stop_ = false;
};

class StoreClient {
 public:
  StoreClient(const char* host, int port, int timeout_ms) {
    // retry-connect until the deadline: ranks may dial before rank 0's
    // server is up (same behavior as c10d TCPStore clients)
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 1);
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (::getaddrinfo(host, std::to_string(port).c_str(), &hints, &res) != 0)
      return;
    do {
      int fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd >= 0) {
        if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          int one = 1;
          ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          fd_ = fd;
          break;
        }
        ::close(fd);
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    } while (std::chrono::steady_clock::now() < deadline);
    ::freeaddrinfo(res);
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return fd_ >= 0; }

  bool Set(const std::string& key, const void* val, int64_t vlen) {
    std::lock_guard<std::mutex> l(m_);
    uint8_t op = kSet;
    uint32_t klen = key.size();
    uint64_t v = static_cast<uint64_t>(vlen);
    if (!WriteFull(fd_, &op, 1) || !WriteFull(fd_, &klen, 4) ||
        !WriteFull(fd_, key.data(), klen) || !WriteFull(fd_, &v, 8) ||
        !WriteFull(fd_, val, vlen))
      return false;
    uint8_t status;
    return ReadFull(fd_, &status, 1) && status == 0;
  }

  // returns value length (copied into buf up to buflen), -1 not-found/timeout,
  // -2 transport error, -3 value larger than buf
  int64_t Get(const std::string& key, void* buf, int64_t buflen, int wait_ms) {
    std::lock_guard<std::mutex> l(m_);
    uint8_t op = kGet;
    uint32_t klen = key.size();
    int32_t w = wait_ms;
    if (!WriteFull(fd_, &op, 1) || !WriteFull(fd_, &klen, 4) ||
        !WriteFull(fd_, key.data(), klen) || !WriteFull(fd_, &w, 4))
      return -2;
    uint8_t status;
    uint64_t vlen;
    if (!ReadFull(fd_, &status, 1) || !ReadFull(fd_, &vlen, 8)) return -2;
    std::string val(vlen, '\0');
    if (vlen > 0 && !ReadFull(fd_, val.data(), vlen)) return -2;
    if (status != 0) return -1;
    if (static_cast<int64_t>(vlen) > buflen) return -3;
    std::memcpy(buf, val.data(), vlen);
    return static_cast<int64_t>(vlen);
  }

  int64_t Add(const std::string& key, int64_t delta) {
    std::lock_guard<std::mutex> l(m_);
    uint8_t op = kAdd;
    uint32_t klen = key.size();
    if (!WriteFull(fd_, &op, 1) || !WriteFull(fd_, &klen, 4) ||
        !WriteFull(fd_, key.data(), klen) || !WriteFull(fd_, &delta, 8))
      return INT64_MIN;
    uint8_t status;
    int64_t now;
    if (!ReadFull(fd_, &status, 1) || !ReadFull(fd_, &now, 8) || status != 0)
      return INT64_MIN;
    return now;
  }

 private:
  int fd_ = -1;
  std::mutex m_;  // one outstanding request per client connection
};

}  // namespace

extern "C" {

void* tpd_store_server_create(int port) {
  auto* s = new StoreServer(port);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int tpd_store_server_port(void* s) {
  return static_cast<StoreServer*>(s)->port();
}

void tpd_store_server_destroy(void* s) { delete static_cast<StoreServer*>(s); }

void* tpd_client_create(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient(host, port, timeout_ms);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

void tpd_client_destroy(void* c) { delete static_cast<StoreClient*>(c); }

int tpd_client_set(void* c, const char* key, const void* val, int64_t vlen) {
  return static_cast<StoreClient*>(c)->Set(key, val, vlen) ? 0 : -1;
}

int64_t tpd_client_get(void* c, const char* key, void* buf, int64_t buflen,
                       int wait_ms) {
  return static_cast<StoreClient*>(c)->Get(key, buf, buflen, wait_ms);
}

int64_t tpd_client_add(void* c, const char* key, int64_t delta) {
  return static_cast<StoreClient*>(c)->Add(key, delta);
}

}  // extern "C"
