// Native batch-assembly core — the TPU-native equivalent of the C++
// machinery behind torch's DataLoader (pinned-memory allocator + worker
// pool) that the reference drives at /root/reference/main.py:54-63.
//
// PyTorch assembles batches with a C++ worker pool and stages them through
// page-locked buffers; on TPU the staging is jax.device_put (async DMA), so
// the native surface that matters is the *host-side gather*: collecting the
// sampler's index shard into one contiguous batch buffer, fused with the
// ToTensor uint8→float32 conversion (/root/reference/main.py:46), in
// parallel across a persistent thread pool.  numpy does the same work in
// two passes (fancy-index gather, then astype+divide) with an intermediate
// allocation; this does it in one pass with no temporaries.
//
// Exposed as a plain C ABI consumed via ctypes (tpudist/data/native.py).

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

class ThreadPool {
 public:
  explicit ThreadPool(int n) {
    n = std::max(n, 1);
    workers_.reserve(n);
    for (int i = 0; i < n; ++i) workers_.emplace_back([this] { Loop(); });
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> l(m_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int size() const { return static_cast<int>(workers_.size()); }

  // Run all tasks on the pool and block until every one has finished.
  void Run(std::vector<std::function<void()>> tasks) {
    if (tasks.empty()) return;
    std::mutex done_m;
    std::condition_variable done_cv;
    size_t remaining = tasks.size();  // guarded by done_m
    {
      std::lock_guard<std::mutex> l(m_);
      for (auto& t : tasks) {
        q_.push([&done_m, &done_cv, &remaining, t = std::move(t)] {
          t();
          // final decrement must happen under done_m so the waiter cannot
          // observe 0 and destroy done_m while we still hold it
          std::lock_guard<std::mutex> dl(done_m);
          if (--remaining == 0) done_cv.notify_all();
        });
      }
    }
    cv_.notify_all();
    std::unique_lock<std::mutex> dl(done_m);
    done_cv.wait(dl, [&] { return remaining == 0; });
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> l(m_);
        cv_.wait(l, [this] { return stop_ || !q_.empty(); });
        if (stop_ && q_.empty()) return;
        task = std::move(q_.front());
        q_.pop();
      }
      task();
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> q_;
  std::mutex m_;
  std::condition_variable cv_;
  bool stop_ = false;
};

// Split [0, n) into at most pool->size() contiguous chunks of at least
// min_chunk rows each and run fn(start, end) on the pool; small inputs run
// inline on the caller to skip scheduling overhead.
void ParallelChunks(ThreadPool* pool, int64_t n, int64_t min_chunk,
                    const std::function<void(int64_t, int64_t)>& fn) {
  int64_t max_tasks = pool ? pool->size() : 1;
  int64_t n_tasks = std::min(max_tasks, (n + min_chunk - 1) / min_chunk);
  if (n_tasks <= 1 || pool == nullptr) {
    fn(0, n);
    return;
  }
  std::vector<std::function<void()>> tasks;
  tasks.reserve(n_tasks);
  int64_t per = (n + n_tasks - 1) / n_tasks;
  for (int64_t s = 0; s < n; s += per) {
    int64_t e = std::min(n, s + per);
    tasks.push_back([s, e, &fn] { fn(s, e); });
  }
  pool->Run(std::move(tasks));
}

}  // namespace

extern "C" {

int tpd_abi_version() { return 2; }

void* tpd_pool_create(int n_threads) {
  if (n_threads <= 0) {
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  }
  return new ThreadPool(n_threads);
}

void tpd_pool_destroy(void* pool) { delete static_cast<ThreadPool*>(pool); }

int tpd_pool_size(void* pool) { return static_cast<ThreadPool*>(pool)->size(); }

// out[i] = src[idx[i]] for rows of item_bytes bytes (dtype-agnostic gather).
void tpd_gather_rows(void* pool, const uint8_t* src, int64_t item_bytes,
                     const int64_t* idx, int64_t n, uint8_t* out) {
  // ~1 MiB of copying per task amortizes scheduling
  int64_t min_chunk = std::max<int64_t>(1, (1 << 20) / std::max<int64_t>(item_bytes, 1));
  ParallelChunks(static_cast<ThreadPool*>(pool), n, min_chunk,
                 [=](int64_t s, int64_t e) {
                   for (int64_t i = s; i < e; ++i) {
                     std::memcpy(out + i * item_bytes,
                                 src + idx[i] * item_bytes, item_bytes);
                   }
                 });
}

// out[i] = float(src[idx[i]]) * scale + shift — the sampler gather fused
// with ToTensor's /255 (one pass, no uint8 intermediate batch).
void tpd_gather_u8_to_f32(void* pool, const uint8_t* src, int64_t item_elems,
                          const int64_t* idx, int64_t n, float* out,
                          float scale, float shift) {
  int64_t min_chunk = std::max<int64_t>(1, (1 << 19) / std::max<int64_t>(item_elems, 1));
  ParallelChunks(static_cast<ThreadPool*>(pool), n, min_chunk,
                 [=](int64_t s, int64_t e) {
                   for (int64_t i = s; i < e; ++i) {
                     const uint8_t* row = src + idx[i] * item_elems;
                     float* dst = out + i * item_elems;
                     for (int64_t j = 0; j < item_elems; ++j) {
                       dst[j] = static_cast<float>(row[j]) * scale + shift;
                     }
                   }
                 });
}

// out[i][..., c] = float(src[idx[i]][..., c]) * scale[c] + shift[c] — the
// gather fused with ToTensor + per-channel normalization ((x/255 - mean)/std
// folds into one affine per channel). `channels` is the innermost dim of an
// item; item_elems must be a multiple of it.
void tpd_gather_u8_to_f32_ch(void* pool, const uint8_t* src,
                             int64_t item_elems, int64_t channels,
                             const int64_t* idx, int64_t n, float* out,
                             const float* scale, const float* shift) {
  int64_t min_chunk = std::max<int64_t>(1, (1 << 19) / std::max<int64_t>(item_elems, 1));
  ParallelChunks(static_cast<ThreadPool*>(pool), n, min_chunk,
                 [=](int64_t s, int64_t e) {
                   for (int64_t i = s; i < e; ++i) {
                     const uint8_t* row = src + idx[i] * item_elems;
                     float* dst = out + i * item_elems;
                     for (int64_t j = 0; j < item_elems; j += channels) {
                       for (int64_t c = 0; c < channels; ++c) {
                         dst[j + c] =
                             static_cast<float>(row[j + c]) * scale[c] + shift[c];
                       }
                     }
                   }
                 });
}

}  // extern "C"
