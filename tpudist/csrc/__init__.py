"""Native (C++) core of tpudist, loaded via ctypes.

The reference's data path and rendezvous are backed by upstream C++
(DataLoader worker pool / pinned allocator, c10d TCPStore — SURVEY.md §2.3,
§2.7); this package holds tpudist's own native equivalents. The library is
compiled lazily on first use (see :mod:`tpudist.csrc.build`); if no
toolchain is available the callers fall back to pure-Python paths, so the
framework degrades gracefully rather than hard-requiring a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import threading

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed = False

ABI_VERSION = 2


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.tpd_abi_version.restype = c.c_int
    lib.tpd_pool_create.restype = c.c_void_p
    lib.tpd_pool_create.argtypes = [c.c_int]
    lib.tpd_pool_destroy.restype = None
    lib.tpd_pool_destroy.argtypes = [c.c_void_p]
    lib.tpd_pool_size.restype = c.c_int
    lib.tpd_pool_size.argtypes = [c.c_void_p]
    lib.tpd_gather_rows.restype = None
    lib.tpd_gather_rows.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p, c.c_int64, c.c_void_p,
    ]
    lib.tpd_gather_u8_to_f32.restype = None
    lib.tpd_gather_u8_to_f32.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p, c.c_int64, c.c_void_p,
        c.c_float, c.c_float,
    ]
    lib.tpd_gather_u8_to_f32_ch.restype = None
    lib.tpd_gather_u8_to_f32_ch.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_void_p, c.c_int64,
        c.c_void_p, c.c_void_p, c.c_void_p,
    ]
    # TCP store (tcpstore.cpp)
    lib.tpd_store_server_create.restype = c.c_void_p
    lib.tpd_store_server_create.argtypes = [c.c_int]
    lib.tpd_store_server_port.restype = c.c_int
    lib.tpd_store_server_port.argtypes = [c.c_void_p]
    lib.tpd_store_server_destroy.restype = None
    lib.tpd_store_server_destroy.argtypes = [c.c_void_p]
    lib.tpd_client_create.restype = c.c_void_p
    lib.tpd_client_create.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.tpd_client_destroy.restype = None
    lib.tpd_client_destroy.argtypes = [c.c_void_p]
    lib.tpd_client_set.restype = c.c_int
    lib.tpd_client_set.argtypes = [
        c.c_void_p, c.c_char_p, c.c_void_p, c.c_int64,
    ]
    lib.tpd_client_get.restype = c.c_int64
    lib.tpd_client_get.argtypes = [
        c.c_void_p, c.c_char_p, c.c_void_p, c.c_int64, c.c_int,
    ]
    lib.tpd_client_add.restype = c.c_int64
    lib.tpd_client_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]


def lib() -> ctypes.CDLL | None:
    """The loaded native library, or None if it cannot be built/loaded."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            from tpudist.csrc.build import build

            path = build()
            loaded = ctypes.CDLL(str(path))
            _declare(loaded)
            got = loaded.tpd_abi_version()
            if got != ABI_VERSION:
                raise RuntimeError(f"native ABI {got} != expected {ABI_VERSION}")
            _lib = loaded
        except Exception as e:  # no toolchain / load failure → Python fallback
            logger.warning("tpudist native core unavailable (%s); "
                           "falling back to pure-Python paths", e)
            _failed = True
    return _lib
