"""Mixed precision policy for TPU training.

The reference trains in fp32 end-to-end (no autocast/AMP anywhere in
/root/reference/main.py — SURVEY.md §2.12 lists "AMP/bf16 autocast" as
explicitly absent); BASELINE.json config 4 (ViT-B/16) demands a bf16 path.
The TPU-native story is simpler than CUDA AMP: MXU matmuls take bf16 inputs
natively and accumulate in fp32, so there is no fp16 loss-scaling dance —
the policy is "fp32 master params, bf16 compute, fp32 logits/loss", which
the flax modules implement via their ``dtype`` field (params are created in
fp32 and cast per-op). This module gives that convention a name, plus
guards for the rare bf16 overflow spike.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype roles for a training step.

    ``param_dtype``: master copy precision (optimizer state math);
    ``compute_dtype``: forward/backward matmul inputs;
    ``output_dtype``: logits/loss precision.
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    def cast_to_compute(self, tree):
        return _cast_floats(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return _cast_floats(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return _cast_floats(tree, self.output_dtype)


FP32 = Policy()
BF16_COMPUTE = Policy(compute_dtype=jnp.bfloat16)


def policy_for(bf16: bool) -> Policy:
    return BF16_COMPUTE if bf16 else FP32


def _cast_floats(tree, dtype):
    def cast(x):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x, dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def all_finite(tree) -> jax.Array:
    """Scalar bool: every float leaf of ``tree`` is finite."""
    leaves = [
        jnp.all(jnp.isfinite(x))
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack(leaves).all()


def nonfinite_count(tree) -> jax.Array:
    """int32 scalar: how many elements across the float leaves of ``tree``
    are non-finite. The telemetry health metric's counter — one home, so
    the compiled step and any future consumer (e.g. the explicit-reduction
    path's detection on dequantized grads) count the same way. Non-float
    leaves don't count (they cannot hold NaN/inf)."""
    counts = [
        jnp.sum(~jnp.isfinite(x))
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    if not counts:
        return jnp.zeros((), jnp.int32)
    return jnp.asarray(sum(counts), jnp.int32)


class SkipNonfinite(NamedTuple):
    """:func:`skip_nonfinite`'s return type: the optax ``(init, update)``
    surface plus ``inner`` — the wrapped transformation, kept visible so
    capability probes (``tpudist.optim``'s fused-optimizer detection) can
    walk through the wrapper the same way they walk through
    ``ShardedStateOptimizer.inner``. Every existing consumer duck-types
    ``init``/``update`` and is unaffected."""

    init: Callable
    update: Callable
    inner: Any


def skip_nonfinite(tx: optax.GradientTransformation) -> SkipNonfinite:
    """Wrap an optimizer so steps with non-finite gradients become no-ops.

    A bf16 overflow spike (or a data glitch) then skips one update instead
    of poisoning params and Adam moments with NaNs forever. The skip count
    is kept in the wrapper's state for observability.
    """

    def init(params):
        return (tx.init(params), jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        inner_state, skipped = state
        ok = all_finite(grads)
        safe = jax.tree_util.tree_map(
            lambda g: jnp.where(ok, g, jnp.zeros_like(g)), grads
        )
        new_updates, new_inner = tx.update(safe, inner_state, params)
        # non-finite step: zero updates, optimizer state unchanged
        updates = jax.tree_util.tree_map(
            lambda u: jnp.where(ok, u, jnp.zeros_like(u)), new_updates
        )
        inner = jax.tree_util.tree_map(
            lambda new, old: jnp.where(ok, new, old)
            if jnp.issubdtype(jnp.asarray(new).dtype, jnp.inexact)
            or jnp.issubdtype(jnp.asarray(new).dtype, jnp.integer)
            else new,
            new_inner, inner_state,
        )
        return updates, (inner, skipped + jnp.where(ok, 0, 1))

    return SkipNonfinite(init, update, tx)


def skipped_steps(opt_state) -> int:
    """Read the skip counter out of a :func:`skip_nonfinite` state."""
    return int(opt_state[1])


def is_skip_state(opt_state) -> bool:
    """True when ``opt_state`` is structurally a :func:`skip_nonfinite`
    state — ``(inner_state, int32 scalar counter)``, the wrapper applied
    outermost by convention (including under
    :func:`tpudist.optim.shard_state`, whose counter leaf is replicated).
    Works on tracers too (shape/dtype are static), which is how
    ``make_train_step``'s non-finite guard finds the counter leaf to
    exempt from its opt-state freeze. The ONE structural definition: a
    future change to the wrapper's state shape is updated here, next to
    the wrapper, and every reader follows."""
    if not (isinstance(opt_state, tuple) and len(opt_state) == 2):
        return False
    counter = opt_state[1]
    return (
        hasattr(counter, "dtype")
        and getattr(counter, "ndim", None) == 0
        and jnp.issubdtype(counter.dtype, jnp.integer)
    )


def maybe_skipped_steps(opt_state) -> int | None:
    """Best-effort :func:`skipped_steps` for chains that may not carry the
    wrapper: the count, or ``None`` when the chain carries no skip wrapper
    — the telemetry run-summary row then reports ``null`` instead of
    fabricating a zero (tpudist.telemetry)."""
    return skipped_steps(opt_state) if is_skip_state(opt_state) else None
