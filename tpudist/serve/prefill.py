"""Chunked, bucketed prompt prefill for the serving engine.

A new request's prompt runs through the model's bulk decode path (causal
within the chunk — the same pass :func:`tpudist.generate.generate` uses)
on a FRESH batch-1 cache, in chunks of at most ``chunk`` tokens with the
final partial chunk padded to a power-of-two bucket
(:func:`tpudist.generate.bucket_length`). The compile set is therefore
bounded: one program per (bucket length) — a handful for any traffic mix —
instead of one per prompt length, the pjit-paper shape discipline applied
to serving. The prefilled cache is then scattered into a free pool slot
(:func:`tpudist.serve.slots.write_slot`) and the request joins the shared
decode batch.

Bit-exactness note: a prompt that fits ONE chunk runs the identical
bucket-padded program shape as ``generate()``'s prefill, which is what
makes greedy continuous-batching output bit-identical to the static path
(pinned in tests/test_serve.py). Longer prompts split across chunks are
the same function in exact arithmetic, but chunk boundaries change XLA's
fusion shapes, so cross-chunk prompts are only almost-everywhere
token-identical.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpudist.generate import bucket_length


@jax.jit
def _index_logits(logits, i):
    """``logits [1, L, V]`` at traced row ``i`` → ``[V]`` (one compile for
    every in-chunk position of the last real token)."""
    return jax.lax.dynamic_index_in_dim(logits[0], i, axis=0, keepdims=False)


class Prefiller:
    """Callable turning a prompt into ``(row_cache, last_logits)``: a
    batch-1 cache holding the prompt's K/V and the logits after the
    prompt's LAST real token (the first sampled position — the request's
    time-to-first-token is the latency of this call plus one sample).

    ``model`` and ``params`` bind at construction: the chunk program
    closes over the weights (per-instance jit) instead of tracing them as
    arguments — traced params make XLA re-canonicalize the weight layouts
    per CALL, a per-admission tax the static path never sees because one
    ``generate()`` call amortizes it over the whole scan — and the fresh
    cache's eval_shape (a full model-init retrace, ~100 ms at 124M) runs
    once here, not per request."""

    def __init__(self, model, params, *, chunk: int = 512, minimum: int = 8):
        self.model = model
        self.chunk = min(int(chunk), model.max_seq_len)
        self.minimum = minimum
        if self.chunk < 1:
            raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
        self._cache_shapes = jax.eval_shape(
            lambda: model.init(
                jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
                train=False, decode=True,
            )
        )["cache"]

        @partial(jax.jit, donate_argnums=(0,))
        def chunk_body(cache, toks):
            # non-final chunks only feed the KV cache — return_hidden
            # skips the LM head entirely (at GPT-2's vocab a 512-token
            # chunk's discarded [1, 512, V] fp32 logits are ~100 MB of
            # HBM traffic plus the head matmul, per admission)
            _, updates = model.apply(
                {"params": params, "cache": cache}, toks,
                train=False, decode=True, mutable=["cache"],
                return_hidden=True,
            )
            return updates["cache"]

        @partial(jax.jit, donate_argnums=(0,))
        def chunk_final(cache, toks):
            logits, updates = model.apply(
                {"params": params, "cache": cache}, toks,
                train=False, decode=True, mutable=["cache"],
            )
            return updates["cache"], logits

        self._chunk_body = chunk_body
        self._chunk_final = chunk_final

    def chunk_plan(self, p: int) -> list[tuple[int, int]]:
        """The ``(real, padded)`` chunk lengths a ``p``-token prompt runs
        as (full chunks, then the remainder's bucket) — the ONE place the
        split is computed (``__call__`` iterates it), exposed so tests can
        pin the compile-count contract. The bucket is capped by BOTH the
        chunk size and the cache space left (``max_seq_len - offset``):
        the scalar cursor advances by PADDED lengths, so an uncapped final
        bucket on a near-full prompt would write past the cache end —
        dynamic_update_slice clamps the start, misaligning the prefix K/V
        silently (the cap is always >= the real length because the prompt
        itself fits the cache)."""
        plan, off = [], 0
        while off < p:
            n = min(self.chunk, p - off)
            plan.append((n, bucket_length(
                n, cap=min(self.chunk, self.model.max_seq_len - off),
                minimum=self.minimum,
            )))
            off += n
        return plan

    def __call__(self, prompt):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        if not 0 < p <= self.model.max_seq_len:
            raise ValueError(
                f"prompt length {p} outside (0, {self.model.max_seq_len}]"
            )
        cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._cache_shapes
        )
        plan = self.chunk_plan(p)
        off, logits, last = 0, None, 0
        for i, (n, padded) in enumerate(plan):
            toks = np.zeros((1, padded), np.int32)
            toks[0, :n] = prompt[off : off + n]
            toks = jnp.asarray(toks)
            if i + 1 < len(plan):
                cache = self._chunk_body(cache, toks)
            else:
                cache, logits = self._chunk_final(cache, toks)
            off += n
            last = n - 1
        # NOTE on the cursor: after a padded final chunk the cache's scalar
        # cursors sit past p. The pool scatter copies only the 4-D buffers
        # (slots.write_slot) and the engine owns the slot's true length, so
        # the overshoot never escapes this function.
        return cache, _index_logits(logits, jnp.asarray(last, jnp.int32))
