"""Chunked, bucketed prompt prefill for the serving engine.

A new request's prompt runs through the model's bulk decode path (causal
within the chunk — the same pass :func:`tpudist.generate.generate` uses)
on a FRESH batch-1 cache, in chunks of at most ``chunk`` tokens with the
final partial chunk padded to a power-of-two bucket
(:func:`tpudist.generate.bucket_length`). The compile set is therefore
bounded: one program per (bucket length) — a handful for any traffic mix —
instead of one per prompt length, the pjit-paper shape discipline applied
to serving. The prefilled cache is then scattered into a free pool slot
(:func:`tpudist.serve.slots.write_slot`) and the request joins the shared
decode batch.

Bit-exactness note: a prompt that fits ONE chunk runs the identical
bucket-padded program shape as ``generate()``'s prefill, which is what
makes greedy continuous-batching output bit-identical to the static path
(pinned in tests/test_serve.py). Longer prompts split across chunks are
the same function in exact arithmetic, but chunk boundaries change XLA's
fusion shapes, so cross-chunk prompts are only almost-everywhere
token-identical.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


from tpudist.generate import bucket_length


def _fresh_cursors(cache, start: int):
    """Set every integer scalar cursor to ``start`` with ONE DISTINCT
    device buffer per leaf. ``tpudist.generate._reset_cursors`` shares a
    single traced scalar across all cursor leaves — correct inside a jit
    (where it runs for the static path), but OUTSIDE one the shared
    buffer makes the chunk programs' donation see the same buffer twice
    and refuse to execute."""
    return jax.tree_util.tree_map(
        lambda leaf: jnp.asarray(np.asarray(start, leaf.dtype))
        if jnp.ndim(leaf) == 0 and jnp.issubdtype(leaf.dtype, jnp.integer)
        else leaf,
        cache,
    )


@jax.jit
def _index_logits(logits, i):
    """``logits [1, L, V]`` at traced row ``i`` → ``[V]`` (one compile for
    every in-chunk position of the last real token)."""
    return jax.lax.dynamic_index_in_dim(logits[0], i, axis=0, keepdims=False)


class Prefiller:
    """Callable turning a prompt into ``(row_cache, last_logits)``: a
    batch-1 cache holding the prompt's K/V and the logits after the
    prompt's LAST real token (the first sampled position — the request's
    time-to-first-token is the latency of this call plus one sample).

    ``model`` and ``params`` bind at construction: the chunk program
    closes over the weights (per-instance jit) instead of tracing them as
    arguments — traced params make XLA re-canonicalize the weight layouts
    per CALL, a per-admission tax the static path never sees because one
    ``generate()`` call amortizes it over the whole scan — and the fresh
    cache's eval_shape (a full model-init retrace, ~100 ms at 124M) runs
    once here, not per request.

    ``head=False`` skips the LM head on the FINAL chunk too and returns
    ``(row_cache, None)`` — the speculative DRAFT prefill
    (``tpudist.serve.engine``): the draft only needs its prompt K/V (its
    first proposal is conditioned on the target-sampled first token, so
    its prompt-end logits are never read), and the head matmul +
    ``[1, bucket, V]`` logits are the expensive part of a narrow model's
    chunk."""

    def __init__(self, model, params, *, chunk: int = 512, minimum: int = 8,
                 head: bool = True, kv_sharding=None):
        self.model = model
        self.chunk = min(int(chunk), model.max_seq_len)
        self.minimum = minimum
        self.head = head
        # multi-chip engine (ServeEngine(mesh=...)): the fresh batch-1
        # cache's [1, H_kv, max_len, dh] buffers start head-sharded so the
        # chunk programs (which close over tensor-sharded params) see
        # consistent placements instead of re-deciding them per admission
        self.kv_sharding = kv_sharding
        if self.chunk < 1:
            raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
        self._cache_shapes = jax.eval_shape(
            lambda: model.init(
                jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
                train=False, decode=True,
            )
        )["cache"]

        @partial(jax.jit, donate_argnums=(0,))
        def chunk_body(cache, toks):
            # non-final chunks only feed the KV cache — return_hidden
            # skips the LM head entirely (at GPT-2's vocab a 512-token
            # chunk's discarded [1, 512, V] fp32 logits are ~100 MB of
            # HBM traffic plus the head matmul, per admission)
            _, updates = model.apply(
                {"params": params, "cache": cache}, toks,
                train=False, decode=True, mutable=["cache"],
                return_hidden=True,
            )
            return updates["cache"]

        @partial(jax.jit, donate_argnums=(0,))
        def chunk_final(cache, toks):
            logits, updates = model.apply(
                {"params": params, "cache": cache}, toks,
                train=False, decode=True, mutable=["cache"],
            )
            return updates["cache"], logits

        self._chunk_body = chunk_body
        self._chunk_final = chunk_final
        # (kind, bucket) -> AOT executable, attached by the engine's
        # deploy-time compile cache; shapes outside the map take the jit
        # path, and a failing executable falls back permanently
        self._aot: dict[tuple[str, int], object] = {}

    def attach_aot(self, programs: dict) -> None:
        """Route chunk programs through cached AOT executables
        (``{("final"|"body", bucket): executable}`` — the engine's
        ``compile_cache=`` warm-start path builds the map)."""
        self._aot = dict(programs)

    def _run_chunk(self, cache, toks, final: bool):
        kind = "final" if final else "body"
        exe = self._aot.get((kind, toks.shape[1]))
        if exe is not None:
            try:
                return exe(cache, toks)
            except Exception:
                # a geometry the fingerprint couldn't see: never again —
                # the cache may cost a trace, not a wrong program. Safe
                # to retry on the same args because argument validation
                # raises PRE-dispatch, before donation invalidates the
                # chunk cache (same boundary as the engine's decode AOT)
                self._aot.pop((kind, toks.shape[1]), None)
        return (self._chunk_final if final else self._chunk_body)(cache, toks)

    def chunk_plan(self, p: int, start: int = 0) -> list[tuple[int, int]]:
        """The ``(real, padded)`` chunk lengths a ``p``-token prompt runs
        as (full chunks, then the remainder's bucket) — the ONE place the
        split is computed (``__call__`` iterates it), exposed so tests can
        pin the compile-count contract. The bucket is capped by BOTH the
        chunk size and the cache space left (``max_seq_len - offset``):
        the scalar cursor advances by PADDED lengths, so an uncapped final
        bucket on a near-full prompt would write past the cache end —
        dynamic_update_slice clamps the start, misaligning the prefix K/V
        silently (the cap is always >= the real length because the prompt
        itself fits the cache). ``start`` plans only the SUFFIX
        ``tokens[start:]`` — the prefix-cache hit path
        (:meth:`resume`), where the first ``start`` tokens' K/V arrive
        from shared pool blocks and never re-run."""
        plan, off = [], start
        while off < p:
            n = min(self.chunk, p - off)
            plan.append((n, bucket_length(
                n, cap=min(self.chunk, self.model.max_seq_len - off),
                minimum=self.minimum,
            )))
            off += n
        return plan

    def __call__(self, prompt):
        cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._cache_shapes
        )
        if self.kv_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.kv_sharding.mesh, PartitionSpec())
            cache = jax.tree_util.tree_map(
                lambda leaf: jax.device_put(
                    leaf,
                    self.kv_sharding if getattr(leaf, "ndim", 0) == 4
                    else rep,
                ),
                cache,
            )
        return self.resume(cache, prompt, 0)

    def resume(self, cache, prompt, start: int):
        """Prefill only ``prompt[start:]`` against a batch-1 cache whose
        K/V already hold positions ``[0, start)`` — the prefix-cache hit
        path (``tpudist.serve.blocks``): the shared blocks are gathered
        into the contiguous view, the cursors rewind to ``start``, and
        the model forward runs for the suffix alone (TTFT for a cache-hit
        admission drops to ~one chunk). ``start=0`` with a fresh cache is
        exactly ``__call__``. ``start`` must be < len(prompt): the last
        prompt token always re-runs so the final chunk yields its logits
        (the first sampled position)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        if not 0 < p <= self.model.max_seq_len:
            raise ValueError(
                f"prompt length {p} outside (0, {self.model.max_seq_len}]"
            )
        if not 0 <= start < p:
            raise ValueError(f"resume start {start} outside [0, {p})")
        if start:
            cache = _fresh_cursors(cache, start)
        plan = self.chunk_plan(p, start)
        off, logits, last = start, None, 0
        for i, (n, padded) in enumerate(plan):
            toks = np.zeros((1, padded), np.int32)
            toks[0, :n] = prompt[off : off + n]
            toks = jnp.asarray(toks)
            if i + 1 < len(plan) or not self.head:
                cache = self._run_chunk(cache, toks, final=False)
            else:
                cache, logits = self._run_chunk(cache, toks, final=True)
            off += n
            last = n - 1
        # NOTE on the cursor: after a padded final chunk the cache's scalar
        # cursors sit past p. The pool scatter copies only the 4-D buffers
        # (slots.write_slot) and the engine owns the slot's true length, so
        # the overshoot never escapes this function.
        if not self.head:
            return cache, None
        return cache, _index_logits(logits, jnp.asarray(last, jnp.int32))
