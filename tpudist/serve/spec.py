"""Speculative decoding: acceptance-rejection sampling over a draft
window, preserving the target distribution EXACTLY.

The engine's speculative tick (``tpudist.serve.engine``) runs, per live
slot: K cheap draft-model steps proposing tokens ``d_1..d_K``, then ONE
bulk target pass scoring the window ``[t_last, d_1..d_K]`` — K+1 rows of
target logits from a single weight sweep (the decode cost that matters
is HBM bytes per sequential pass, docs/PERF.md §7d). This module decides
what to EMIT from those two logit sets.

The acceptance identity (Leviathan et al. / Chen et al.): draft token
``d_i`` (sampled from the draft's warped distribution ``q_i``) is
accepted with probability ``min(1, p_i(d_i) / q_i(d_i))`` where ``p_i``
is the target's warped distribution at that position; at the FIRST
rejection the emitted token is drawn from the residual distribution
``norm(max(p_i - q_i, 0))``; if all K drafts are accepted a BONUS token
is drawn from ``p_{K+1}`` (the verify pass's last row — free, its logits
already exist). Marginally every emitted token is distributed exactly as
``p`` — speculation changes throughput, never the output distribution.

Both ``p`` and ``q`` here are the WARPED per-row distributions
(temperature → top_k → top_p) via :func:`tpudist.generate.per_row_log_probs`,
which shares its filter math with :func:`tpudist.generate.sample_logits_per_row`
— the distribution the draft was ACTUALLY sampled from, not the raw
softmax. Greedy rows (``temperature == 0``) need no special case: their
warped distribution is a point mass at the argmax, so the ratio test
accepts iff the draft matched the target argmax and the residual/bonus
is the target argmax itself — which is what makes greedy speculative
output token-identical to the non-speculative engine (pinned in
tests/test_serve_spec.py).

RNG discipline: the engine derives one key per (request, cursor) and
this module folds purpose salts into it — draft steps use salts
``0..K-1`` at the engine layer, acceptance uniforms and the residual
draw use the disjoint salts below. Cursors are strictly increasing and
replay-stable, so a preempted request re-draws the same stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpudist.generate import per_row_log_probs

# purpose salts folded into the engine's per-(request, cursor) key; the
# engine folds 0..K-1 for the K draft sampling steps, so these live far
# above any sane K
SALT_ACCEPT = 1 << 20
SALT_RESIDUAL = (1 << 20) + 1


def _rep(a, n: int):
    """Per-row sampling params ``[B]`` → per-verify-row ``[B * n]``
    (row-major, matching ``logits.reshape(b * n, v)``)."""
    return jnp.repeat(jnp.asarray(a), n, axis=0)


def speculative_accept(t_logits, d_logits, d_toks, n_spec, keys, *,
                       temperature, top_k, top_p):
    """Accept/reject a draft window against the target's verify logits.

    Args:
      t_logits: ``[B, K+1, V]`` target logits — row ``i`` is the target
        distribution at the position draft ``d_{i+1}`` was proposed for
        (row ``K`` scores the bonus position after a fully-accepted
        window).
      d_logits: ``[B, K, V]`` draft logits the proposals were sampled
        from (raw — warped here with the same per-row params).
      d_toks: ``[B, K]`` proposed draft tokens.
      n_spec: ``[B]`` int32 — per-row cap on how many drafts are ELIGIBLE
        (sequence-end / budget clamp from the engine; rows beyond it are
        treated as rejected without consuming randomness semantics).
      keys: ``[B]`` per-(request, cursor) rng keys.
      temperature / top_k / top_p: ``[B]`` per-row sampling params.

    Returns ``(emit [B, K+1] int32, n_emit [B] int32)``: the emitted
    tokens (accepted prefix + one correction/bonus token; positions past
    ``n_emit`` are zero-padded) with ``1 <= n_emit <= K+1``.
    """
    b, k1, v = t_logits.shape
    kk = k1 - 1
    n_spec = jnp.asarray(n_spec, jnp.int32)
    logp = per_row_log_probs(
        t_logits.reshape(b * k1, v),
        temperature=_rep(temperature, k1),
        top_k=_rep(top_k, k1),
        top_p=_rep(top_p, k1),
    ).reshape(b, k1, v)
    if kk:
        logq = per_row_log_probs(
            d_logits.reshape(b * kk, v),
            temperature=_rep(temperature, kk),
            top_k=_rep(top_k, kk),
            top_p=_rep(top_p, kk),
        ).reshape(b, kk, v)
    u_keys = jax.vmap(lambda key: jax.random.fold_in(key, SALT_ACCEPT))(keys)
    us = jax.vmap(lambda key: jax.random.uniform(key, (max(kk, 1),)))(u_keys)

    # sequential accept scan, unrolled (K is small and static): a draft is
    # kept iff every earlier draft was kept AND its own ratio test passes
    acc = jnp.ones(b, bool)
    n_acc = jnp.zeros(b, jnp.int32)
    for i in range(kk):
        d_i = d_toks[:, i][:, None]
        lp = jnp.take_along_axis(logp[:, i], d_i, axis=-1)[:, 0]
        lq = jnp.take_along_axis(logq[:, i], d_i, axis=-1)[:, 0]
        # min(1, p/q) as exp(min(0, lp - lq)); lp=-inf → ratio 0 (reject),
        # lq=-inf (can't arise from a q-sampled token; ties aside) → NaN
        # or ratio 1, and u < NaN rejects — both safe
        ratio = jnp.exp(jnp.clip(lp - lq, None, 0.0))
        ok = (us[:, i] < ratio) & (i < n_spec) & acc
        n_acc = n_acc + ok
        acc = acc & ok

    # first-rejection (or bonus) position m = n_acc: correction token from
    # the residual norm(max(p_m - q_m, 0)). Where no proposal existed
    # (m == n_spec: the bonus row, a sequence-end clamp, or K == 0) q is
    # zero and the residual is p_m itself — the plain target draw.
    m = n_acc
    logp_m = jnp.take_along_axis(logp, m[:, None, None], axis=1)[:, 0]
    p_m = jnp.exp(logp_m)  # [B, V]
    if kk:
        mi = jnp.minimum(m, kk - 1)[:, None, None]
        q_m = jnp.exp(jnp.take_along_axis(logq, mi, axis=1)[:, 0])
        q_m = jnp.where((m < n_spec)[:, None], q_m, 0.0)
    else:
        q_m = jnp.zeros_like(p_m)
    residual = jnp.maximum(p_m - q_m, 0.0)
    rsum = jnp.sum(residual, axis=-1, keepdims=True)
    # all-zero residual (p <= q pointwise — only float rounding can get
    # here, since exact p == q never rejects): fall back to p itself
    res = jnp.where(rsum > 0.0, residual / rsum, p_m)
    corr_keys = jax.vmap(
        lambda key: jax.random.fold_in(key, SALT_RESIDUAL)
    )(keys)
    corr = jax.vmap(jax.random.categorical)(corr_keys, jnp.log(res))

    cols = jnp.arange(k1)[None, :]
    if kk:
        d_pad = jnp.concatenate(
            [d_toks, jnp.zeros((b, 1), d_toks.dtype)], axis=1
        )
    else:
        d_pad = jnp.zeros((b, k1), jnp.int32)
    emit = jnp.where(cols < m[:, None], d_pad, 0)
    emit = jnp.where(cols == m[:, None], corr[:, None], emit)
    return emit.astype(jnp.int32), (m + 1).astype(jnp.int32)


def early_exit_draft(model, params, depth: int):
    """A draft that is the target's own SHALLOW PREFIX: same embeddings,
    first ``depth`` transformer blocks, and final norm/head, sharing the
    target's parameter arrays (zero extra weight HBM — the draft's only
    footprint is its KV cache). The natural stand-in before a distilled
    draft exists: early-exit logits correlate with the full model's, and
    the correlation (= acceptance rate) is MEASURED by the engine's
    telemetry, never assumed.

    Works for the unrolled GPT-2 (``h_{i}`` blocks, ``wte``/``wpe``/
    ``ln_f``) and Llama (``layer_{i}``, ``embed``/``norm``[/``lm_head``])
    families. Returns ``(draft_model, draft_params)``.
    """
    if not 1 <= depth < model.depth:
        raise ValueError(
            f"draft depth {depth} outside [1, {model.depth}) of the target"
        )
    draft = model.clone(depth=depth)
    if "wte" in params:  # GPT-2 family
        block, shared = "h_{}", ("wte", "wpe", "ln_f")
    elif "embed" in params:  # Llama family
        block, shared = "layer_{}", ("embed", "norm", "lm_head")
    else:
        raise ValueError(
            f"unrecognized param layout {sorted(params)[:4]}...; "
            "early_exit_draft knows the GPT-2 and Llama families"
        )
    if block.format(0) not in params:
        raise ValueError(
            f"early_exit_draft needs unrolled per-layer params (missing "
            f"{block.format(0)!r}); scanned/stacked layouts aren't "
            "sliceable by depth"
        )
    dp = {k: params[k] for k in shared if k in params}
    for i in range(depth):
        dp[block.format(i)] = params[block.format(i)]
    return draft, dp


def cache_bytes(model, rows: int, *, tensor_world: int = 1) -> int:
    """KV-cache bytes ``model.init_cache(rows)`` would allocate (4-D K/V
    buffers only, via ``eval_shape`` — nothing materializes). The number
    the equal-HBM A/B and SERVING.md's "cache sizing with a draft" use:
    a speculative engine pays this for its draft on TOP of the target
    pool, so at fixed HBM the draft cache comes out of the target's block
    budget (:func:`tpudist.serve.blocks.draft_equivalent_blocks`).

    ``tensor_world``: PER-CHIP bytes on a tensor-sharded engine
    (``ServeEngine(mesh=...)``) — the 4-D buffers shard exactly on the
    KV-head dim, so each chip holds ``1/T`` of every buffer (the engine's
    head-divisibility refusal guarantees the split is even; the
    ``mc_serve`` bench leg budgets with this)."""
    tree = jax.eval_shape(lambda: model.init_cache(rows))
    total = sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if len(leaf.shape) == 4
    )
    return total // max(int(tensor_world), 1)
