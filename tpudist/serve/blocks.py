"""Paged KV cache: a shared block pool with per-slot block tables.

The contiguous slot pool (:mod:`tpudist.serve.slots`) reserves a
worst-case ``[max_slots, H, max_seq_len, dh]`` cache — every slot pays
``max_seq_len`` whether its request is 20 tokens or 400. Under the
long-tail budgets real chat traffic has (the serve bench's 16+Exp(80)
distribution), most of the bytes each decode step's attention window
COULD cover are never written, yet they bound how many requests fit a
chip. This module replaces that layout with the vLLM-style paged one,
grounded in the Gemma-on-TPU serving comparison (PAPERS.md,
arxiv 2605.25645):

- **one pool per layer** — ``[n_blocks, H_kv, block_size, dh]``
  (:func:`paged_cache` builds the tree by re-shaping the model's
  contiguous ``init_cache`` leaves, so the flax cache collection's
  structure is untouched and no model init path is needed);
- **per-slot block tables** — host-side ``[max_slots, max_blocks]`` maps
  from logical block index to physical pool block, fed to the compiled
  decode step each tick (``tpudist.ops.decode.cached_kv(block_tables=)``);
  a slot allocates its next block only when its cursor crosses a block
  boundary, so HBM holds **Σ(actual lengths)** rounded up to the block
  and the engine admits far more concurrent requests per chip;
- **refcounted blocks + prefix cache** — physical blocks are refcounted
  (:class:`BlockPool`); completed prompt-prefix blocks are content-hashed
  by their token ids (:class:`PrefixCache`) so N requests sharing a
  system prompt map the SAME physical blocks and pay prefill once. The
  divergence point is block-granular copy-on-write by construction: only
  FULL blocks whose tokens match exactly are shared, a shared block is
  never written again (decode writes always land in the slot's private
  suffix), and the first divergent/partial block is private from the
  start — so there is no write-fault machinery to get wrong.

Physical block 0 is a reserved GARBAGE block, never allocated: inactive
decode rows carry all-zero tables and positions, so their masked
ride-along writes land in block 0 where no live table ever points.

Lifecycle invariants (pinned by the refcount torture test):

- ``refcount[b] == (#live slot tables containing b) + (1 if the prefix
  cache holds b)``;
- a block returns to the free list exactly when its refcount hits 0 —
  releasing a slot cannot free a block the prefix cache (or another
  slot, via a shared prefix) still holds;
- prefix-cache entries form hash CHAINS (entry i's hash folds entry
  i-1's); eviction only takes LRU **leaves** whose block no slot maps,
  so a cached chain is never broken in the middle (a mid-chain hole
  would orphan its descendants' refcounts forever).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


#: reserved garbage block: inactive rows' tables point here; never allocated
GARBAGE_BLOCK = 0


def paged_cache(model, n_blocks: int, block_size: int, kv_sharding=None):
    """The device-side block pool: the model's contiguous decode-cache
    tree (``init_cache`` shapes at batch 1) with every 4-D
    ``[1, H, max_len, dh]`` K/V leaf re-shaped to
    ``[n_blocks, H, block_size, dh]``. Scalar cursor leaves keep their
    (unused in paged mode, but structure-preserving) zeros — the same
    tree-structure discipline that lets one donated pytree flow through
    the compiled decode step.

    ``kv_sharding``: optional :class:`jax.sharding.NamedSharding` for the
    4-D pool leaves — the multi-chip engine shards the pool on the
    KV-head dim (``[n_blocks, H_kv/T, block_size, dh]`` per chip,
    ``P(None, 'tensor', None, None)``); scalar leaves stay replicated.
    Host block tables are NOT affected — all chips see the same logical
    pool, each holding its own head slice of every block."""
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
            train=False, decode=True,
        )
    )["cache"]

    rep = None
    if kv_sharding is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(kv_sharding.mesh, PartitionSpec())

    def build(leaf):
        if len(leaf.shape) == 4:
            buf = jnp.zeros(
                (n_blocks, leaf.shape[1], block_size, leaf.shape[3]),
                leaf.dtype,
            )
            return buf if kv_sharding is None else jax.device_put(
                buf, kv_sharding
            )
        buf = jnp.zeros(leaf.shape, leaf.dtype)
        # scalar cursors commit replicated on the same mesh — a leaf left
        # on one device would make the decode step's AOT lowering mix
        # device sets
        return buf if rep is None else jax.device_put(buf, rep)

    return jax.tree_util.tree_map(build, shapes)


@partial(jax.jit, donate_argnums=(0,), static_argnames=("block_size",))
def scatter_blocks(pool, row_cache, table, start, end, *, block_size):
    """Scatter a contiguous batch-1 prefill cache's K/V into the pool
    blocks ``table[start:end]`` (each block ``j`` takes rows
    ``[j·bs, (j+1)·bs)`` of the row cache). ``start``/``end`` are traced
    scalars — ONE compiled program serves every (hit length, prompt
    length) pair. The pool is donated (in-place per-block
    dynamic_update_slices); blocks outside ``[start, end)`` — shared
    prefix-cache hits in particular — are never touched."""
    start = jnp.asarray(start, jnp.int32)
    end = jnp.asarray(end, jnp.int32)
    table = jnp.asarray(table, jnp.int32)

    def per_leaf(p, row):
        if getattr(row, "ndim", 0) != 4 or p.ndim != 4:
            return p

        def body(j, acc):
            src = jax.lax.dynamic_slice(
                row, (0, 0, j * block_size, 0),
                (1, row.shape[1], block_size, row.shape[3]),
            )
            return jax.lax.dynamic_update_slice(
                acc, src.astype(acc.dtype), (table[j], 0, 0, 0)
            )

        return jax.lax.fori_loop(start, end, body, p)

    return jax.tree_util.tree_map(per_leaf, pool, row_cache)


@jax.jit
def gather_prefix(pool, table):
    """The inverse view for prefix-cache hits: assemble a contiguous
    batch-1 cache tree from the pool blocks ``table`` maps (one gather
    per layer, fixed shape — one compile). Blocks past the hit length map
    the garbage block; their bytes sit above the prefill cursor where the
    causal mask never admits them, so no zeroing is needed. The caller
    (the engine's admission path) resumes chunked prefill on the result
    at the hit length, paying the model forward only for the suffix."""
    table = jnp.asarray(table, jnp.int32)

    def per_leaf(p):
        if p.ndim != 4:
            return jnp.zeros(p.shape, p.dtype)
        mb = table.shape[0]
        g = p[table]  # [mb, H, bs, dh]
        return g.transpose(1, 0, 2, 3).reshape(
            1, p.shape[1], mb * p.shape[2], p.shape[3]
        )

    return jax.tree_util.tree_map(per_leaf, pool)


class BlockPool:
    """Host-side physical-block accounting: a free list plus per-block
    refcounts. Pure bookkeeping — the device tree lives with
    :class:`PagedSlotPool`. Block 0 (:data:`GARBAGE_BLOCK`) is reserved."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (one is the garbage block), got "
                f"{n_blocks}"
            )
        self.n_blocks = n_blocks
        self.refcount = np.zeros(n_blocks, np.int32)
        self._free: collections.deque[int] = collections.deque(
            range(1, n_blocks)
        )

    @property
    def n_usable(self) -> int:
        return self.n_blocks - 1

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.n_usable - self.n_free

    @property
    def occupancy(self) -> float:
        return self.n_used / self.n_usable

    def alloc(self) -> int | None:
        """Take a free block (refcount 1) or ``None`` when the pool is
        dry — the caller then evicts/preempts; allocation itself never
        raises so admission control can probe."""
        if not self._free:
            return None
        b = self._free.popleft()
        self.refcount[b] = 1
        return b

    def incref(self, block: int) -> None:
        if block == GARBAGE_BLOCK or self.refcount[block] <= 0:
            raise RuntimeError(f"incref of unallocated block {block}")
        self.refcount[block] += 1

    def decref(self, block: int) -> None:
        """Drop one reference; the block returns to the free list exactly
        at refcount 0 (a double-free raises — the torture test's bar)."""
        if block == GARBAGE_BLOCK or self.refcount[block] <= 0:
            raise RuntimeError(f"decref of free block {block} (double free)")
        self.refcount[block] -= 1
        if self.refcount[block] == 0:
            self._free.append(block)


@dataclasses.dataclass
class _PrefixEntry:
    block: int
    parent: bytes | None
    children: int
    last_use: int


class PrefixCache:
    """Content-addressed prompt-prefix blocks: chain hash → physical
    block. Entry ``i``'s key folds entry ``i-1``'s digest with block
    ``i``'s token bytes, so a lookup walks the prompt's full blocks until
    the first miss — a hit can only be an exact token-prefix match.

    The cache holds ONE pool reference per entry; slots sharing the block
    hold their own. Eviction (:meth:`evict`) frees LRU chain LEAVES whose
    block no slot maps (pool refcount == 1), never mid-chain blocks.
    Hit/lookup accounting lives with :class:`ServeStats` (the engine
    reports per COMMITTED admission — one home for the hit rate)."""

    def __init__(self, pool: BlockPool, block_size: int):
        self.pool = pool
        self.block_size = block_size
        self._entries: dict[bytes, _PrefixEntry] = {}
        self._tick = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _chain(self, tokens: np.ndarray) -> list[bytes]:
        """Chain digests for every FULL block of ``tokens``."""
        bs = self.block_size
        digests, prev = [], b""
        for j in range(len(tokens) // bs):
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(np.ascontiguousarray(
                tokens[j * bs:(j + 1) * bs], np.int32).tobytes())
            prev = h.digest()
            digests.append(prev)
        return digests

    def lookup(self, tokens: np.ndarray, max_tokens: int) -> list[int]:
        """Physical blocks of the longest cached full-block prefix of
        ``tokens``, capped at ``max_tokens`` (the engine caps at
        ``len(prompt) - 1``: the last prompt token must always re-run so
        prefill yields its logits). Touches matched entries' LRU
        clocks."""
        self._tick += 1
        usable = tokens[: max(int(max_tokens), 0)]
        hits: list[int] = []
        chain = self._chain(np.asarray(usable))
        for digest in chain:
            e = self._entries.get(digest)
            if e is None:
                break
            e.last_use = self._tick
            hits.append(e.block)
        return hits

    def insert(self, tokens: np.ndarray, blocks, n_known: int) -> None:
        """Register the full blocks of ``tokens`` beyond the first
        ``n_known`` (the lookup's hits, already cached) under their chain
        hashes, taking one pool reference each. ``blocks[j]`` is the
        slot's physical block for logical block ``j`` — freshly written by
        the prefill scatter and never written again (decode appends past
        the prompt), which is what makes sharing them safe."""
        self._tick += 1
        chain = self._chain(np.asarray(tokens))
        for j in range(n_known, len(chain)):
            digest = chain[j]
            if digest in self._entries:
                # already cached by a racing admission this drain — the
                # slot keeps its private copy; no second cache ref
                continue
            parent = chain[j - 1] if j else None
            self.pool.incref(int(blocks[j]))
            self._entries[digest] = _PrefixEntry(
                int(blocks[j]), parent, 0, self._tick
            )
            if parent is not None and parent in self._entries:
                self._entries[parent].children += 1

    def evict(self, need: int) -> int:
        """Free up to ``need`` blocks by dropping LRU leaf entries whose
        block only the cache still references; returns how many were
        freed. Dropping a leaf may expose its parent as the next leaf —
        the loop walks chains tail-first, never breaking one mid-chain."""
        freed = 0
        while freed < need:
            best = None
            for digest, e in self._entries.items():
                if e.children:
                    continue
                if self.pool.refcount[e.block] != 1:
                    continue  # a live slot still maps it
                if best is None or e.last_use < self._entries[best].last_use:
                    best = digest
            if best is None:
                return freed
            e = self._entries.pop(best)
            if e.parent is not None and e.parent in self._entries:
                self._entries[e.parent].children -= 1
            self.pool.decref(e.block)
            freed += 1
        return freed


class PagedSlotPool:
    """The paged counterpart of :class:`tpudist.serve.slots.SlotPool`:
    same slot bookkeeping surface (``positions``/``active``/``n_active``/
    ``n_free``/``advance``/``release``), but ``cache`` is the shared
    block pool and each slot owns a block TABLE instead of a contiguous
    row. The engine feeds ``tables[:, :]`` to the compiled decode step
    alongside the per-slot cursors.

    ``utilization`` reports BLOCK-pool occupancy, not active/max_slots:
    under block-budget admission the slot count no longer measures free
    capacity (16 slots can be "free" while the pool is byte-full, and
    vice versa) — the contiguous :class:`SlotPool`'s slot-count property
    would overstate it. The engine's ``serve`` rows keep the old
    ``slot_utilization`` field with its old slot-count meaning and add
    ``pool_occupancy`` for this number (docs/OBSERVABILITY.md §1).
    """

    def __init__(self, model, max_slots: int, *, n_blocks: int,
                 block_size: int, prefix_cache: bool = True,
                 kv_sharding=None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if not hasattr(model, "init_cache"):
            raise ValueError(
                f"{type(model).__name__} has no init_cache hook (the decode "
                "contract tpudist.serve requires); GPT-2 and Llama carry it"
            )
        if block_size < 1 or model.max_seq_len % block_size:
            raise ValueError(
                f"block_size {block_size} must divide max_seq_len "
                f"{model.max_seq_len} (tables and the prefill scatter "
                "assume whole blocks)"
            )
        self.max_slots = max_slots
        self.max_seq_len = model.max_seq_len
        self.block_size = block_size
        self.max_blocks = model.max_seq_len // block_size
        self.blocks = BlockPool(n_blocks)
        self.prefix = (
            PrefixCache(self.blocks, block_size) if prefix_cache else None
        )
        self.cache = paged_cache(model, n_blocks, block_size, kv_sharding)
        self.tables = np.zeros((max_slots, self.max_blocks), np.int32)
        self.fill = np.zeros(max_slots, np.int32)  # table entries in use
        self.positions = np.zeros(max_slots, np.int32)
        self.active = np.zeros(max_slots, bool)
        self._free: collections.deque[int] = collections.deque(
            range(max_slots)
        )

    # -- capacity ----------------------------------------------------------

    @property
    def n_active(self) -> int:
        return int(self.active.sum())

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def utilization(self) -> float:
        """BLOCK occupancy (the byte truth), NOT active/max_slots — see
        the class docstring for why the slot-count reading is wrong under
        paged admission.

        On a tensor-sharded engine (``ServeEngine(mesh=...)``) this is a
        PER-CHIP reading: the pool shards on the KV-head dim, so every
        chip maps the same block set (one host-side ``BlockPool``, one
        table) and occupancy is identical on all T chips — the fraction
        reported here is of each chip's ``n_blocks × bytes/T`` slice, not
        of the aggregate. The ``serve`` rows label it with
        ``tensor_world`` so readers can do the aggregate math
        (docs/OBSERVABILITY.md §1)."""
        return self.blocks.occupancy

    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def free_after_evict(self) -> int:
        """Blocks available to a new allocation if every evictable
        prefix-cache leaf were dropped — what admission budgets against."""
        free = self.blocks.n_free
        if self.prefix is None:
            return free
        # every cache-only block (refcount 1) is transitively evictable: a
        # slot mapping a chain's block necessarily maps its whole prefix
        # (its table holds the consecutive blocks), so refcount 1 on any
        # entry implies refcount 1 on all its descendants — the eviction
        # loop reaches them leaves-first
        return free + sum(
            1 for e in self.prefix._entries.values()
            if self.blocks.refcount[e.block] == 1
        )

    # -- slot lifecycle ----------------------------------------------------

    def insert(self, row_cache, true_len: int, *, prompt=None,
               hit_blocks=()) -> int:
        """Admit a prefilled request: take a slot, map ``hit_blocks``
        (shared prefix, one ref each), allocate private blocks for the
        rest of ``true_len`` tokens, scatter the row cache's K/V into the
        PRIVATE blocks only, and (when a prompt is given and the prefix
        cache is on) register the prompt's full blocks for future
        sharing. The caller verified the block budget; an allocation
        failure here is an admission bug and raises."""
        if not self._free:
            raise RuntimeError("slot pool exhausted (admission bug)")
        if not 0 < true_len <= self.max_seq_len:
            raise ValueError(
                f"prefix length {true_len} outside (0, {self.max_seq_len}]"
            )
        n_hit = len(hit_blocks)
        n_need = self.blocks_for(true_len)
        if n_hit > n_need:
            raise ValueError(f"hit blocks {n_hit} exceed prefix {true_len}")
        slot = self._free.popleft()
        table = np.zeros(self.max_blocks, np.int32)
        for j, b in enumerate(hit_blocks):
            self.blocks.incref(int(b))
            table[j] = int(b)
        for j in range(n_hit, n_need):
            b = self.blocks.alloc()
            if b is None:  # roll back to stay leak-free before raising
                for jj in range(j):
                    self.blocks.decref(int(table[jj]))
                self._free.appendleft(slot)
                raise RuntimeError(
                    "block pool exhausted mid-insert (admission bug)"
                )
            table[j] = b
        if n_need > n_hit:
            self.cache = scatter_blocks(
                self.cache, row_cache, jnp.asarray(table),
                n_hit, n_need, block_size=self.block_size,
            )
        self.tables[slot] = table
        self.fill[slot] = n_need
        self.positions[slot] = true_len
        self.active[slot] = True
        if self.prefix is not None and prompt is not None:
            self.prefix.insert(prompt, table, n_hit)
        return slot

    def needs_block(self, slot: int) -> bool:
        """True when the slot's next write position falls past its mapped
        blocks — the engine must ``ensure_next`` (or preempt) before
        dispatching this slot."""
        return int(self.positions[slot]) // self.block_size >= int(
            self.fill[slot]
        )

    def ensure_next(self, slot: int) -> bool:
        """Map the slot's next block; ``False`` when the pool is dry (the
        engine then evicts prefix leaves or preempts a victim)."""
        if not self.needs_block(slot):
            return True
        b = self.blocks.alloc()
        if b is None:
            return False
        self.tables[slot, self.fill[slot]] = b
        self.fill[slot] += 1
        return True

    def ensure_to(self, slot: int, n_tokens: int) -> bool:
        """Map blocks until the slot's table covers ``n_tokens`` positions
        (clamped to the table's extent); ``False`` when the pool runs dry
        mid-way (already-mapped blocks stay mapped — the engine escalates
        and retries). The SPECULATIVE dispatch path: a verify sweep writes
        up to ``spec_k + 1`` tokens past a cursor the host only knows one
        fetch late, so the engine maps the whole conservative window at
        once instead of one ``ensure_next`` per emitted token."""
        need = min(self.blocks_for(n_tokens), self.max_blocks)
        while int(self.fill[slot]) < need:
            b = self.blocks.alloc()
            if b is None:
                return False
            self.tables[slot, self.fill[slot]] = b
            self.fill[slot] += 1
        return True

    def advance(self, slot: int) -> None:
        """One decode step wrote this slot's token at its cursor; bump it."""
        self.positions[slot] += 1

    def release(self, slot: int) -> None:
        """Drop the slot's reference on every mapped block (shared prefix
        blocks survive under the cache's or other slots' refs) and recycle
        the slot."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} released twice")
        for j in range(int(self.fill[slot])):
            self.blocks.decref(int(self.tables[slot, j]))
        self.tables[slot] = 0
        self.fill[slot] = 0
        self.positions[slot] = 0
        self.active[slot] = False
        self._free.append(slot)

    def evict_prefix(self, need: int) -> int:
        return 0 if self.prefix is None else self.prefix.evict(need)

    def gather_row(self, hit_blocks) -> object:
        """Contiguous batch-1 cache view of a prefix-cache hit (pads the
        table with the garbage block; the bytes above the hit cursor are
        never attended) — the admission path resumes chunked prefill on
        it. Scalar cursor leaves are re-created HOST-side with one buffer
        each: inside the jitted gather XLA CSEs the identical scalar
        zeros into one output buffer, and the chunk programs then donate
        that buffer twice (a hard runtime error)."""
        table = np.zeros(self.max_blocks, np.int32)
        table[: len(hit_blocks)] = hit_blocks
        row = gather_prefix(self.cache, jnp.asarray(table))
        return jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, l.dtype) if l.ndim != 4 else l, row
        )


def draft_equivalent_blocks(model, draft_model, max_slots: int,
                            block_size: int) -> int:
    """How many TARGET-model KV blocks the draft pool's bytes buy — the
    equal-HBM handicap for the speculative-vs-autoregressive A/B
    (bench.py's ``spec`` leg): the speculative engine allocates a full
    contiguous draft SlotPool on top of its paged target pool, so the
    honest baseline gives the plain engine that many EXTRA target blocks
    instead. Rounds up (the baseline gets the benefit of the doubt)."""
    from tpudist.serve.spec import cache_bytes

    per_token = cache_bytes(model, 1) // model.max_seq_len
    draft = cache_bytes(draft_model, max_slots)
    return -(-draft // max(per_token * block_size, 1))
