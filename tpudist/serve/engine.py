"""Continuous-batching inference engine.

The scheduler over the slot pool: a priority-laned request queue with
admission control, per-slot sampling/stop params, per-step streaming token
delivery, and latency-SLO telemetry. One scheduler **tick**
(:meth:`ServeEngine.step`) is:

1. **admit** — while a slot is free, the active count is under
   ``max_active``, and the budget holds (slot count on the contiguous
   pool; BLOCK budget on the paged pool): pop the most urgent queued
   request, run its bucketed chunked prefill (``tpudist.serve.prefill``
   — resumed past any prefix-cache hit), sample its FIRST token from the
   prefill logits (that emission is the request's TTFT), and map its
   prefix K/V into a slot;
2. **dispatch** — ONE compiled masked decode step over the FULL slot batch
   (``positions=`` per-slot cursors — plus per-slot block tables in paged
   mode — non-live slots ride along masked): write each fed token's K/V
   at its slot's cursor, sample each slot's next token with its own
   params and rng stream (:func:`tpudist.generate.sample_logits_per_row`),
   apply the shared stop rule (:func:`tpudist.generate.eos_retire`);
3. **process** — fetch the PREVIOUS tick's dispatched step, stream its
   tokens, and retire finished slots (stop token or budget), making room
   for the next admission — requests join and leave between decode steps
   with ZERO recompiles.

The decode loop is **one-step-delayed**, the same pipeline idiom as
``fit()``'s metric fetch (docs/PERF.md §3): step ``k`` is dispatched
BEFORE step ``k-1``'s tokens are fetched, and each step's sampled tokens
feed the next step ON DEVICE (a carried ``[S]`` token array, overridden
per-slot at admission), so the device never idles waiting for a host
round-trip. On this repo's remote attach a synchronous per-step fetch
costs ~100 ms RTT — more than ten 124M decode steps; the delayed fetch
hides it entirely. The price is bounded and paid only on retirement: a
slot whose stop token is discovered one tick late burns at most ONE
masked zombie row-step (its write lands at its own cursor and the slot
is released before anything reads it), and the ``(request_id, slot
ownership)`` snapshot guard discards the zombie's output.

**Paged mode** (``paged=True``, docs/SERVING.md "Paged memory"): the KV
cache becomes a shared block pool with per-slot block tables
(:mod:`tpudist.serve.blocks`), so HBM holds Σ(actual lengths) instead of
``max_slots × max_seq_len`` and ``max_slots`` can rise to whatever the
byte budget actually supports under the traffic's length distribution.
Three scheduler behaviors only exist there:

- **block-budget admission**: a request admits when the pool can map its
  (post-prefix-hit) prompt plus ``watermark_blocks`` of decode headroom,
  evicting cold prefix-cache leaves first — slot count alone no longer
  measures capacity;
- **prefix cache**: completed prompt-prefix blocks are content-hashed and
  shared copy-on-write at block granularity, so requests repeating a
  system prompt skip its prefill (TTFT drops to ~one chunk) and share
  its bytes;
- **preempt-to-queue**: when the pool runs dry mid-decode (a slot's
  cursor needs a block and eviction finds none), the newest
  lowest-priority slot is evicted back to the FRONT of its lane — its
  blocks free NOW, its prompt+progress replay at re-admission (prefix
  cache usually making the replay cheap), and its token stream continues
  exactly where it stopped (the replayed request re-enters decode at the
  same cursor, rng stream, and sampling state — greedy output stays
  bit-identical through an eviction cycle, pinned by test).

**Speculative mode** (``draft_model=``, docs/SERVING.md §6): each tick a
cheap DRAFT model proposes ``spec_k`` tokens per live slot (K+1
single-token draft steps against a second, slot-pinned draft KV pool),
and the target scores the whole window ``[last, d_1..d_K]`` in ONE bulk
decode pass — the accepted prefix plus one correction/bonus token all
land in a single target weight sweep, so a slot emits up to ``spec_k+1``
tokens per tick at roughly one sequential-pass cost (docs/PERF.md §7d:
fewer passes beats faster passes). Acceptance-rejection sampling
(:mod:`tpudist.serve.spec`) preserves the target distribution EXACTLY —
greedy speculative output is token-identical to the non-speculative
engine, pinned by test. The cursor becomes DEVICE-carried (``[S]``
positions ride the step outputs, since only the device knows how many
tokens each sweep accepted); the host's view syncs at each delayed
fetch, lagging at most two sweeps — the paged block-mapping horizon
covers ``2·(spec_k+1)`` tokens of that lag. "Rollback" of rejected
draft K/V is pure cursor bookkeeping: stale entries above the cursor
are overwritten before the causal mask ever admits them.

**Priority lanes**: ``submit(priority=N)`` — admission always serves the
highest-priority non-empty lane, FIFO within a lane, UNLESS
``ttft_slo_s`` is set and a lower lane's head has waited past it (then
the oldest overdue head goes first — TTFT-deadline-driven aging, fed by
the same clock ``stats.py`` measures TTFT with, so starvation surfaces
in the ``serve`` rows exactly when the scheduler acts on it).

Why this wins over static batching: a static batch must assemble before
prefill (queue wait on the LAST arrival) and every row decodes until the
LONGEST request finishes (retired rows burn full decode steps). The
engine's decode batch stays full under mixed-length Poisson arrivals —
the ``serve`` bench leg measures the tokens/s gap and the TTFT collapse;
the ``paged`` leg measures what the block pool adds at equal HBM.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpudist.generate import eos_retire, sample_logits_per_row
from tpudist.serve.prefill import Prefiller
from tpudist.serve.slots import SlotPool
from tpudist.serve.stats import ServeStats

NO_EOS = -1  # token ids are non-negative, so -1 never matches


class QueueFull(RuntimeError):
    """Admission control: the request queue is at ``max_queue``. Callers
    shed load (or retry later) — unbounded queues just move the failure
    to an OOM or an SLO blowout."""


@dataclasses.dataclass(frozen=True)
class Request:
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = NO_EOS
    priority: int = 0
    # a preempted request re-queues with the tokens it already emitted:
    # re-admission rebuilds its K/V (prompt + replay[:-1]) via prefill —
    # prefix-cache hits making most of that a gather — and feeds
    # replay[-1] as the next step's input, continuing the stream without
    # re-emitting anything
    replay_tokens: tuple | None = None


@dataclasses.dataclass(frozen=True)
class TokenEvent:
    """One streamed token: ``index`` is its 0-based position in the
    request's generated sequence; ``done`` marks the request's last
    token (EOS or budget)."""

    request_id: int
    token: int
    index: int
    done: bool


@dataclasses.dataclass
class _Inflight:
    """A dispatched-but-unfetched decode step: the device token/stop
    futures plus the host-side snapshot of which slots were live and who
    owned them at dispatch time (ownership can change before the fetch —
    the processing guard keys on it)."""

    tok: jax.Array
    done: jax.Array
    live: np.ndarray   # [S] bool — rows fed for real at this dispatch
    rid: np.ndarray    # [S] int64 — owner snapshot


@dataclasses.dataclass
class _SpecInflight:
    """A dispatched-but-unfetched SPECULATIVE sweep: the device futures
    for the emitted window (``emit [S, K+1]`` / ``n_emit [S]``), the
    eligible-draft counts (``n_spec`` — acceptance-rate telemetry), the
    advanced cursors (``pos`` — the host's position sync), the eos flags,
    and the same ownership snapshot the plain pipeline keys its zombie
    guard on."""

    emit: jax.Array
    n_emit: jax.Array
    n_spec: jax.Array
    pos: jax.Array
    done: jax.Array
    live: np.ndarray   # [S] bool — rows fed for real at this dispatch
    rid: np.ndarray    # [S] int64 — owner snapshot


def _build_spec_step(model, params, draft_model, draft_params, base_key,
                     spec_k: int, paged: bool):
    """The one compiled SPECULATIVE step over the full slot batch:
    ``spec_k`` single-token draft proposals (plus one priming step so a
    fully-accepted window's K/V is complete), ONE bulk target verify pass
    over ``[last, d_1..d_K]``, acceptance-rejection
    (:func:`tpudist.serve.spec.speculative_accept`), and an in-graph
    first-EOS cut. Both caches are donated; the cursor and last-token
    lanes are device-carried outputs (only the device knows each row's
    acceptance count).

    Per-row clamps make one formula cover sequence end AND budget:
    ``limit = prompt_len + max_new_tokens`` rides in as a device input,
    ``allowed = limit - 1 - pos`` is how many tokens the row may still
    emit, and ``n_spec = clip(allowed - 1, 0, K)`` caps eligibility so
    ``n_emit <= allowed`` — the device NEVER overshoots a budget, which
    is what keeps the paged block-mapping horizon inside the worst case
    ``submit()`` already validated (no admission livelock). Draft/verify
    writes past the clamp land above the cursor (contiguous: the one-hot
    write self-clamps past ``max_seq_len``; paged: unmapped table
    entries redirect to the garbage block) and rows past ``n_spec`` are
    never consumed, so the overshoot is dead weight, not corruption.

    RNG: one key per (request, cursor) — ``fold(fold(base, rid), pos)``;
    draft step ``i`` folds salt ``i``, acceptance/residual use the
    disjoint salts in :mod:`tpudist.serve.spec`. ``pos`` is strictly
    increasing and replay-stable, so a preempted request re-draws the
    same stream; ``pos >= 1`` (prompts are non-empty) keeps the space
    disjoint from ``_first_token``'s token-index-0 keys."""
    from tpudist.serve.spec import speculative_accept

    K = int(spec_k)

    def body(cache, d_cache, prev_tok, override_tok, use_override, pos_in,
             override_pos, done, req_ids, temperature, top_k, top_p, eos,
             limit, block_tables=None):
        extra = {} if block_tables is None else {"block_tables": block_tables}
        tok0 = jnp.where(use_override, override_tok, prev_tok)
        pos = jnp.where(use_override, override_pos, pos_in).astype(jnp.int32)
        allowed = limit - 1 - pos          # tokens this row may still emit
        n_spec = jnp.clip(allowed - 1, 0, K).astype(jnp.int32)
        alive = (~done) & (allowed > 0)

        keys = jax.vmap(
            lambda r, p: jax.random.fold_in(jax.random.fold_in(base_key, r), p)
        )(req_ids, pos)

        # K draft proposals, each a masked single-token step at its own
        # per-row position (the draft pool rides the SAME slot/cursor
        # lanes as the target), sampled from the draft's WARPED
        # distribution — the distribution the acceptance ratio divides by
        cur, d_toks, d_logits = tok0, [], []
        for i in range(K):
            dl, dup = draft_model.apply(
                {"params": draft_params, "cache": d_cache}, cur[:, None],
                train=False, decode=True, mutable=["cache"],
                positions=pos + i,
            )
            d_cache = dup["cache"]
            ki = jax.vmap(lambda kk: jax.random.fold_in(kk, i))(keys)
            cur = sample_logits_per_row(
                dl[:, -1], ki, temperature=temperature, top_k=top_k,
                top_p=top_p,
            )
            d_toks.append(cur)
            d_logits.append(dl[:, -1])
        if K:
            # prime d_K's draft K/V (logits discarded — return_hidden
            # skips the head): after a FULLY accepted window the next
            # tick feeds the bonus token at pos+K+1, and the draft must
            # attend d_K at pos+K
            _, dup = draft_model.apply(
                {"params": draft_params, "cache": d_cache}, cur[:, None],
                train=False, decode=True, mutable=["cache"],
                positions=pos + K, return_hidden=True,
            )
            d_cache = dup["cache"]
        d_toks_a = jnp.stack(d_toks, axis=1)      # [S, K]
        d_logits_a = jnp.stack(d_logits, axis=1)  # [S, K, V]

        # ONE bulk target pass scores the whole window [tok0, d_1..d_K]:
        # K+1 rows of target logits from a single weight sweep, writing
        # every window token's K/V at its own per-row position in the
        # same pass (accepted tokens' K/V is already in place next tick;
        # rejected tokens' K/V sits above the cursor, dead)
        window = jnp.concatenate([tok0[:, None], d_toks_a], axis=1)
        t_logits, updates = model.apply(
            {"params": params, "cache": cache}, window,
            train=False, decode=True, mutable=["cache"], positions=pos,
            **extra,
        )
        cache = updates["cache"]
        emit, n_emit = speculative_accept(
            t_logits, d_logits_a, d_toks_a, n_spec, keys,
            temperature=temperature, top_k=top_k, top_p=top_p,
        )

        # in-graph first-EOS cut (the window analog of eos_retire): keep
        # through the first stop token, flag the row for retirement
        cols = jnp.arange(K + 1)[None, :]
        is_eos = (emit == eos[:, None]) & (eos >= 0)[:, None] & (
            cols < n_emit[:, None]
        )
        first_eos = jnp.min(
            jnp.where(is_eos, cols, K + 1), axis=1
        ).astype(jnp.int32)
        n_emit = jnp.minimum(n_emit, first_eos + 1)
        eos_hit = first_eos < n_emit
        n_emit = jnp.where(alive, n_emit, 0)
        n_spec = jnp.where(alive, n_spec, 0)
        emit = jnp.where(cols < n_emit[:, None], emit, 0)
        done_out = done | (alive & eos_hit)

        new_pos = pos + n_emit
        last = jnp.take_along_axis(
            emit, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
        )[:, 0]
        next_tok = jnp.where(n_emit > 0, last, tok0)
        return (cache, d_cache, new_pos, next_tok, emit, n_emit, n_spec,
                done_out)

    if paged:
        @partial(jax.jit, donate_argnums=(0, 1))
        def step(cache, d_cache, prev_tok, override_tok, use_override,
                 pos_in, override_pos, block_tables, done, req_ids,
                 temperature, top_k, top_p, eos, limit):
            return body(cache, d_cache, prev_tok, override_tok,
                        use_override, pos_in, override_pos, done, req_ids,
                        temperature, top_k, top_p, eos, limit,
                        block_tables=block_tables)

        return step

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(cache, d_cache, prev_tok, override_tok, use_override, pos_in,
             override_pos, done, req_ids, temperature, top_k, top_p, eos,
             limit):
        return body(cache, d_cache, prev_tok, override_tok, use_override,
                    pos_in, override_pos, done, req_ids, temperature,
                    top_k, top_p, eos, limit)

    return step


def _build_decode_step(model, params, base_key, paged: bool):
    """The one compiled decode step over the full slot batch: feed each
    slot's last token (the PREVIOUS step's on-device sample, or the
    admission override for slots that just joined) at its own position,
    sample each slot's next token with its own params from its own rng
    stream, apply the shared stop rule. Non-live slots arrive with
    ``done=True``: they emit the pad id and their (masked, later
    overwritten) cache writes are dead — in paged mode those ride-along
    writes land in the reserved garbage block their all-zero tables map.

    ``model``/``params``/``base_key`` are CLOSURE constants, not traced
    arguments (one compiled step per engine instance): with params as jit
    arguments, XLA re-canonicalizes the big weight layouts on EVERY call
    — the vocab-sized embedding table alone is read with two access
    patterns — measured 41 vs 17 ms/step at a 4-layer serving geometry
    on CPU. The static ``generate()`` path keeps params traced because
    one call amortizes that over the whole in-graph scan; the engine
    calls once per token and cannot."""

    def body(cache, prev_tok, override_tok, use_override, pos, done,
             req_ids, tok_idx, temperature, top_k, top_p, eos,
             block_tables=None):
        tok = jnp.where(use_override, override_tok, prev_tok)
        extra = {} if block_tables is None else {"block_tables": block_tables}
        logits, updates = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            train=False, decode=True, mutable=["cache"], positions=pos,
            **extra,
        )
        # per-slot rng streams: (request id, token index) keys the draw,
        # so a slot's stream is independent of which other requests share
        # the batch — and survives a preempt/replay cycle unchanged
        keys = jax.vmap(
            lambda r, t: jax.random.fold_in(jax.random.fold_in(base_key, r), t)
        )(req_ids, tok_idx)
        nxt = sample_logits_per_row(
            logits[:, -1], keys, temperature=temperature, top_k=top_k,
            top_p=top_p,
        )
        nxt, done = eos_retire(nxt, done, eos, 0)
        return updates["cache"], nxt, done

    if paged:
        @partial(jax.jit, donate_argnums=(0,))
        def step(cache, prev_tok, override_tok, use_override, pos,
                 block_tables, done, req_ids, tok_idx, temperature, top_k,
                 top_p, eos):
            return body(cache, prev_tok, override_tok, use_override, pos,
                        done, req_ids, tok_idx, temperature, top_k, top_p,
                        eos, block_tables=block_tables)

        return step

    @partial(jax.jit, donate_argnums=(0,))
    def step(cache, prev_tok, override_tok, use_override, pos, done,
             req_ids, tok_idx, temperature, top_k, top_p, eos):
        return body(cache, prev_tok, override_tok, use_override, pos, done,
                    req_ids, tok_idx, temperature, top_k, top_p, eos)

    return step


def engine_param_shardings(model, params, mesh):
    """``NamedSharding`` tree for a serving param tree over ``mesh``, by
    the models' own Megatron ``nn.with_partitioning`` metadata (the same
    annotations the training side shards by —
    ``tpudist.train.state_shardings_from_meta``; unannotated leaves
    replicate). One deviation from the training path: a spec dim whose
    size the mesh axis does NOT divide is dropped to replicated for that
    dim — jax refuses uneven named placements at runtime (tpudist.memory's
    ceil-shard note), and GPT-2's 50257-row vocab table under ``tensor=2``
    is exactly that case. Replicating such a leaf is always correct under
    GSPMD (the matmuls still partition on the other operand); it just
    forgoes that leaf's byte saving.

    ``params`` may be concrete arrays or a ``jax.eval_shape`` tree — only
    leaf SHAPES are read, so the ``mc_serve`` bench leg budgets a
    geometry's per-chip bytes (``tpudist.memory.per_device_bytes``)
    without materializing a weight."""
    import flax.linen as nn
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    specs = nn.get_partition_spec(jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((1, 1), jnp.int32), train=False
        )["params"]
    ))
    # PartitionSpec is a tuple subclass: flatten with is_leaf, and align
    # leaves by flatten order (dict/FrozenDict both flatten key-sorted) so
    # the spec tree's container types need not match the params tree's
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, P)
    )
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if len(spec_leaves) != len(leaves):
        raise ValueError(
            f"params tree has {len(leaves)} leaves but the model's "
            f"partition-spec tree has {len(spec_leaves)} — params do not "
            "belong to this model architecture"
        )

    def fix(spec, leaf):
        dims = []
        for i, ax in enumerate(spec):
            if ax is None:
                dims.append(None)
                continue
            axes = ax if isinstance(ax, tuple) else (ax,)
            world = int(np.prod([mesh.shape[a] for a in axes]))
            dims.append(ax if leaf.shape[i] % world == 0 else None)
        return P(*dims)

    shardings = [
        NamedSharding(mesh, fix(spec, leaf))
        for spec, leaf in zip(spec_leaves, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def _shard_engine_params(model, params, mesh):
    """Place a serving param tree over ``mesh`` per
    :func:`engine_param_shardings`."""
    shardings = engine_param_shardings(model, params, mesh)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


@jax.jit
def _first_token(logits, base_key, request_id, temperature, top_k, top_p):
    """Sample a just-prefilled request's first token (token index 0 of its
    stream) from the prefill logits ``[V]``."""
    key = jax.random.fold_in(
        jax.random.fold_in(base_key, request_id), jnp.int32(0)
    )
    return sample_logits_per_row(
        logits[None], key[None], temperature=temperature[None],
        top_k=top_k[None], top_p=top_p[None],
    )[0]


class ServeEngine:
    """Continuous-batching engine over a model with the decode contract
    (GPT-2 / Llama: ``decode=True`` + ``cache`` collection + per-row
    ``positions``; paged mode additionally threads ``block_tables``).

    ``max_slots`` sizes the decode batch; ``max_active`` (default
    ``max_slots``) caps concurrently-decoding requests below it when
    prefill latency must be bounded; ``max_queue`` bounds admission
    (submit raises :class:`QueueFull` beyond it). ``sink`` (a
    :class:`tpudist.telemetry.TelemetrySink`) streams ``serve`` rows every
    ``stats_every`` ticks; ``on_token`` is the streaming callback, called
    with each :class:`TokenEvent` as it is emitted (one tick after its
    dispatch — the delayed-fetch pipeline).

    Paged-mode knobs (``paged=True``): ``block_size`` (must divide
    ``model.max_seq_len``), ``n_blocks`` (default: the contiguous pool's
    byte budget, ``max_slots × max_seq_len / block_size``, plus the
    garbage block — size it DOWN and raise ``max_slots`` to serve more
    concurrency from the same HBM; docs/SERVING.md "Paged memory" has the
    sizing math), ``prefix_cache`` (content-hash completed prompt-prefix
    blocks for sharing), ``watermark_blocks`` (admission headroom kept
    free for live slots' decode growth; default ``max_slots``).
    ``ttft_slo_s`` arms priority-lane aging (module docstring).

    ``compile_cache=dir`` routes the engine's compiled program inventory
    (the decode step + the per-bucket prefill programs) through
    :class:`tpudist.compile_cache.CompileCache`: construction AOT-compiles
    everything NOW (deploy-time, instead of lazily on first traffic) and
    a REDEPLOYED server with the same weights/geometry loads the
    serialized executables instead of re-tracing — engine cold-start is a
    recorded number (``compile_cache_info``), not a first-request tax.
    The key fingerprints the param VALUES (the programs close over the
    weights, so the serialized payload embeds them): one hashing pass
    over the params at construction, and a new checkpoint can never be
    served by a stale executable. Fail-soft like the training cache — a
    load or first-call failure falls back to the jit path permanently.

    ``retain_results=False`` drops a request's state (its accumulated
    token list) the moment it completes — the long-lived-server mode:
    consume tokens through ``on_token``/``events()``, and host memory
    stays bounded by the ACTIVE requests instead of growing with every
    request ever served. The default keeps results so the drain-style
    ``run()``/``result()`` batch API works."""

    def __init__(self, model, params, *, max_slots: int = 8,
                 max_active: int | None = None, max_queue: int = 256,
                 prefill_chunk: int = 512, seed: int = 0, sink=None,
                 stats_every: int = 50, on_token=None,
                 retain_results: bool = True, clock=time.perf_counter,
                 paged: bool = False, block_size: int = 32,
                 n_blocks: int | None = None, prefix_cache: bool = True,
                 watermark_blocks: int | None = None,
                 ttft_slo_s: float | None = None, compile_cache=None,
                 draft_model=None, draft_params=None, spec_k: int = 4,
                 mesh=None, trace: bool = False,
                 metrics_port: int | None = None,
                 anatomy: bool = False):
        self.mesh = mesh
        self.tensor_world = 1
        self._kv_sharding = None
        self._rep_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from tpudist.mesh import TENSOR_AXIS

            if TENSOR_AXIS in mesh.axis_names:
                self.tensor_world = int(mesh.shape[TENSOR_AXIS])
            self._rep_sharding = NamedSharding(mesh, P())
        if self.tensor_world > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from tpudist.mesh import TENSOR_AXIS

            for name, m in (("model", model), ("draft_model", draft_model)):
                if m is None:
                    continue
                h = int(m.num_heads)
                h_kv = int(getattr(m, "num_kv_heads", None) or h)
                if h % self.tensor_world or h_kv % self.tensor_world:
                    raise ValueError(
                        f"{name}: num_heads={h} / num_kv_heads={h_kv} not "
                        f"divisible by tensor={self.tensor_world} — the KV "
                        "pool shards on the KV-head dim and the paged "
                        "kernel runs per-shard, so BOTH head counts must "
                        "divide the tensor world (GQA: the KV heads are "
                        "the binding constraint); pick a smaller tensor= "
                        "or serve unsharded (mesh=None)"
                    )
            # the models already thread mesh= (context-parallel attention
            # uses the same field); setting it here routes the paged
            # kernel through its shard_map wrap (ops/decode.py)
            if getattr(model, "mesh", None) is not mesh:
                model = model.clone(mesh=mesh)
            params = _shard_engine_params(model, params, mesh)
            if draft_model is not None and draft_params is not None:
                if getattr(draft_model, "mesh", None) is not mesh:
                    draft_model = draft_model.clone(mesh=mesh)
                draft_params = _shard_engine_params(
                    draft_model, draft_params, mesh
                )
            # the KV pools — contiguous [S, H_kv, max_len, dh], paged
            # [n_blocks, H_kv, block_size, dh], and the prefiller's
            # batch-1 rows — all shard on their KV-head dim (dim 1);
            # host-side tables/cursors stay replicated
            self._kv_sharding = NamedSharding(
                mesh, P(None, TENSOR_AXIS, None, None)
            )
        self.model = model
        self.params = params
        self.spec = draft_model is not None
        self.spec_k = int(spec_k)
        if self.spec:
            if draft_params is None:
                raise ValueError("draft_model given without draft_params")
            if self.spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            if getattr(draft_model, "vocab_size", None) != model.vocab_size:
                raise ValueError(
                    f"draft vocab {getattr(draft_model, 'vocab_size', None)} "
                    f"!= target vocab {model.vocab_size} — the acceptance "
                    "ratio compares per-token distributions"
                )
            if draft_model.max_seq_len < model.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {draft_model.max_seq_len} < target's "
                    f"{model.max_seq_len}: the draft pool rides the target's "
                    "cursor lane and must cover the same positions"
                )
        self.draft_model = draft_model
        self.draft_params = draft_params
        self.max_active = max_slots if max_active is None else max_active
        if not 1 <= self.max_active <= max_slots:
            raise ValueError(
                f"max_active {self.max_active} outside [1, {max_slots}]"
            )
        self.max_queue = max_queue
        self.paged = bool(paged)
        if self.paged:
            from tpudist.serve.blocks import PagedSlotPool

            if n_blocks is None:
                # equal-HBM default: the contiguous pool's bytes, paged
                # (+1 for the reserved garbage block). Sizing n_blocks
                # DOWN while raising max_slots is the point of the layout.
                n_blocks = max_slots * (model.max_seq_len // block_size) + 1
            self.pool = PagedSlotPool(
                model, max_slots, n_blocks=n_blocks, block_size=block_size,
                prefix_cache=prefix_cache, kv_sharding=self._kv_sharding,
            )
            self.watermark = (
                max_slots if watermark_blocks is None else int(watermark_blocks)
            )
        else:
            self.pool = SlotPool(
                model, max_slots, kv_sharding=self._kv_sharding
            )
            self.watermark = 0
        self.prefiller = Prefiller(
            model, params, chunk=prefill_chunk,
            kv_sharding=self._kv_sharding,
        )
        self.on_token = on_token
        self.ttft_slo_s = ttft_slo_s
        self.stats = ServeStats(
            slots=max_slots, sink=sink, every=stats_every, clock=clock,
            paged=self.paged, tensor_world=self.tensor_world,
        )
        # per-request lifecycle spans (tpudist.telemetry.trace.ServeTracer,
        # docs/OBSERVABILITY.md §8): every hook reuses the EXACT clock
        # reading the stats call returned, so span-derived TTFT/TPOT are
        # bit-equal to the SLO samples. Off (the default) constructs
        # nothing and the streams stay byte-identical.
        self.tracer = None
        if trace:
            if sink is None:
                raise ValueError("trace=True needs a sink= to write spans to")
            from tpudist.telemetry.trace import ServeTracer

            self.tracer = ServeTracer(sink)
        # live Prometheus endpoint: a scrape-time snapshot() reader — the
        # request hot path pays nothing for it (no pushes, no device work)
        self.exporter = None
        self.metrics_port: int | None = None
        if metrics_port is not None:
            from tpudist.telemetry.trace import MetricsExporter

            self.exporter = MetricsExporter(metrics_port)
            self.exporter.add_collector(self._metrics_snapshot)
            self.metrics_port = self.exporter.port
        self._base_key = jax.random.key(seed)
        if self.spec:
            # second, slot-pinned KV pool for the draft (contiguous even
            # under a paged target — the draft cache is small enough to
            # pay its full rectangle; equal-HBM comparisons account for
            # it via blocks.draft_equivalent_blocks) plus a HEADLESS
            # draft prefiller: the draft's first proposal conditions on
            # the target-sampled first token, so its prompt-end logits
            # are never read
            self._draft_pool = SlotPool(
                draft_model, max_slots, kv_sharding=self._kv_sharding
            )
            self._draft_prefiller = Prefiller(
                draft_model, draft_params, chunk=prefill_chunk, head=False,
                kv_sharding=self._kv_sharding,
            )
            self._decode_fn = _build_spec_step(
                model, params, draft_model, draft_params, self._base_key,
                self.spec_k, self.paged,
            )
        else:
            self._decode_fn = _build_decode_step(
                model, params, self._base_key, self.paged
            )
        self._lanes: dict[int, collections.deque[Request]] = {}
        self._t_submit: dict[int, float] = {}
        self.retain_results = retain_results
        self._results: dict[int, list[int]] = {}
        self._counts: dict[int, int] = {}  # emitted per LIVE request
        self._live_toks: dict[int, list[int]] = {}  # emitted values (replay)
        self._next_id = 0
        self._step = 0
        s = max_slots
        # per-slot request state (host side; shipped as tiny arrays each
        # tick). A slot's row is meaningful iff pool.active[slot].
        self._req = np.full(s, -1, np.int64)
        self._dispatched = np.zeros(s, np.int32)  # tokens dispatched so far
        self._budget = np.zeros(s, np.int32)
        self._temp = np.zeros(s, np.float32)
        self._topk = np.zeros(s, np.int32)
        self._topp = np.ones(s, np.float32)
        self._eos = np.full(s, NO_EOS, np.int32)
        self._slot_prio = np.zeros(s, np.int32)
        self._admit_seq = np.zeros(s, np.int64)  # victim choice: newest first
        self._seq = 0
        self._slot_req: dict[int, Request] = {}  # original request per slot
        # the device-carried token feedback (each step's samples feed the
        # next step without a host round-trip) and the admission overrides
        # that splice a new request's first token into its slot's lane
        self._prev_tok = self._dev(jnp.zeros(s, jnp.int32))
        self._override: dict[int, int] = {}
        # speculative device-carried cursor lane + per-slot emission limit
        # (prompt_len + max_new — the spec step's one clamp covering both
        # sequence end and budget); host positions sync at each fetch
        self._pos_dev = self._dev(jnp.zeros(s, jnp.int32))
        self._limit = np.zeros(s, np.int32)
        self._inflight: _Inflight | None = None
        self._drained_events: list[TokenEvent] = []
        self._decode_aot: dict | None = None
        self.compile_cache_info: dict | None = None
        if compile_cache is not None:
            self._setup_compile_cache(compile_cache, seed=seed)
        # program anatomy at bring-up (docs/OBSERVABILITY.md §9): one
        # `anatomy` row per serving program — XLA's own FLOPs/bytes for a
        # decode tick and a prefill body chunk. The AOT executables above
        # yield cost AND static memory for free; without a compile cache
        # each program pays one lowering (no compile). Off (the default)
        # runs nothing and the streams stay byte-identical.
        self.anatomy_info: list[dict] | None = None
        if anatomy:
            if sink is None:
                raise ValueError("anatomy=True needs a sink= to write to")
            self.anatomy_info = self.program_anatomy()
            for row in self.anatomy_info:
                sink.write("anatomy", **row)

    # -- submission --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               eos_id: int | None = None, priority: int = 0) -> int:
        """Enqueue a request; returns its id. Sampling params are
        PER-REQUEST (``temperature=0`` greedy, ``top_k<=0`` / ``top_p>=1``
        off — :func:`tpudist.generate.sample_logits_per_row` semantics);
        ``priority`` picks the lane (higher = served first, subject to
        ``ttft_slo_s`` aging). Raises :class:`QueueFull` past
        ``max_queue`` and ``ValueError`` when the request cannot fit the
        KV budget (per-slot window, and in paged mode the block pool)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            # reject HERE like every other bad request: deferred to the
            # prefiller it would abort the whole drain mid-flight
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.model.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + {max_new_tokens} new tokens exceeds "
                f"max_seq_len {self.model.max_seq_len} (the per-slot KV size)"
            )
        if self.paged:
            worst = self.pool.blocks_for(prompt.size + max_new_tokens)
            if worst > self.pool.blocks.n_usable:
                raise ValueError(
                    f"request needs up to {worst} blocks but the pool has "
                    f"{self.pool.blocks.n_usable}; raise n_blocks"
                )
        if self.queue_depth >= self.max_queue:
            raise QueueFull(
                f"request queue at max_queue={self.max_queue}; shed load"
            )
        rid = self._next_id
        self._next_id += 1
        req = Request(
            rid, prompt, int(max_new_tokens), float(temperature),
            int(top_k or 0), float(1.0 if top_p is None else top_p),
            NO_EOS if eos_id is None else int(eos_id), int(priority),
        )
        self._lanes.setdefault(req.priority, collections.deque()).append(req)
        self._counts[rid] = 0
        if self.paged:
            self._live_toks[rid] = []
        if self.retain_results:
            self._results[rid] = []
        self._t_submit[rid] = self.stats.on_submit(rid)
        if self.tracer is not None:
            self.tracer.on_submit(rid, self._t_submit[rid], lane=req.priority)
        return rid

    # -- scheduler ---------------------------------------------------------

    @property
    def pending(self) -> bool:
        return (self.queue_depth > 0 or self.pool.n_active > 0
                or self._inflight is not None)

    @property
    def queue_depth(self) -> int:
        return sum(len(d) for d in self._lanes.values())

    def step(self) -> list[TokenEvent]:
        """One scheduler tick: admit, dispatch, process. Returns the
        tokens emitted this tick (also delivered to ``on_token``) — a
        dispatched token surfaces on the NEXT tick's process phase."""
        t_tick0 = None if self.tracer is None else self.stats._clock()
        events = self._admit()
        self._drained_events = []
        new_inflight = self._dispatch()
        # a preemption inside _dispatch force-fetched the in-flight step
        # (its retirements can free blocks) — surface those tokens now
        events.extend(self._drained_events)
        if self._inflight is not None:
            events.extend(self._process(self._inflight))
        self._inflight = new_inflight
        self._step += 1
        self.stats.on_tick(
            self._step, queue_depth=self.queue_depth,
            active=self.pool.n_active,
            pool_occupancy=(
                self.pool.blocks.occupancy if self.paged else None
            ),
        )
        if self.tracer is not None:
            self.tracer.on_tick(
                self._step, t_tick0, self.stats._clock(),
                active=self.pool.n_active, queue_depth=self.queue_depth,
                emitted=len(events),
            )
        if self.on_token is not None:
            for e in events:
                self.on_token(e)
        return events

    def run(self) -> dict[int, list[int]]:
        """Drain queue and slots to completion; returns
        ``{request_id: tokens}`` and writes the ``serve_summary`` row.
        (With ``retain_results=False`` the dict only holds still-live
        requests — i.e. nothing after a full drain; stream via
        ``on_token``/``events()`` in that mode.)"""
        while self.pending:
            self.step()
        self.stats.write_summary(self._step)
        return {r: list(t) for r, t in self._results.items()}

    def events(self):
        """Generator of :class:`TokenEvent` until the engine drains —
        the streaming consumption shape (``for ev in engine.events():``)."""
        while self.pending:
            yield from self.step()
        self.stats.write_summary(self._step)

    def result(self, request_id: int) -> list[int]:
        """Tokens accumulated for a request (``KeyError`` once a completed
        request's state was dropped under ``retain_results=False``)."""
        return list(self._results[request_id])

    def reset_stats(self) -> None:
        """Fresh SLO accounting on a warm engine (same sink/cadence/clock)
        — benches warm the compiled programs with a throwaway workload on
        ONE engine instance (the decode step and prefill programs are
        per-instance closures), then reset before the timed run."""
        s = self.stats
        self.stats = ServeStats(
            slots=self.pool.max_slots, sink=s.sink, every=s.every,
            clock=s._clock, paged=self.paged,
            tensor_world=self.tensor_world,
        )

    def close(self) -> None:
        """Release the engine's host-side services (today: the live
        metrics endpoint's server thread). Safe to call twice; a no-op
        when ``metrics_port`` was never given."""
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None

    # -- internals ---------------------------------------------------------

    def _occ(self) -> float | None:
        """Block-pool occupancy at a scheduler transition (None on a
        contiguous engine) — the pressure tag span rows carry."""
        return self.pool.blocks.occupancy if self.paged else None

    def _metrics_snapshot(self) -> dict:
        """The live-metrics collector: host-side SLO state at scrape time
        (``ServeStats.snapshot()`` plus the queue/slot live readings).
        Runs on the exporter's HTTP thread — reads only host scalars, so
        a scrape can never block or perturb the serving loop."""
        snap = {f"serve_{k}": v for k, v in self.stats.snapshot().items()}
        snap["serve_queue_depth"] = self.queue_depth
        snap["serve_active"] = self.pool.n_active
        snap["serve_preemptions_total"] = snap.pop("serve_preemptions", 0)
        return snap

    def _dev(self, x):
        """Host lane → device argument. On a mesh engine the lane commits
        to the REPLICATED placement: the compiled step's weights and KV
        live mesh-sharded, and the AOT executables validate argument
        shardings, so an uncommitted single-device array would either
        force a reshard per tick or fail warm-start validation outright.
        Off-mesh this is a plain ``jnp.asarray`` (the call sites keep
        their ``.copy()`` snapshots — the XLA:CPU aliasing discipline is
        unchanged)."""
        if self._rep_sharding is None:
            return jnp.asarray(x)
        return jax.device_put(jnp.asarray(x), self._rep_sharding)

    def _emit(self, rid: int, token: int, done: bool) -> TokenEvent:
        ev = TokenEvent(rid, token, self._counts[rid], done)
        self._counts[rid] += 1
        if self.paged:
            # replay record for preempt-to-queue — paged-only machinery;
            # a contiguous streaming server should not pay double host
            # memory per live token for a list nothing ever reads
            self._live_toks[rid].append(token)
        if self.retain_results:
            self._results[rid].append(token)
        return ev

    def _finish(self, rid: int) -> None:
        """Request complete: close out its SLO accounting and (in
        streaming mode) drop its per-request state — host memory stays
        bounded by live requests, not requests ever served."""
        n_tokens = self._counts.pop(rid)
        t_done = self.stats.on_done(rid, n_tokens)
        if self.tracer is not None:
            self.tracer.on_done(
                rid, t_done, n_tokens, pool_occupancy=self._occ()
            )
        self._live_toks.pop(rid, None)
        self._t_submit.pop(rid, None)
        if not self.retain_results:
            self._results.pop(rid, None)

    def _peek_next(self) -> tuple[int, Request] | None:
        """The lane/request admission would serve next: highest-priority
        non-empty lane's head, unless ``ttft_slo_s`` aging promotes an
        overdue lower lane's head (oldest overdue first)."""
        heads = [(lane, dq[0]) for lane, dq in self._lanes.items() if dq]
        if not heads:
            return None
        if self.ttft_slo_s is not None:
            now = self.stats._clock()
            overdue = [
                (lane, r) for lane, r in heads
                if now - self._t_submit.get(r.request_id, now)
                > self.ttft_slo_s
            ]
            if overdue:
                return min(
                    overdue,
                    key=lambda lr: self._t_submit.get(
                        lr[1].request_id, float("inf")
                    ),
                )
        return max(heads, key=lambda lr: lr[0])

    def _admit(self) -> list[TokenEvent]:
        events: list[TokenEvent] = []
        while self.pool.n_free > 0 and self.pool.n_active < self.max_active:
            picked = self._peek_next()
            if picked is None:
                break
            lane, req = picked
            replay = req.replay_tokens
            # the K/V the slot must hold before its first dispatch: the
            # prompt for a fresh request; prompt + all-but-the-last
            # emitted token for a replay (the last one is the next step's
            # INPUT, exactly the steady-state shape)
            if replay is not None:
                kv_tokens = np.concatenate(
                    [req.prompt, np.asarray(replay[:-1], np.int32)]
                )
            else:
                kv_tokens = req.prompt
            hit_blocks: list[int] = []
            lookup_blocks = 0
            if self.paged:
                bs = self.pool.block_size
                worst = self.pool.blocks_for(len(kv_tokens))
                # a fresh request must re-run its LAST prompt token (its
                # logits are the first sample); a replay needs no logits,
                # so its whole K/V may come from the cache
                limit = (len(kv_tokens) if replay is not None
                         else len(kv_tokens) - 1)
                max_hits = (
                    0 if self.pool.prefix is None
                    else max(min(limit, len(kv_tokens)), 0) // bs
                )
                # the watermark is decode headroom against the OTHER live
                # slots' growth; on an idle pool there is nothing to
                # thrash against, and insisting on it would make a
                # request whose need_new + watermark exceeds the pool
                # permanently unadmittable (head-of-line livelock) even
                # though submit() verified it fits
                wm = self.watermark if self.pool.n_active else 0
                if self.pool.free_after_evict() < worst - max_hits + wm:
                    # even a FULL prefix hit cannot fit: stop admitting
                    # before paying the prompt hash + pin work this tick
                    # (FIFO head-of-line — the request stays queued,
                    # decode drains the pool; a blocked tick costs one
                    # evictability scan, not O(prompt) hashing)
                    break
                if self.pool.prefix is not None:
                    hit_blocks = self.pool.prefix.lookup(kv_tokens, limit)
                    lookup_blocks = max_hits
                    # PIN the hits until insert takes its own refs: the
                    # eviction below frees cache-only (refcount-1) leaves,
                    # and the matched blocks are exactly that until the
                    # slot maps them — without the pin a budget eviction
                    # could free the blocks this admission is about to use
                    for blk in hit_blocks:
                        self.pool.blocks.incref(int(blk))
                budget = worst - len(hit_blocks) + wm
                if self.pool.free_after_evict() < budget:
                    # the actual hits fell short of the optimistic
                    # pre-check (and the pins just excluded them from the
                    # evictable count): release and stay queued
                    for blk in hit_blocks:
                        self.pool.blocks.decref(int(blk))
                    break
                if self.pool.blocks.n_free < budget:
                    self.pool.evict_prefix(budget - self.pool.blocks.n_free)
            self._lanes[lane].popleft()
            # admission commit: the queue-wait sample closes here (the
            # prefill dispatch follows immediately); a replay re-admission
            # doesn't re-sample, it closes its preempted span instead
            t_adm = self.stats.on_prefill_start(req.request_id)
            if self.tracer is not None:
                if replay is None:
                    self.tracer.on_admit(
                        req.request_id, t_adm, pool_occupancy=self._occ()
                    )
                else:
                    self.tracer.on_resume(
                        req.request_id, t_adm, pool_occupancy=self._occ()
                    )
            if self.paged and self.pool.prefix is not None:
                # record the prefix outcome only for COMMITTED admissions:
                # a budget-blocked head retries the lookup every tick, and
                # counting those attempts would let one stuck request
                # inflate prefix_hit_rate with phantom lookups
                self.stats.on_prefix(len(hit_blocks), lookup_blocks)
            n_hit_tokens = len(hit_blocks) * (
                self.pool.block_size if self.paged else 0
            )
            if self.paged and hit_blocks:
                if n_hit_tokens < len(kv_tokens):
                    row_cache, last_logits = self.prefiller.resume(
                        self.pool.gather_row(hit_blocks), kv_tokens,
                        n_hit_tokens,
                    )
                else:
                    # full-hit replay: every block is shared and insert
                    # scatters nothing — skip the whole-window gather too
                    row_cache, last_logits = None, None
            else:
                row_cache, last_logits = self.prefiller(kv_tokens)
            if replay is None:
                tok = int(_first_token(
                    last_logits, self._base_key,
                    jnp.asarray(req.request_id, jnp.int32),
                    jnp.asarray(req.temperature, jnp.float32),
                    jnp.asarray(req.top_k, jnp.int32),
                    jnp.asarray(req.top_p, jnp.float32),
                ))
                t_first = self.stats.on_first_token(req.request_id)
                if self.tracer is not None:
                    self.tracer.on_first_token(
                        req.request_id, t_first,
                        prefix_hit=(len(hit_blocks) if self.paged else None),
                        prefix_lookup=(lookup_blocks if self.paged else None),
                    )
                done = tok == req.eos_id or req.max_new_tokens == 1
                events.append(self._emit(req.request_id, tok, done))
                if done:
                    # one-token request (or instant EOS): never occupies
                    # a slot — release the prefix pins insert would have
                    # taken over, or the hit blocks' refcounts stay
                    # elevated forever (unevictable, never freed)
                    for blk in hit_blocks:
                        self.pool.blocks.decref(int(blk))
                    self._finish(req.request_id)
                    continue
                override, n_disp = tok, 1
            else:
                # re-admission after preemption: everything through
                # replay[-1] was already emitted; feed it back and resume
                # the stream at the same cursor/rng position
                override, n_disp = int(replay[-1]), len(replay)
            # the pool write composes with an in-flight decode step: the
            # pool's cache is already the dispatched step's output future,
            # and the scatter simply queues behind it on the device stream
            if self.paged:
                slot = self.pool.insert(
                    row_cache, len(kv_tokens), prompt=kv_tokens,
                    hit_blocks=hit_blocks,
                )
                for blk in hit_blocks:  # insert holds its own refs now
                    self.pool.blocks.decref(int(blk))
            else:
                slot = self.pool.insert(row_cache, len(kv_tokens))
            if self.tracer is not None:
                self.tracer.set_slot(req.request_id, slot)
            if self.spec:
                # the draft's K/V for the same window, pinned to the SAME
                # slot (shared cursor lane). Always a real prefill — the
                # draft has no paged pool or prefix cache to resume from,
                # and headless chunks on a narrow model are cheap
                d_row, _ = self._draft_prefiller(kv_tokens)
                self._draft_pool.write_row(d_row, slot)
                self._limit[slot] = len(req.prompt) + req.max_new_tokens
            self._req[slot] = req.request_id
            self._dispatched[slot] = n_disp
            self._budget[slot] = req.max_new_tokens
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._eos[slot] = req.eos_id
            self._slot_prio[slot] = req.priority
            self._seq += 1
            self._admit_seq[slot] = self._seq
            self._slot_req[slot] = req
            self._override[slot] = override
        return events

    def _choose_victim(self) -> int | None:
        """The slot preemption evicts when the pool runs dry: lowest
        priority first, newest admission within a priority (LIFO — the
        request that has invested least, and whose re-queue at the front
        of its lane costs the least reordering)."""
        cands = np.nonzero(self.pool.active)[0]
        if cands.size == 0:
            return None
        return int(min(
            cands,
            key=lambda s: (self._slot_prio[s], -self._admit_seq[s]),
        ))

    def _preempt(self, victim: int) -> None:
        """Evict a live slot back to its lane's FRONT: its blocks free
        now, its request replays at re-admission (the in-flight step was
        already drained by the caller, so the emitted-token record is
        complete and the stream resumes exactly where it stopped)."""
        rid = int(self._req[victim])
        orig = self._slot_req[victim]
        req = dataclasses.replace(
            orig, replay_tokens=tuple(self._live_toks.get(rid, ()))
        )
        self._lanes.setdefault(req.priority, collections.deque()).appendleft(
            req
        )
        self._override.pop(victim, None)
        self._slot_req.pop(victim, None)
        self.pool.release(victim)
        self._req[victim] = -1
        t_pre = self.stats.on_preempt(rid)
        if self.tracer is not None:
            self.tracer.on_preempt(rid, t_pre, pool_occupancy=self._occ())

    def _ensure_blocks(self, live: np.ndarray) -> np.ndarray:
        """Paged pre-dispatch pass: every live slot whose cursor crossed a
        block boundary must map a fresh block before the step runs. When
        the pool is dry the escalation ladder is: (1) force-fetch the
        in-flight step — its retirements may free blocks (one extra host
        sync, only on the pressure path); (2) evict a cold prefix-cache
        leaf; (3) preempt the newest lowest-priority slot to the queue.
        The loop terminates because every preemption removes a slot from
        ``live`` — in the worst case the requesting slot preempts
        itself."""
        for slot in np.nonzero(live)[0]:
            while live[slot] and not self.pool.ensure_next(slot):
                if self._inflight is not None:
                    self._drained_events.extend(
                        self._process(self._inflight)
                    )
                    self._inflight = None
                    live &= self.pool.active & (
                        self._dispatched < self._budget
                    )
                    continue
                if self.pool.evict_prefix(1):
                    continue
                victim = self._choose_victim()
                if victim is None:  # no active slots left to free
                    live[slot] = False
                    break
                self._preempt(victim)
                live[victim] = False
        return live

    def _ensure_blocks_spec(self, live: np.ndarray) -> np.ndarray:
        """Paged pre-dispatch pass, speculative flavor: one sweep writes
        up to ``spec_k + 1`` positions past a cursor the host only knows
        ONE FETCH LATE (the in-flight sweep may have advanced it another
        ``spec_k + 1``), so each live slot maps a whole window — host
        cursor + ``2·(spec_k+1)`` tokens, capped at the slot's emission
        limit, which ``submit()`` already validated fits the pool — via
        :meth:`tpudist.serve.blocks.PagedSlotPool.ensure_to`. Dry-pool
        escalation is the same ladder as the plain path: force-fetch the
        in-flight sweep (retirements free blocks AND tighten the horizon,
        since the host cursor catches up), evict a cold prefix leaf,
        preempt the newest lowest-priority slot."""
        horizon = 2 * (self.spec_k + 1)
        for slot in np.nonzero(live)[0]:
            while live[slot]:
                need = min(
                    int(self.pool.positions[slot]) + horizon,
                    int(self._limit[slot]),
                )
                if self.pool.ensure_to(slot, need):
                    break
                if self._inflight is not None:
                    self._drained_events.extend(
                        self._process(self._inflight)
                    )
                    self._inflight = None
                    live &= self.pool.active
                    continue
                if self.pool.evict_prefix(1):
                    continue
                victim = self._choose_victim()
                if victim is None:  # no active slots left to free
                    live[slot] = False
                    break
                self._preempt(victim)
                live[victim] = False
        return live

    def _dispatch_spec(self) -> _SpecInflight | None:
        """The speculative analog of :meth:`_dispatch`: live rows are
        simply the occupied slots — budget gating moved ON DEVICE (the
        step's ``limit`` clamp emits zero once a row is exhausted, so an
        over-dispatched zombie sweep is dead weight, and the host retires
        the slot at the fetch that consumes its budget). The cursor lane
        is device-carried (``_pos_dev`` chains through the step outputs);
        admission overrides splice a fresh slot's cursor in exactly like
        its first token."""
        live = self.pool.active.copy()
        if self.paged and live.any():
            live = self._ensure_blocks_spec(live)
        if not live.any():
            return None
        s = self.pool.max_slots
        override_tok = np.zeros(s, np.int32)
        override_pos = np.zeros(s, np.int32)
        use_override = np.zeros(s, bool)
        for slot, tok in self._override.items():
            override_tok[slot] = tok
            override_pos[slot] = self.pool.positions[slot]
            use_override[slot] = True
        self._override.clear()
        # same snapshot discipline as _dispatch: every host array copies
        # before becoming a device argument (XLA:CPU zero-copy aliasing)
        args = [
            self.pool.cache, self._draft_pool.cache, self._prev_tok,
            self._dev(override_tok), self._dev(use_override),
            self._pos_dev, self._dev(override_pos),
        ]
        if self.paged:
            args.append(self._dev(self.pool.tables.copy()))
        args += [
            self._dev(~live), self._dev(self._req.astype(np.int32)),
            self._dev(self._temp.copy()), self._dev(self._topk.copy()),
            self._dev(self._topp.copy()), self._dev(self._eos.copy()),
            self._dev(self._limit.copy()),
        ]
        (self.pool.cache, self._draft_pool.cache, new_pos, next_tok, emit,
         n_emit, n_spec, done_dev) = self._call_decode(*args)
        self._pos_dev = new_pos
        self._prev_tok = next_tok
        return _SpecInflight(
            emit, n_emit, n_spec, new_pos, done_dev, live, self._req.copy()
        )

    def _process_spec(self, prev: _SpecInflight) -> list[TokenEvent]:
        """Fetch a speculative sweep (the one host sync per tick): stream
        each owned row's emitted window IN ORDER (every token its own
        :class:`TokenEvent` — the consumer-visible contract is unchanged,
        there are just up to ``spec_k + 1`` per slot per tick), sync the
        host cursor from the device's, and retire on the in-graph EOS
        flag or the budget landing exactly on the window's last token
        (the device clamp guarantees no mid-window overshoot)."""
        emit = np.asarray(prev.emit)
        n_emit = np.asarray(prev.n_emit)
        n_spec = np.asarray(prev.n_spec)
        pos = np.asarray(prev.pos)
        done = np.asarray(prev.done)
        events: list[TokenEvent] = []
        drafted = accepted = 0
        for slot in np.nonzero(prev.live)[0]:
            rid = int(prev.rid[slot])
            if self._req[slot] != rid or rid not in self._counts:
                continue  # zombie sweep: ownership guard, as in _process
            self.pool.positions[slot] = int(pos[slot])
            m = int(n_emit[slot])
            if m == 0:
                continue
            # accepted = emitted minus the one correction/bonus token the
            # target pass supplies anyway; drafted = ELIGIBLE proposals
            # (the device's n_spec clamp), so a budget-clamped window
            # doesn't read as rejection
            drafted += int(n_spec[slot])
            accepted += m - 1
            if self.tracer is not None:
                self.tracer.on_spec(rid, int(n_spec[slot]), m - 1)
            for j in range(m):
                n = self._counts[rid]
                finished = (
                    (bool(done[slot]) and j == m - 1)
                    or n + 1 >= int(self._budget[slot])
                )
                events.append(self._emit(rid, int(emit[slot, j]), finished))
                if finished:
                    self._finish(rid)
                    self.pool.release(slot)
                    self._req[slot] = -1
                    self._slot_req.pop(slot, None)
                    break
        self.stats.on_decode_step(int(prev.live.sum()), len(events))
        self.stats.on_spec(drafted, accepted)
        return events

    def _dispatch(self) -> _Inflight | _SpecInflight | None:
        """Dispatch the next decode step without waiting on the previous
        one's results. Live rows = occupied slots with budget left; a slot
        whose stop token sits in the unfetched step rides one extra masked
        zombie row (discarded at process time by the ownership guard)."""
        if self.spec:
            return self._dispatch_spec()
        live = self.pool.active & (self._dispatched < self._budget)
        if self.paged and live.any():
            live = self._ensure_blocks(live)
        if not live.any():
            return None
        override_tok = np.zeros(self.pool.max_slots, np.int32)
        use_override = np.zeros(self.pool.max_slots, bool)
        for slot, tok in self._override.items():
            override_tok[slot] = tok
            use_override[slot] = True
        self._override.clear()
        # every host array is SNAPSHOTTED (.copy()/astype) before it
        # becomes a device argument: XLA:CPU's device_put zero-copy
        # ALIASES aligned numpy buffers, and under async dispatch the
        # step may read them only after this tick's host-side bookkeeping
        # (advance/admission) has already mutated them in place —
        # reproduced on jax 0.4.x as per-process-deterministic corrupted
        # token streams, pinned by test_serve_paged's aliasing regression
        # test. The copies are tiny ([S]-scalar lanes and the [S, MB]
        # table) next to the decode step itself.
        args = [
            self.pool.cache, self._prev_tok, self._dev(override_tok),
            self._dev(use_override), self._dev(self.pool.positions.copy()),
        ]
        if self.paged:
            args.append(self._dev(self.pool.tables.copy()))
        args += [
            self._dev(~live), self._dev(self._req.astype(np.int32)),
            self._dev(self._dispatched.copy()), self._dev(self._temp.copy()),
            self._dev(self._topk.copy()), self._dev(self._topp.copy()),
            self._dev(self._eos.copy()),
        ]
        self.pool.cache, tok_dev, done_dev = self._call_decode(*args)
        self._prev_tok = tok_dev
        for slot in np.nonzero(live)[0]:
            self.pool.advance(slot)
            self._dispatched[slot] += 1
        return _Inflight(tok_dev, done_dev, live, self._req.copy())

    def _process(self, prev) -> list[TokenEvent]:
        """Fetch a dispatched step's tokens (the ONE host sync per tick,
        one step behind the device) and stream/retire."""
        if isinstance(prev, _SpecInflight):
            return self._process_spec(prev)
        tok = np.asarray(prev.tok)
        done = np.asarray(prev.done)
        events: list[TokenEvent] = []
        for slot in np.nonzero(prev.live)[0]:
            rid = int(prev.rid[slot])
            # ownership guard: a zombie row (its request retired between
            # this step's dispatch and its fetch) is discarded — the slot
            # may already belong to a newly admitted request. The slot
            # check alone suffices (a completing request's slot resets to
            # -1 in the same _process pass, before the one step that can
            # still reference it is fetched); the _counts membership is a
            # second, O(live)-memory line of defense
            if self._req[slot] != rid or rid not in self._counts:
                continue
            n = self._counts[rid]
            finished = bool(done[slot]) or n + 1 >= int(self._budget[slot])
            events.append(self._emit(rid, int(tok[slot]), finished))
            if finished:
                self._finish(rid)
                self.pool.release(slot)
                self._req[slot] = -1
                self._slot_req.pop(slot, None)
        self.stats.on_decode_step(int(prev.live.sum()), len(events))
        return events

    # -- deploy-time compile cache (warm start) ----------------------------

    def _call_decode(self, *args):
        """Dispatch through the cached AOT executable when one loaded;
        any failure (geometry the fingerprint couldn't see) permanently
        falls back to the jit path — the cache may cost a trace, never a
        wrong step. The fallback boundary is PRE-dispatch: an input
        mismatch raises at the executable's argument validation, before
        donation invalidates the cache buffers, so re-invoking the jit
        path on the same args is safe. A fault AFTER dispatch (device
        OOM mid-step) leaves the donated cache deleted and the retry
        dies on it — correct, since the cache contents are undefined at
        that point and no fallback could serve them."""
        if self._decode_aot is not None and self._decode_aot["exe"] is not None:
            try:
                return self._decode_aot["exe"](*args)
            except Exception:
                self._decode_aot["exe"] = None
        return self._decode_fn(*args)

    def _fingerprint(self, seed: int) -> str:
        """Content hash of everything the engine's executables bake in:
        model identity/config, engine geometry, jax versions, backend —
        and the PARAM VALUES, because the programs close over the weights
        (the serialized payload embeds them; a redeployed server with a
        new checkpoint must miss, or it would silently serve the old
        weights). One hashing pass over the params at construction — the
        deploy-time cost of the warm start."""
        from tpudist.compile_cache import SCHEMA, model_identity

        h = hashlib.sha256()
        cfg = {
            "schema": SCHEMA,
            "model": model_identity(self.model),
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "max_slots": self.pool.max_slots,
            "max_seq_len": self.model.max_seq_len,
            "paged": self.paged,
            "block_size": getattr(self.pool, "block_size", 0),
            "n_blocks": (
                self.pool.blocks.n_blocks if self.paged else 0
            ),
            "chunk": self.prefiller.chunk,
            "minimum": self.prefiller.minimum,
            "seed": seed,
            # speculative geometry: the step program bakes in K and the
            # draft architecture, and closes over the draft weights too
            "spec_k": self.spec_k if self.spec else 0,
            "draft": model_identity(self.draft_model) if self.spec else None,
            # mesh topology: the executables bake in the device assignment
            # and every argument's sharding — a cache dir shared across
            # topologies must miss cheaply here, not fail (or worse,
            # validate) a wrong-geometry executable at first call
            "mesh": None if self.mesh is None else {
                "axes": [str(a) for a in self.mesh.axis_names],
                "shape": [
                    int(self.mesh.shape[a]) for a in self.mesh.axis_names
                ],
            },
            "tensor_world": self.tensor_world,
        }
        h.update(json.dumps(cfg, sort_keys=True).encode())
        trees = [("", self.params)]
        if self.spec:
            trees.append(("draft/", self.draft_params))
        for prefix, tree in trees:
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            for path, leaf in flat:
                arr = np.asarray(jax.device_get(leaf))
                h.update((prefix + jax.tree_util.keystr(path)).encode())
                h.update(str(arr.dtype).encode())
                h.update(arr.tobytes())
        return h.hexdigest()[:24]

    def _sds(self, x):
        """Shape/dtype (and, on a mesh engine, COMMITTED sharding) struct
        of one example argument: the lowered executable must see each
        argument's real placement (replicated lanes, KV-sharded pools) or
        first-call validation rejects the real args. Shared by the AOT
        compile-cache lowers and program introspection."""
        sh = getattr(x, "sharding", None)
        if self.mesh is not None and sh is not None:
            return jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=sh)
        return jax.ShapeDtypeStruct(np.shape(x), x.dtype)

    def _i32(self, *shape):
        return self._dev(jnp.zeros(shape, jnp.int32))

    def _decode_example_args(self) -> list:
        """Example argument list of ONE decode tick — exactly the shapes,
        dtypes, and committed placements `_decode_fn` is fed every step
        (mesh engine: each lane commits replicated via the same _dev
        discipline the per-tick dispatch uses). One definition feeds both
        the AOT compile-cache lower and :meth:`program_anatomy`, so the
        cached program and the introspected one can never drift."""
        s = self.pool.max_slots
        i32 = self._i32
        zeros_b = lambda: self._dev(jnp.zeros(s, bool))
        zeros_f = lambda: self._dev(jnp.zeros(s, jnp.float32))
        ones_f = lambda: self._dev(jnp.ones(s, jnp.float32))
        if self.spec:
            args = [
                self.pool.cache, self._draft_pool.cache, i32(s), i32(s),
                zeros_b(), i32(s), i32(s),
            ]
            if self.paged:
                args.append(i32(s, self.pool.max_blocks))
            args += [
                zeros_b(), i32(s), zeros_f(),
                i32(s), ones_f(), i32(s), i32(s),
            ]
            return args
        args = [self.pool.cache, i32(s), i32(s), zeros_b(), i32(s)]
        if self.paged:
            args.append(i32(s, self.pool.max_blocks))
        args += [
            zeros_b(), i32(s), i32(s), zeros_f(),
            i32(s), ones_f(), i32(s),
        ]
        return args

    def _prefill_row_example(self, prefiller):
        """The batch-1 KV-row example tree a prefill program is lowered
        against: ``_cache_shapes`` is already a ShapeDtypeStruct tree (no
        device allocation just to describe shapes); on a mesh engine it is
        re-structed with the KV sharding the prefiller's fresh caches
        actually carry."""
        row_ex = prefiller._cache_shapes
        if self._kv_sharding is not None:
            row_ex = jax.tree_util.tree_map(
                lambda t: jax.ShapeDtypeStruct(
                    t.shape, t.dtype,
                    sharding=(
                        self._kv_sharding if len(t.shape) == 4
                        else self._rep_sharding
                    ),
                ),
                row_ex,
            )
        return row_ex

    def program_anatomy(self) -> list[dict]:
        """XLA's own account of the serving programs (docs/OBSERVABILITY
        .md §9): one info dict per program — the decode tick and a prefill
        body chunk — with XLA-counted FLOPs/bytes and, when the program
        came through the AOT compile cache, the static HBM breakdown too
        (a merely-lowered program yields costs only; lowering is cheap, no
        compile). Per-program fail-soft: an un-analyzable config
        contributes nothing rather than failing engine bring-up."""
        from tpudist.telemetry.anatomy import analyze_program

        rows: list[dict] = []
        try:
            exe = (self._decode_aot or {}).get("exe")
            lowered = None
            if exe is None:
                lowered = self._decode_fn.lower(*jax.tree_util.tree_map(
                    self._sds, self._decode_example_args()
                ))
            info = analyze_program(
                "serve_spec_decode" if self.spec else "serve_decode",
                compiled=exe, lowered=lowered,
            )
            if info is not None:
                info["slots"] = int(self.pool.max_slots)
                info["paged"] = self.paged
                rows.append(info)
        except Exception:
            pass
        try:
            chunk = self.prefiller.chunk
            exe = self.prefiller._aot.get(("body", chunk))
            lowered = None
            if exe is None:
                example = (self._prefill_row_example(self.prefiller),
                           self._i32(1, chunk))
                lowered = self.prefiller._chunk_body.lower(
                    *jax.tree_util.tree_map(self._sds, example)
                )
            info = analyze_program("serve_prefill_body", compiled=exe,
                                   lowered=lowered)
            if info is not None:
                info["chunk"] = int(chunk)
                rows.append(info)
        except Exception:
            pass
        return rows

    def _setup_compile_cache(self, directory, *, seed: int) -> None:
        """Deploy-time program inventory through the AOT executable cache:
        the decode step plus every power-of-two prefill bucket's body/
        final program, compiled NOW (cold) or deserialized (warm). Rare
        shapes outside the inventory (a capped non-power-of-two final
        bucket near the cache end) simply take the jit path."""
        from tpudist.compile_cache import CompileCache

        t0 = time.perf_counter()
        cc = CompileCache(directory)
        fp = self._fingerprint(seed)
        info: dict = {"hits": 0, "misses": 0, "programs": {}, "bytes": 0}

        def fetch(name, jitted, *example):
            key = f"{fp}-{name}"
            exe = cc.load(key)
            if exe is not None:
                info["hits"] += 1
                info["programs"][name] = "hit"
                return exe
            try:
                exe = jitted.lower(
                    *jax.tree_util.tree_map(self._sds, example)
                ).compile()
                nbytes = cc.store(key, exe, {"program": name})
                if nbytes and cc.load(key) is None:
                    # XLA:CPU wart (same family as tests/conftest.py's
                    # persistent-cache notes): an executable whose compile
                    # was satisfied from JAX's OWN persistent compilation
                    # cache serializes to a payload missing its fused-
                    # kernel symbols — it can never deserialize. Drop the
                    # dead entry so warm starts don't re-fail on it; the
                    # live executable still serves this process.
                    cc.path_for(key).unlink(missing_ok=True)
                    cc.path_for(key).with_suffix(".json").unlink(
                        missing_ok=True
                    )
                    info["programs"][name] = "unserializable"
                else:
                    info["bytes"] += nbytes
                    info["misses"] += 1
                    info["programs"][name] = "miss"
                return exe
            except Exception as exc:  # exotic config: jit path serves it
                info["programs"][name] = f"error:{type(exc).__name__}"
                return None

        decode_args = self._decode_example_args()
        self._decode_aot = {"exe": fetch(
            "spec" if self.spec else "decode", self._decode_fn, *decode_args
        )}
        # _cache_shapes is already a ShapeDtypeStruct tree and _sds() maps
        # it through unchanged — no device-side batch-1 cache allocation
        # just to describe shapes (mesh engine: re-struct with the KV
        # sharding the prefiller's fresh caches actually carry)
        row_ex = self._prefill_row_example(self.prefiller)
        buckets, b = [], self.prefiller.minimum
        while b <= self.prefiller.chunk:
            buckets.append(b)
            b *= 2
        aot = {}
        for b in buckets:
            exe = fetch(f"pf{b}", self.prefiller._chunk_final,
                        row_ex, self._i32(1, b))
            if exe is not None:
                aot[("final", b)] = exe
        # body chunks are always exactly `chunk` long (only the final
        # chunk is partial), so one body program covers them
        exe = fetch(f"pb{self.prefiller.chunk}", self.prefiller._chunk_body,
                    row_ex, self._i32(1, self.prefiller.chunk))
        if exe is not None:
            aot[("body", self.prefiller.chunk)] = exe
        self.prefiller.attach_aot(aot)
        if self.spec:
            # the HEADLESS draft prefiller runs every chunk — including
            # the bucketed final one — through its body program, so it
            # needs a body executable at every bucket, not just `chunk`
            dpf = self._draft_prefiller
            d_row_ex = self._prefill_row_example(dpf)
            d_aot = {}
            for b in {*buckets, dpf.chunk}:
                exe = fetch(f"dpb{b}", dpf._chunk_body, d_row_ex,
                            self._i32(1, b))
                if exe is not None:
                    d_aot[("body", b)] = exe
            dpf.attach_aot(d_aot)
        info["build_s"] = round(time.perf_counter() - t0, 6)
        self.compile_cache_info = info
