"""Continuous-batching inference engine.

The scheduler over the slot pool: a FIFO request queue with admission
control, per-slot sampling/stop params, per-step streaming token delivery,
and latency-SLO telemetry. One scheduler **tick** (:meth:`ServeEngine.step`)
is:

1. **admit** — while a slot is free, the active count is under
   ``max_active``, and the queue is non-empty: pop the oldest request,
   run its bucketed chunked prefill (``tpudist.serve.prefill``), sample
   its FIRST token from the prefill logits (that emission is the
   request's TTFT), and scatter its prefix K/V into a free slot;
2. **dispatch** — ONE compiled masked decode step over the FULL slot batch
   (``positions=`` per-slot cursors, non-live slots ride along masked):
   write each fed token's K/V at its slot's cursor, sample each slot's
   next token with its own sampling params and rng stream
   (:func:`tpudist.generate.sample_logits_per_row`), apply the shared
   stop rule (:func:`tpudist.generate.eos_retire`);
3. **process** — fetch the PREVIOUS tick's dispatched step, stream its
   tokens, and retire finished slots (stop token or budget), making room
   for the next admission — requests join and leave between decode steps
   with ZERO recompiles.

The decode loop is **one-step-delayed**, the same pipeline idiom as
``fit()``'s metric fetch (docs/PERF.md §3): step ``k`` is dispatched
BEFORE step ``k-1``'s tokens are fetched, and each step's sampled tokens
feed the next step ON DEVICE (a carried ``[S]`` token array, overridden
per-slot at admission), so the device never idles waiting for a host
round-trip. On this repo's remote attach a synchronous per-step fetch
costs ~100 ms RTT — more than ten 124M decode steps; the delayed fetch
hides it entirely. The price is bounded and paid only on retirement: a
slot whose stop token is discovered one tick late burns at most ONE
masked zombie row-step (its write lands at its own cursor and the slot
is released before anything reads it), and the ``(request_id, slot
ownership)`` snapshot guard discards the zombie's output.

Why this wins over static batching: a static batch must assemble before
prefill (queue wait on the LAST arrival) and every row decodes until the
LONGEST request finishes (retired rows burn full decode steps). The
engine's decode batch stays full under mixed-length Poisson arrivals —
the ``serve`` bench leg measures the tokens/s gap and the TTFT collapse.

The decode step costs the same whether 1 or ``max_slots`` slots are
live (the batch shape is fixed); ``max_slots`` trades HBM (the pool is
``max_slots × depth × 2 × H × max_seq_len × dh``) against utilization.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpudist.generate import eos_retire, sample_logits_per_row
from tpudist.serve.prefill import Prefiller
from tpudist.serve.slots import SlotPool
from tpudist.serve.stats import ServeStats

NO_EOS = -1  # token ids are non-negative, so -1 never matches


class QueueFull(RuntimeError):
    """Admission control: the request queue is at ``max_queue``. Callers
    shed load (or retry later) — unbounded queues just move the failure
    to an OOM or an SLO blowout."""


@dataclasses.dataclass(frozen=True)
class Request:
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = NO_EOS


@dataclasses.dataclass(frozen=True)
class TokenEvent:
    """One streamed token: ``index`` is its 0-based position in the
    request's generated sequence; ``done`` marks the request's last
    token (EOS or budget)."""

    request_id: int
    token: int
    index: int
    done: bool


@dataclasses.dataclass
class _Inflight:
    """A dispatched-but-unfetched decode step: the device token/stop
    futures plus the host-side snapshot of which slots were live and who
    owned them at dispatch time (ownership can change before the fetch —
    the processing guard keys on it)."""

    tok: jax.Array
    done: jax.Array
    live: np.ndarray   # [S] bool — rows fed for real at this dispatch
    rid: np.ndarray    # [S] int64 — owner snapshot


def _build_decode_step(model, params, base_key):
    """The one compiled decode step over the full slot batch: feed each
    slot's last token (the PREVIOUS step's on-device sample, or the
    admission override for slots that just joined) at its own position,
    sample each slot's next token with its own params from its own rng
    stream, apply the shared stop rule. Non-live slots arrive with
    ``done=True``: they emit the pad id and their (masked, later
    overwritten) cache writes are dead.

    ``model``/``params``/``base_key`` are CLOSURE constants, not traced
    arguments (one compiled step per engine instance): with params as jit
    arguments, XLA re-canonicalizes the big weight layouts on EVERY call
    — the vocab-sized embedding table alone is read with two access
    patterns — measured 41 vs 17 ms/step at a 4-layer serving geometry
    on CPU. The static ``generate()`` path keeps params traced because
    one call amortizes that over the whole in-graph scan; the engine
    calls once per token and cannot."""

    @partial(jax.jit, donate_argnums=(0,))
    def step(cache, prev_tok, override_tok, use_override, pos, done,
             req_ids, tok_idx, temperature, top_k, top_p, eos):
        tok = jnp.where(use_override, override_tok, prev_tok)
        logits, updates = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            train=False, decode=True, mutable=["cache"], positions=pos,
        )
        # per-slot rng streams: (request id, token index) keys the draw,
        # so a slot's stream is independent of which other requests share
        # the batch
        keys = jax.vmap(
            lambda r, t: jax.random.fold_in(jax.random.fold_in(base_key, r), t)
        )(req_ids, tok_idx)
        nxt = sample_logits_per_row(
            logits[:, -1], keys, temperature=temperature, top_k=top_k,
            top_p=top_p,
        )
        nxt, done = eos_retire(nxt, done, eos, 0)
        return updates["cache"], nxt, done

    return step


@jax.jit
def _first_token(logits, base_key, request_id, temperature, top_k, top_p):
    """Sample a just-prefilled request's first token (token index 0 of its
    stream) from the prefill logits ``[V]``."""
    key = jax.random.fold_in(
        jax.random.fold_in(base_key, request_id), jnp.int32(0)
    )
    return sample_logits_per_row(
        logits[None], key[None], temperature=temperature[None],
        top_k=top_k[None], top_p=top_p[None],
    )[0]


class ServeEngine:
    """Continuous-batching engine over a model with the decode contract
    (GPT-2 / Llama: ``decode=True`` + ``cache`` collection + per-row
    ``positions``).

    ``max_slots`` sizes the KV pool (the decode batch); ``max_active``
    (default ``max_slots``) caps concurrently-decoding requests below the
    pool size when prefill latency must be bounded; ``max_queue`` bounds
    admission (submit raises :class:`QueueFull` beyond it). ``sink`` (a
    :class:`tpudist.telemetry.TelemetrySink`) streams ``serve`` rows every
    ``stats_every`` ticks; ``on_token`` is the streaming callback, called
    with each :class:`TokenEvent` as it is emitted (one tick after its
    dispatch — the delayed-fetch pipeline).

    ``retain_results=False`` drops a request's state (its accumulated
    token list) the moment it completes — the long-lived-server mode:
    consume tokens through ``on_token``/``events()``, and host memory
    stays bounded by the ACTIVE requests instead of growing with every
    request ever served. The default keeps results so the drain-style
    ``run()``/``result()`` batch API works."""

    def __init__(self, model, params, *, max_slots: int = 8,
                 max_active: int | None = None, max_queue: int = 256,
                 prefill_chunk: int = 512, seed: int = 0, sink=None,
                 stats_every: int = 50, on_token=None,
                 retain_results: bool = True, clock=time.perf_counter):
        self.model = model
        self.params = params
        self.max_active = max_slots if max_active is None else max_active
        if not 1 <= self.max_active <= max_slots:
            raise ValueError(
                f"max_active {self.max_active} outside [1, {max_slots}]"
            )
        self.max_queue = max_queue
        self.pool = SlotPool(model, max_slots)
        self.prefiller = Prefiller(model, params, chunk=prefill_chunk)
        self.on_token = on_token
        self.stats = ServeStats(
            slots=max_slots, sink=sink, every=stats_every, clock=clock
        )
        self._base_key = jax.random.key(seed)
        self._decode_fn = _build_decode_step(model, params, self._base_key)
        self._queue: collections.deque[Request] = collections.deque()
        self.retain_results = retain_results
        self._results: dict[int, list[int]] = {}
        self._counts: dict[int, int] = {}  # emitted per LIVE request
        self._next_id = 0
        self._step = 0
        s = max_slots
        # per-slot request state (host side; shipped as tiny arrays each
        # tick). A slot's row is meaningful iff pool.active[slot].
        self._req = np.full(s, -1, np.int64)
        self._dispatched = np.zeros(s, np.int32)  # tokens dispatched so far
        self._budget = np.zeros(s, np.int32)
        self._temp = np.zeros(s, np.float32)
        self._topk = np.zeros(s, np.int32)
        self._topp = np.ones(s, np.float32)
        self._eos = np.full(s, NO_EOS, np.int32)
        # the device-carried token feedback (each step's samples feed the
        # next step without a host round-trip) and the admission overrides
        # that splice a new request's first token into its slot's lane
        self._prev_tok = jnp.zeros(s, jnp.int32)
        self._override: dict[int, int] = {}
        self._inflight: _Inflight | None = None

    # -- submission --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               eos_id: int | None = None) -> int:
        """Enqueue a request; returns its id. Sampling params are
        PER-REQUEST (``temperature=0`` greedy, ``top_k<=0`` / ``top_p>=1``
        off — :func:`tpudist.generate.sample_logits_per_row` semantics).
        Raises :class:`QueueFull` past ``max_queue`` and ``ValueError``
        when the request cannot fit the KV pool."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            # reject HERE like every other bad request: deferred to the
            # prefiller it would abort the whole drain mid-flight
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.model.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + {max_new_tokens} new tokens exceeds "
                f"max_seq_len {self.model.max_seq_len} (the per-slot KV size)"
            )
        if len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"request queue at max_queue={self.max_queue}; shed load"
            )
        rid = self._next_id
        self._next_id += 1
        self._queue.append(Request(
            rid, prompt, int(max_new_tokens), float(temperature),
            int(top_k or 0), float(1.0 if top_p is None else top_p),
            NO_EOS if eos_id is None else int(eos_id),
        ))
        self._counts[rid] = 0
        if self.retain_results:
            self._results[rid] = []
        self.stats.on_submit(rid)
        return rid

    # -- scheduler ---------------------------------------------------------

    @property
    def pending(self) -> bool:
        return (bool(self._queue) or self.pool.n_active > 0
                or self._inflight is not None)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def step(self) -> list[TokenEvent]:
        """One scheduler tick: admit, dispatch, process. Returns the
        tokens emitted this tick (also delivered to ``on_token``) — a
        dispatched token surfaces on the NEXT tick's process phase."""
        events = self._admit()
        prev, self._inflight = self._inflight, self._dispatch()
        if prev is not None:
            events.extend(self._process(prev))
        self._step += 1
        self.stats.on_tick(
            self._step, queue_depth=len(self._queue),
            active=self.pool.n_active,
        )
        if self.on_token is not None:
            for e in events:
                self.on_token(e)
        return events

    def run(self) -> dict[int, list[int]]:
        """Drain queue and slots to completion; returns
        ``{request_id: tokens}`` and writes the ``serve_summary`` row.
        (With ``retain_results=False`` the dict only holds still-live
        requests — i.e. nothing after a full drain; stream via
        ``on_token``/``events()`` in that mode.)"""
        while self.pending:
            self.step()
        self.stats.write_summary(self._step)
        return {r: list(t) for r, t in self._results.items()}

    def events(self):
        """Generator of :class:`TokenEvent` until the engine drains —
        the streaming consumption shape (``for ev in engine.events():``)."""
        while self.pending:
            yield from self.step()
        self.stats.write_summary(self._step)

    def result(self, request_id: int) -> list[int]:
        """Tokens accumulated for a request (``KeyError`` once a completed
        request's state was dropped under ``retain_results=False``)."""
        return list(self._results[request_id])

    def reset_stats(self) -> None:
        """Fresh SLO accounting on a warm engine (same sink/cadence/clock)
        — benches warm the compiled programs with a throwaway workload on
        ONE engine instance (the decode step and prefill programs are
        per-instance closures), then reset before the timed run."""
        s = self.stats
        self.stats = ServeStats(
            slots=self.pool.max_slots, sink=s.sink, every=s.every,
            clock=s._clock,
        )

    # -- internals ---------------------------------------------------------

    def _emit(self, rid: int, token: int, done: bool) -> TokenEvent:
        ev = TokenEvent(rid, token, self._counts[rid], done)
        self._counts[rid] += 1
        if self.retain_results:
            self._results[rid].append(token)
        return ev

    def _finish(self, rid: int) -> None:
        """Request complete: close out its SLO accounting and (in
        streaming mode) drop its per-request state — host memory stays
        bounded by live requests, not by every request ever served."""
        self.stats.on_done(rid, self._counts.pop(rid))
        if not self.retain_results:
            self._results.pop(rid, None)

    def _admit(self) -> list[TokenEvent]:
        events: list[TokenEvent] = []
        while (self._queue and self.pool.n_free > 0
               and self.pool.n_active < self.max_active):
            req = self._queue.popleft()
            row_cache, last_logits = self.prefiller(req.prompt)
            tok = int(_first_token(
                last_logits, self._base_key,
                jnp.asarray(req.request_id, jnp.int32),
                jnp.asarray(req.temperature, jnp.float32),
                jnp.asarray(req.top_k, jnp.int32),
                jnp.asarray(req.top_p, jnp.float32),
            ))
            self.stats.on_first_token(req.request_id)
            done = tok == req.eos_id or req.max_new_tokens == 1
            events.append(self._emit(req.request_id, tok, done))
            if done:
                # one-token request (or instant EOS): never occupies a slot
                self._finish(req.request_id)
                continue
            # the pool write composes with an in-flight decode step: the
            # pool's cache is already the dispatched step's output future,
            # and the scatter simply queues behind it on the device stream
            slot = self.pool.insert(row_cache, req.prompt.size)
            self._req[slot] = req.request_id
            self._dispatched[slot] = 1
            self._budget[slot] = req.max_new_tokens
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._eos[slot] = req.eos_id
            self._override[slot] = tok
        return events

    def _dispatch(self) -> _Inflight | None:
        """Dispatch the next decode step without waiting on the previous
        one's results. Live rows = occupied slots with budget left; a slot
        whose stop token sits in the unfetched step rides one extra masked
        zombie row (discarded at process time by the ownership guard)."""
        live = self.pool.active & (self._dispatched < self._budget)
        if not live.any():
            return None
        override_tok = np.zeros(self.pool.max_slots, np.int32)
        use_override = np.zeros(self.pool.max_slots, bool)
        for slot, tok in self._override.items():
            override_tok[slot] = tok
            use_override[slot] = True
        self._override.clear()
        self.pool.cache, tok_dev, done_dev = self._decode_fn(
            self.pool.cache, self._prev_tok, jnp.asarray(override_tok),
            jnp.asarray(use_override), jnp.asarray(self.pool.positions),
            jnp.asarray(~live), jnp.asarray(self._req.astype(np.int32)),
            jnp.asarray(self._dispatched), jnp.asarray(self._temp),
            jnp.asarray(self._topk), jnp.asarray(self._topp),
            jnp.asarray(self._eos),
        )
        self._prev_tok = tok_dev
        for slot in np.nonzero(live)[0]:
            self.pool.advance(slot)
            self._dispatched[slot] += 1
        return _Inflight(tok_dev, done_dev, live, self._req.copy())

    def _process(self, prev: _Inflight) -> list[TokenEvent]:
        """Fetch a dispatched step's tokens (the ONE host sync per tick,
        one step behind the device) and stream/retire."""
        tok = np.asarray(prev.tok)
        done = np.asarray(prev.done)
        events: list[TokenEvent] = []
        for slot in np.nonzero(prev.live)[0]:
            rid = int(prev.rid[slot])
            # ownership guard: a zombie row (its request retired between
            # this step's dispatch and its fetch) is discarded — the slot
            # may already belong to a newly admitted request. The slot
            # check alone suffices (a completing request's slot resets to
            # -1 in the same _process pass, before the one step that can
            # still reference it is fetched); the _counts membership is a
            # second, O(live)-memory line of defense
            if self._req[slot] != rid or rid not in self._counts:
                continue
            n = self._counts[rid]
            finished = bool(done[slot]) or n + 1 >= int(self._budget[slot])
            events.append(self._emit(rid, int(tok[slot]), finished))
            if finished:
                self._finish(rid)
                self.pool.release(slot)
                self._req[slot] = -1
        self.stats.on_decode_step(int(prev.live.sum()), len(events))
        return events
