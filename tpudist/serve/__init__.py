"""Serving subsystem: continuous-batching inference over the decode path.

The training side (PRs 3–8) made the framework fast and resilient; this
package opens the INFERENCE workload the north star names ("serves heavy
traffic from millions of users"). The static ``tpudist.generate`` path —
one jit program, batch-at-once — cannot admit, stream, or retire requests
independently; under real mixed-length arrivals its batch assembly and
longest-row decode dominate latency and waste throughput. The engine here
keeps the decode batch full instead:

- :mod:`tpudist.serve.slots` — slot-pooled KV cache: one pre-allocated
  ``[max_slots, ...]`` cache with per-slot cursors/masks; requests join
  and leave between decode steps with zero recompiles.
- :mod:`tpudist.serve.blocks` — PAGED KV cache (``ServeEngine(paged=True)``):
  a shared refcounted block pool with per-slot block tables and a
  content-hashed prefix cache, so HBM holds Σ(actual lengths) instead of
  ``max_slots × max_seq_len`` and shared system prompts pay prefill once
  (docs/SERVING.md "Paged memory").
- :mod:`tpudist.serve.prefill` — chunked prefill compiled at a small set
  of power-of-two bucket lengths, writing prefix K/V into a free slot
  (resumable past a prefix-cache hit).
- :mod:`tpudist.serve.engine` — the scheduler: priority-laned admission
  control (block-budget accounting + preempt-to-queue in paged mode),
  per-slot sampling/stop params, one compiled masked decode step over the
  full slot batch, per-step streaming delivery, optional deploy-time AOT
  program cache (``compile_cache=``).
- :mod:`tpudist.serve.spec` — speculative decoding
  (``ServeEngine(draft_model=...)``): a cheap draft proposes ``spec_k``
  tokens per slot per tick, the target verifies the whole window in ONE
  bulk pass, and acceptance-rejection sampling preserves the target
  distribution exactly — greedy output stays token-identical to the
  non-speculative engine (docs/SERVING.md §6, docs/PERF.md §7d).
- :mod:`tpudist.serve.stats` — TTFT/TPOT percentiles, queue depth, slot
  utilization, block-pool occupancy / prefix hit rate / preemptions,
  speculative acceptance rate, tokens/s as ``serve`` JSONL rows through
  the telemetry sink (docs/OBSERVABILITY.md; architecture in
  docs/SERVING.md).

Quick start::

    from tpudist.serve import ServeEngine
    engine = ServeEngine(model, params, max_slots=8,
                         on_token=lambda ev: print(ev.request_id, ev.token))
    engine.submit(prompt_ids, max_new_tokens=64, temperature=0.7, top_k=50)
    results = engine.run()   # or: for ev in engine.events(): ...
"""

from tpudist.serve.blocks import BlockPool, PagedSlotPool, PrefixCache
from tpudist.serve.engine import (
    NO_EOS,
    QueueFull,
    Request,
    ServeEngine,
    TokenEvent,
)
from tpudist.serve.prefill import Prefiller
from tpudist.serve.slots import SlotPool, write_slot
from tpudist.serve.spec import (
    cache_bytes,
    early_exit_draft,
    speculative_accept,
)
from tpudist.serve.stats import ServeStats

__all__ = [
    "ServeEngine",
    "Request",
    "TokenEvent",
    "QueueFull",
    "NO_EOS",
    "Prefiller",
    "SlotPool",
    "write_slot",
    "BlockPool",
    "PagedSlotPool",
    "PrefixCache",
    "ServeStats",
    "speculative_accept",
    "early_exit_draft",
    "cache_bytes",
]
