"""Latency-SLO accounting for the serving engine: TTFT, TPOT, queue depth,
slot utilization, decode throughput — streamed as ``serve`` JSONL rows
through the existing :class:`tpudist.telemetry.TelemetrySink` (schema in
docs/OBSERVABILITY.md), with a terminal ``serve_summary`` row.

The two latency SLOs a serving deployment is actually held to:

- **TTFT** (time to first token): submit → the request's first streamed
  token. Under continuous batching this is queue wait + one prefill + one
  sample; under static batching it includes waiting for the whole batch
  to assemble — the number the bench leg's comparison shows collapsing.
- **TPOT** (time per output token): the mean inter-token gap AFTER the
  first token, ``(t_done - t_first) / (n_tokens - 1)`` — the streaming
  cadence a reader experiences.

Percentiles are computed over a sliding window of the most recent
``SLO_WINDOW`` samples (p50/p95 via numpy) — bounded memory and a bounded
per-row percentile pass on a server that lives for millions of requests;
interval quantities (tokens/s, utilization) reset at each ``serve`` row
so the stream shows the live state, not a lifetime average.
"""

from __future__ import annotations

import collections
import time

import numpy as np

# sliding-window size for the TTFT/TPOT percentile samples: recent-enough
# to be an SLO signal, bounded so a long-lived server neither grows the
# sample lists nor pays an ever-larger percentile sort per telemetry row
SLO_WINDOW = 4096


def _pct(xs, q) -> float | None:
    return None if not xs else round(float(np.percentile(list(xs), q)), 6)


def fmt_s(x, scale: float = 1.0, digits: int = 3) -> str:
    """Human-display helper for snapshot fields that are ``None`` until
    the first sample lands (percentiles before any completion, utilization
    before any decode step): ``n/a`` instead of a format TypeError."""
    return "n/a" if x is None else f"{x * scale:.{digits}f}"


class ServeStats:
    """Host-side SLO bookkeeping, driven by the engine: ``on_submit`` /
    ``on_first_token`` / ``on_done`` per request, ``on_decode_step`` per
    compiled step, ``on_tick`` once per scheduler tick (writes the cadence
    row). ``sink=None`` keeps full accounting with no stream (the bench
    and the notebook path read :meth:`snapshot` directly)."""

    def __init__(self, *, slots: int, sink=None, every: int = 50,
                 clock=time.perf_counter, paged: bool = False,
                 tensor_world: int = 1):
        self.slots = slots
        self.sink = sink
        self.every = max(int(every), 0)
        self._clock = clock
        self.paged = paged
        # tensor-parallel world of the engine (1 = single chip): rides
        # every serve row so per-chip readings (pool_occupancy on a
        # sharded block pool is of each chip's 1/T byte slice) carry
        # their denominator — docs/OBSERVABILITY.md §1
        self.tensor_world = int(tensor_world)
        self.t_start = clock()
        self.submitted = 0
        self.completed = 0
        self.tokens = 0
        # paged-pool telemetry (zero/None on a contiguous engine): the
        # engine drives on_preempt / on_prefix; pool occupancy rides each
        # on_tick so the serve row shows the live block budget
        self.preemptions = 0
        self._prefix_hit_blocks = 0
        self._prefix_lookup_blocks = 0
        self._pool_occupancy: float | None = None
        self.ttft: collections.deque[float] = collections.deque(
            maxlen=SLO_WINDOW
        )
        self.tpot: collections.deque[float] = collections.deque(
            maxlen=SLO_WINDOW
        )
        # queue-wait samples (submit → first prefill dispatch): the slice
        # of TTFT spent waiting for admission — invisible inside the TTFT
        # number alone, and the first thing to saturate under overload.
        # Same bounded-deque sampling as ttft/tpot.
        self.queue_wait: collections.deque[float] = collections.deque(
            maxlen=SLO_WINDOW
        )
        self._arrival: dict[int, float] = {}
        self._first: dict[int, float] = {}
        # interval accumulators (reset at each serve row)
        self._win_t0 = self.t_start
        self._win_tokens = 0
        self._win_active = 0
        self._win_steps = 0
        # lifetime slot-occupancy accumulators (never reset — snapshot())
        self._life_active = 0
        self._life_steps = 0
        # speculative-decoding counters (zero on a non-spec engine): the
        # engine drives on_spec once per processed verify sweep; the
        # acceptance rate is the live health reading of the draft — when
        # it sags, speculation is burning draft FLOPs for nothing and the
        # rate on the serve row says so (docs/OBSERVABILITY.md §1)
        self.spec_drafted = 0
        self.spec_accepted = 0
        self._win_spec_drafted = 0
        self._win_spec_accepted = 0

    # -- per-request lifecycle --------------------------------------------

    def on_submit(self, request_id: int) -> float:
        """Returns the arrival timestamp so the engine's TTFT-SLO aging
        runs on the same clock reading TTFT is measured against."""
        self.submitted += 1
        t = self._clock()
        self._arrival[request_id] = t
        return t

    def on_prefill_start(self, request_id: int) -> float:
        """The request's FIRST prefill dispatch: closes the queue-wait
        sample (submit → here). Replay re-admissions after a preemption
        don't re-sample (the arrival entry is gone by then — first-token
        pops it); the preemption gap is accounted separately by the span
        layer. Returns the clock reading so the tracer's queued-phase span
        ends on the exact timestamp the sample was taken at."""
        t = self._clock()
        arrival = self._arrival.get(request_id)
        if arrival is not None and request_id not in self._first:
            self.queue_wait.append(t - arrival)
        return t

    def on_first_token(self, request_id: int) -> float:
        """Returns the first-token timestamp — the tracer's prefill-phase
        span ends on the same reading the TTFT sample was computed from,
        so span-derived TTFT is bit-equal to the SLO sample."""
        t = self._clock()
        self._first[request_id] = t
        self.ttft.append(t - self._arrival.pop(request_id, t))
        # the first token comes from prefill, not a decode step — count it
        # here so throughput covers every emitted token
        self.tokens += 1
        self._win_tokens += 1
        return t

    def on_done(self, request_id: int, n_tokens: int) -> float:
        """Returns the retire timestamp (same contract as
        :meth:`on_first_token`: the tracer reuses the exact reading the
        TPOT sample was computed from)."""
        t = self._clock()
        self.completed += 1
        first = self._first.pop(request_id, None)
        if first is not None and n_tokens > 1:
            self.tpot.append((t - first) / (n_tokens - 1))
        return t

    def on_preempt(self, request_id: int) -> float:
        """A live request was evicted back to the queue (pool ran dry);
        its blocks freed, its prompt+progress replay at re-admission.
        Returns the eviction timestamp for the span layer."""
        self.preemptions += 1
        return self._clock()

    def on_prefix(self, hit_blocks: int, lookup_blocks: int) -> None:
        """One admission's prefix-cache outcome, in BLOCK units (hit rate
        = hit blocks / full prompt blocks looked up — token-weighted, so
        one long shared system prompt counts for what it saves)."""
        self._prefix_hit_blocks += hit_blocks
        self._prefix_lookup_blocks += lookup_blocks

    @property
    def prefix_hit_rate(self) -> float | None:
        if not self._prefix_lookup_blocks:
            return None
        return round(self._prefix_hit_blocks / self._prefix_lookup_blocks, 4)

    # -- per-step drive ----------------------------------------------------

    def on_spec(self, drafted: int, accepted: int) -> None:
        """One verify sweep's outcome across the batch: ``drafted`` =
        eligible draft proposals scored, ``accepted`` = how many survived
        the ratio test (bonus/correction tokens are NOT counted here —
        they'd be emitted by a plain engine too, so counting them would
        flatter the rate)."""
        self.spec_drafted += drafted
        self.spec_accepted += accepted
        self._win_spec_drafted += drafted
        self._win_spec_accepted += accepted

    @staticmethod
    def _rate(accepted: int, drafted: int) -> float | None:
        return None if not drafted else round(accepted / drafted, 4)

    def on_decode_step(self, active: int, emitted: int) -> None:
        self.tokens += emitted
        self._win_tokens += emitted
        self._win_active += active
        self._win_steps += 1
        self._life_active += active
        self._life_steps += 1

    def on_tick(self, step: int, *, queue_depth: int, active: int,
                pool_occupancy: float | None = None) -> None:
        self._pool_occupancy = pool_occupancy
        if self.sink is None or not self.every or step % self.every:
            return
        self.sink.write("serve", step, **self._window_row(queue_depth, active))
        self._win_t0 = self._clock()
        self._win_tokens = self._win_active = self._win_steps = 0
        self._win_spec_drafted = self._win_spec_accepted = 0

    # -- readouts ----------------------------------------------------------

    def _window_row(self, queue_depth: int, active: int) -> dict:
        dt = max(self._clock() - self._win_t0, 1e-9)
        return {
            "queue_depth": queue_depth,
            "active": active,
            "slots": self.slots,
            "tensor_world": self.tensor_world,
            "slot_utilization": (
                round(self._win_active / (self.slots * self._win_steps), 4)
                if self._win_steps else 0.0
            ),
            "tokens_per_sec": round(self._win_tokens / dt, 2),
            "submitted": self.submitted,
            "completed": self.completed,
            "ttft_p50": _pct(self.ttft, 50),
            "ttft_p95": _pct(self.ttft, 95),
            "tpot_p50": _pct(self.tpot, 50),
            "tpot_p95": _pct(self.tpot, 95),
            # paged-pool fields (docs/OBSERVABILITY.md §1): block-pool
            # occupancy (null on a contiguous engine, where
            # slot_utilization above IS the capacity truth — under paged
            # admission it keeps its slot-count meaning but no longer
            # measures free bytes), prefix-cache hit rate (block-
            # weighted, null before any lookup), lifetime preempt count
            "pool_occupancy": (
                None if self._pool_occupancy is None
                else round(self._pool_occupancy, 4)
            ),
            "prefix_hit_rate": self.prefix_hit_rate,
            "preemptions": self.preemptions,
            # speculative fields (docs/OBSERVABILITY.md §1): window-scoped
            # like tokens_per_sec — the LIVE acceptance rate, not a
            # lifetime average that smooths over a draft going stale
            "spec_drafted": self._win_spec_drafted,
            "spec_accepted": self._win_spec_accepted,
            "spec_acceptance_rate": self._rate(
                self._win_spec_accepted, self._win_spec_drafted
            ),
            # queue-wait percentiles (submit → first prefill dispatch),
            # appended after existing fields (the append-only schema
            # discipline): the admission-pressure slice of TTFT
            "queue_p50": _pct(self.queue_wait, 50),
            "queue_p95": _pct(self.queue_wait, 95),
        }

    def snapshot(self) -> dict:
        """Lifetime totals (the bench record's fields)."""
        wall = max(self._clock() - self.t_start, 1e-9)
        return {
            "wall_s": round(wall, 6),
            "tensor_world": self.tensor_world,
            "tokens": self.tokens,
            "tokens_per_sec": round(self.tokens / wall, 2),
            "submitted": self.submitted,
            "completed": self.completed,
            "slot_utilization": (
                round(self._life_active / (self.slots * self._life_steps), 4)
                if self._life_steps else None
            ),
            "ttft_p50": _pct(self.ttft, 50),
            "ttft_p95": _pct(self.ttft, 95),
            "tpot_p50": _pct(self.tpot, 50),
            "tpot_p95": _pct(self.tpot, 95),
            "pool_occupancy": (
                None if self._pool_occupancy is None
                else round(self._pool_occupancy, 4)
            ),
            "prefix_hit_rate": self.prefix_hit_rate,
            "preemptions": self.preemptions,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_acceptance_rate": self._rate(
                self.spec_accepted, self.spec_drafted
            ),
            "queue_p50": _pct(self.queue_wait, 50),
            "queue_p95": _pct(self.queue_wait, 95),
        }

    def write_summary(self, step: int) -> None:
        if self.sink is not None:
            self.sink.write("serve_summary", step, **self.snapshot())
