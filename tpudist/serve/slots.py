"""Slot-pooled KV cache: one pre-allocated ``[max_slots, ...]`` decode
cache whose batch rows are SLOTS that requests occupy and vacate between
decode steps.

The shape discipline that makes continuous batching XLA-native: the pool
is allocated once (``zero_cache`` at ``batch_size=max_slots``), every
decode step runs over the FULL slot batch with per-slot positions and an
active mask (``tpudist.ops.decode.cached_kv(positions=...)``), and
admission/retirement are pure bookkeeping plus one compiled scatter
(:func:`write_slot`) — zero recompiles as requests join and leave. A
request's lifecycle against the pool:

1. **acquire** — a free slot index is taken (FIFO recycle order, so slot
   assignment is deterministic for tests);
2. **insert** — the prefilled batch-1 cache (``tpudist.serve.prefill``) is
   scattered over the slot's rows; the full buffer is copied, so whatever
   a previous occupant left above the new prompt's length is overwritten
   or sits above the cursor where the per-slot mask never admits it;
3. **advance** — each decode step writes the slot's token at its own
   cursor and the engine bumps ``positions[slot]``;
4. **release** — the slot returns to the free list; nothing is zeroed
   (the next insert overwrites, and masked slots are never read).
"""

from __future__ import annotations

import collections
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, donate_argnums=(0,))
def write_slot(pool, row_cache, slot):
    """Scatter a prefilled batch-1 cache into row ``slot`` of the pool
    (donated — the pool updates in place, no second copy of the full
    ``[max_slots, H, max_len, dh]`` buffers). Only the 4-D K/V buffers
    transfer; the scalar cursors (``cache_index``, GPT-2's ``position``)
    keep the pool's values — per-slot lengths live with the engine, not
    in the cache tree."""
    slot = jnp.asarray(slot, jnp.int32)

    def put(dst, src):
        if getattr(src, "ndim", 0) == 4 and dst.ndim == 4:
            return jax.lax.dynamic_update_slice(
                dst, src.astype(dst.dtype), (slot, 0, 0, 0)
            )
        return dst

    return jax.tree_util.tree_map(put, pool, row_cache)


class SlotPool:
    """The pool cache plus host-side slot bookkeeping. ``cache`` is the
    live device pytree the engine's compiled decode step donates through;
    ``positions``/``active`` are the per-slot masks it feeds in."""

    def __init__(self, model, max_slots: int, *, kv_sharding=None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if not hasattr(model, "init_cache"):
            raise ValueError(
                f"{type(model).__name__} has no init_cache hook (the decode "
                "contract tpudist.serve requires); GPT-2 and Llama carry it"
            )
        self.max_slots = max_slots
        self.max_seq_len = model.max_seq_len
        self.cache = model.init_cache(max_slots)
        if kv_sharding is not None:
            # multi-chip engine: the [max_slots, H_kv, max_len, dh] buffers
            # shard on the head dim (P(None, 'tensor', None, None)); scalar
            # cursors commit replicated on the SAME mesh (a leaf left on
            # one device would make the AOT decode step's lowering mix
            # device sets). Committing placements here keeps GSPMD from
            # re-deciding the cache layout per decode step.
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(kv_sharding.mesh, PartitionSpec())
            self.cache = jax.tree_util.tree_map(
                lambda leaf: jax.device_put(
                    leaf,
                    kv_sharding if getattr(leaf, "ndim", 0) == 4 else rep,
                ),
                self.cache,
            )
        self.positions = np.zeros(max_slots, np.int32)
        self.active = np.zeros(max_slots, bool)
        # FIFO recycle order: deterministic slot assignment, and a retired
        # slot goes to the BACK of the line (its stale K/V ages out of HBM
        # cache lines naturally instead of being rewritten immediately)
        self._free: collections.deque[int] = collections.deque(
            range(max_slots)
        )

    @property
    def n_active(self) -> int:
        return int(self.active.sum())

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def utilization(self) -> float:
        """Active-slot fraction. On THIS (contiguous) pool slots and bytes
        are the same resource, so this is also byte occupancy; under paged
        admission that identity breaks, and
        :class:`tpudist.serve.blocks.PagedSlotPool` overrides this
        property to report BLOCK-pool occupancy instead (a slot-count
        reading there overstates free capacity — the `serve` rows keep
        `slot_utilization` with the slot-count meaning and carry
        `pool_occupancy` separately; docs/OBSERVABILITY.md §1). Slot
        occupancy is topology-free: on a tensor-sharded engine the count
        is the same on every chip, so unlike the paged pool's per-chip
        byte reading this fraction needs no ``tensor_world`` footnote."""
        return self.n_active / self.max_slots

    def write_row(self, row_cache, slot: int) -> None:
        """Scatter a prefilled batch-1 cache into a SPECIFIC slot row,
        bypassing the pool's occupancy bookkeeping — the speculative
        DRAFT cache (``tpudist.serve.engine``) is a second SlotPool whose
        row-for-a-request is PINNED to whatever slot the target's
        admission chose, and whose cursors are the engine's shared
        per-slot position lane; this pool variant therefore keeps no
        positions/active of its own."""
        if not 0 <= slot < self.max_slots:
            raise ValueError(f"slot {slot} outside [0, {self.max_slots})")
        self.cache = write_slot(self.cache, row_cache, slot)

    def insert(self, row_cache, true_len: int) -> int:
        """Scatter a prefilled batch-1 cache into a free slot; returns the
        slot index. Raises when the pool is full — the engine's admission
        control checks ``n_free`` first, so hitting this is a bug."""
        if not self._free:
            raise RuntimeError("slot pool exhausted (admission bug)")
        if not 0 < true_len <= self.max_seq_len:
            raise ValueError(
                f"prefix length {true_len} outside (0, {self.max_seq_len}]"
            )
        slot = self._free.popleft()
        self.cache = write_slot(self.cache, row_cache, slot)
        self.positions[slot] = true_len
        self.active[slot] = True
        return slot

    def advance(self, slot: int) -> None:
        """One decode step wrote this slot's token at its cursor; bump it."""
        self.positions[slot] += 1

    def release(self, slot: int) -> None:
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} released twice")
        self.active[slot] = False
        self.positions[slot] = 0
        self._free.append(slot)
