"""The compiled training step and epoch driver.

This is where the reference's layers L2–L5 collapse (SURVEY.md §3.4): the
per-step sequence ``.cuda() → forward (SyncBN all-gathers) → loss →
zero_grad → backward (DDP bucketed async all-reduce) → opt.step() →
reduce_loss`` (/root/reference/main.py:98-105) becomes ONE jit-compiled SPMD
program over the device mesh:

- params are replicated, the batch is sharded over the ``data`` axis;
- the loss is the mean over the *global* logical batch, so ``jax.grad``
  produces already-all-reduced gradients — XLA inserts the ICI/DCN psum and
  overlaps it with backward compute, which *is* the TPU-native equivalent of
  DDP's C++ Reducer bucketing (SURVEY.md §2.5);
- batch-norm statistics are computed over the global batch inside the same
  program (the SyncBatchNorm equivalent, §2.8);
- the Adam update (optax) runs in-graph (§2.9);
- the only host↔device traffic is the batch in and the scalar loss out.

Init-sync: DDP broadcasts rank-0 params at wrap time (main.py:83);
:func:`create_train_state` instead initializes from an explicit PRNG seed
inside a compiled program with replicated output sharding, so every process
holds bit-identical params by construction.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import struct
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist import mesh as mesh_lib
from tpudist.metrics import MetricsLogger
from tpudist.profiling import WindowedProfiler


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any  # empty FrozenDict for models without BN
    opt_state: Any
    # error-feedback residual of the explicit quantized gradient reduction
    # (tpudist.parallel.dp) — [world, n_buckets, bucket_size] fp32 sharded
    # over `data`, attached by GradReducer.attach_residual. None (the empty
    # pytree: zero leaves, so checkpoints and shardings of residual-free
    # states are untouched) everywhere else.
    comm_residual: Any = None


def cross_entropy_loss(logits, labels):
    """Softmax CE on logits vs int labels — the reference's
    ``CrossEntropyLoss`` (/root/reference/main.py:79,101)."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def smoothed_cross_entropy(smoothing: float):
    """CE against smoothed targets — the standard ImageNet recipe knob
    (ε=0.1 for the 76%-top-1 ResNet-50 schedule); ε=0 reduces exactly to
    :func:`cross_entropy_loss`."""

    def loss_fn(logits, labels):
        n = logits.shape[-1]
        targets = optax.smooth_labels(jax.nn.one_hot(labels, n), smoothing)
        return optax.softmax_cross_entropy(logits, targets).mean()

    return loss_fn


def lm_loss(logits, tokens):
    """Next-token CE for the GPT-2 config: predict tokens[1:] from tokens[:-1]."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]
    ).mean()


def create_train_state(
    model,
    rng: jax.Array | int,
    sample_input,
    tx: optax.GradientTransformation,
    mesh: Mesh | None = None,
    plan=None,
) -> TrainState:
    """Initialize params/opt state on the mesh.

    Placement follows the model's ``nn.with_partitioning`` metadata:
    metadata-free models (ResNet — the DDP model) come out fully
    replicated; annotated models (GPT-2's and ViT's Megatron specs, inert
    on a size-1 ``tensor`` axis) come out sharded, with the optimizer's
    params-shaped mirrors sharded to match.

    Same seed on every process ⇒ bit-identical params — the TPU-native
    init-sync replacing DDP's rank-0 broadcast (SURVEY.md §2.5).

    A ZeRO-1 optimizer (``tpudist.optim.shard_state`` — it advertises
    ``state_shardings``) overrides the metadata-derived (replicated)
    opt-state placement with its own data-axis shardings, so the Adam
    mirrors are BORN sharded inside this one compiled init — they never
    materialize replicated, not even transiently, which is what lets a
    ~1B-param state fit 16 GB HBM at bring-up.

    A ``plan`` (:class:`tpudist.parallel.plan.ParallelPlan`) resolves the
    whole composed placement instead: Megatron/pipe metadata kept, every
    still-replicated leaf (optimizer mirrors included) scattered over
    ``fsdp``, ZeRO-1's data-axis layout overlaid where the plan skipped —
    the state is born 3-D/4-D sharded in the same one compiled init.
    """
    if isinstance(rng, int):
        rng = jax.random.key(rng)

    def _boxed():
        # params stay in their nn.Partitioned boxes through tx.init, so the
        # optimizer's params-shaped mirrors (adam mu/nu) carry the same
        # partitioning metadata — the sharding tree below covers them too
        variables = model.init(rng, sample_input, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", FrozenDict())
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=tx.init(params),
        )

    def _init():
        return nn.meta.unbox(_boxed())

    if plan is not None:
        if mesh is not None and mesh != plan.mesh:
            raise ValueError(
                "create_train_state got both a mesh and a plan with a "
                "DIFFERENT mesh — build the plan over the run's mesh "
                "(ParallelPlan(mesh)) or drop the mesh argument"
            )
        shardings = plan.state_shardings(_boxed, tx)
        return jax.jit(_init, out_shardings=shardings)()
    if mesh is None:
        return jax.jit(_init)()
    shardings = state_shardings_from_meta(_boxed, mesh)
    if hasattr(tx, "state_shardings"):
        # ZeRO-1: the optimizer owns its state's placement
        params_shapes = jax.eval_shape(_boxed).params
        shardings = shardings.replace(
            opt_state=tx.state_shardings(params_shapes)
        )
    return jax.jit(_init, out_shardings=shardings)()


def state_shardings_from_meta(boxed_init_fn, mesh: Mesh):
    """TrainState-shaped tree of NamedShardings from ``nn.with_partitioning``
    metadata (unannotated leaves → replicated). The tree matches the
    *unboxed* state, which is what ``nn.get_partition_spec`` returns."""
    specs = nn.get_partition_spec(jax.eval_shape(boxed_init_fn))
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def state_shardings_of(state: TrainState):
    """The concrete sharding of every leaf of a placed TrainState — pass to
    :func:`make_train_step` as ``state_sharding`` for TP/FSDP runs."""
    return jax.tree_util.tree_map(lambda x: x.sharding, state)



def _apply_input_transform(transform, inputs, batch, step=None):
    """The one home for the input_transform calling convention: plain
    transforms receive the inputs; transforms declaring ``wants_batch``
    also receive the whole batch dict — the hook for device-resident
    operands (e.g. DeviceCachedLoader's "_cache") that must arrive as REAL
    jit arguments. A closure-captured jax.Array would be lowered as an HLO
    literal, and on a remote-compile attach a literal the size of a dataset
    ships with the HLO over the (slow) tunnel — a measured multi-minute
    stall per compile.

    Transforms declaring ``wants_step`` additionally receive the step
    counter (last positional arg) — the randomness key for in-graph
    augmentation (``tpudist.data.transforms.device_random_crop_flip``).
    Eval paths pass ``step=None`` and refuse such transforms: augmentation
    has no business in an eval pass, and scoring through one silently
    would corrupt the measurement."""
    if transform is None:
        return inputs
    wants_step = getattr(transform, "wants_step", False)
    if wants_step and step is None:
        raise ValueError(
            "input_transform declares wants_step (an augmenting transform) "
            "but this is an eval path — evaluate with the normalization "
            "transform only"
        )
    args = [inputs]
    if getattr(transform, "wants_batch", False):
        args.append(batch)
    if wants_step:
        args.append(step)
    return transform(*args)


def resolve_fused(fused, model, tx) -> frozenset:
    """Resolve a ``fused=`` request against what the model/optimizer
    support — the ONE mapping both :func:`make_train_step` and ``fit``
    (via the step's ``fused_info``) rely on.

    ``None``/``False``/``"none"`` → nothing (programs bit-identical to the
    pre-fusion rounds). ``"auto"`` → every fusion that is AVAILABLE: the
    Pallas LN path when the model exposes a ``fused_ln`` knob (the
    GPT-2/Llama/BERT/ViT families), the fused-optimizer forward wiring
    when ``tx`` carries a :func:`tpudist.optim.fused_adamw` (directly or
    under ``shard_state``/``skip_nonfinite``). ``"ln"``/``"optimizer"``
    demand exactly one side and raise when unsupported — a request that
    silently did nothing would be a benchmark lying about its
    configuration. ``"all"`` demands both.
    """
    if not fused or fused == "none":
        return frozenset()
    if fused is True:
        fused = "auto"
    if fused not in ("auto", "ln", "optimizer", "all"):
        raise ValueError(
            f"fused={fused!r}: expected None/'none'/'auto'/'ln'/"
            "'optimizer'/'all'"
        )
    from tpudist.optim import find_fused

    ln_ok = hasattr(model, "fused_ln")
    opt_ok = find_fused(tx) is not None
    out = set()
    if fused in ("ln", "all") or (fused == "auto" and ln_ok):
        if not ln_ok:
            raise ValueError(
                f"fused={fused!r} requests the fused LN path but "
                f"{type(model).__name__} has no fused_ln knob (the "
                "GPT-2/Llama/BERT/ViT families carry it)"
            )
        out.add("ln")
    if fused in ("optimizer", "all") or (fused == "auto" and opt_ok):
        if not opt_ok:
            raise ValueError(
                f"fused={fused!r} requests the fused-optimizer path but "
                "the optimizer chain carries no tpudist.optim.fused_adamw "
                "(build one via make_optimizer(fused=True) or "
                "optim.fused_adamw; shard_state/skip_nonfinite wrappers "
                "are looked through)"
            )
        out.add("optimizer")
    return frozenset(out)


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    loss_fn: Callable = cross_entropy_loss,
    input_key: str = "image",
    label_key: str = "label",
    grad_accum: int = 1,
    remat: bool | str = False,
    state_sharding=None,
    batch_spec: Mapping[str, P] | None = None,
    forward_loss: Callable | None = None,
    dropout_seed: int = 0,
    input_transform: Callable | None = None,
    telemetry: bool = False,
    guard_nonfinite: bool = False,
    reduce: Any = "none",
    reduce_bucket_size: int | None = None,
    error_feedback: bool = True,
    fused: str | bool | None = None,
    plan=None,
):
    """Build the jit-compiled (state, batch) → (state, metrics) step.

    ``reduce`` selects the gradient-reduction path (``tpudist.parallel.dp``):
    ``"none"`` (default) keeps the implicit XLA psum — optimal on ICI;
    ``"bucketed"`` computes per-replica gradients inside a ``shard_map`` and
    all-reduces them explicitly as fixed-size fp32 buckets (the DDP-Reducer
    structure, exact); ``"quantized"`` additionally ships int8 on the wire —
    per-bucket scales, stochastic rounding, fp32 master accumulation, and an
    error-feedback residual carried in ``state.comm_residual`` (attach once
    via ``step.grad_reducer.attach_residual(state)``; ``fit()`` does it) so
    convergence tracks fp32 within tolerance; ``"auto"`` picks quantized on
    a multi-slice (DCN-crossing) attach and none otherwise. A prebuilt
    ``dp.GradReducer`` is accepted verbatim. With ``grad_accum > 1`` the
    quantized+error-feedback reduction is double-buffered inside the
    accumulation scan: microbatch ``i-1``'s buckets reduce while microbatch
    ``i``'s forward/backward runs (residual-free configs accumulate locally
    and reduce once after the scan).
    The explicit path is pure-DP (replicated params, no ``batch_spec``, no
    device-resident ``"_"`` operands — enforced loudly) and composes with
    ZeRO-1 ``shard_opt_state``, ``amp.skip_nonfinite`` and
    ``guard_nonfinite`` (both see the already-dequantized gradients; a
    skipped step never poisons the residual). ``reduce_bucket_size``
    overrides the bucket size in ELEMENTS (default
    ``tpudist.comm.DEFAULT_BUCKET_ELEMS``); ``error_feedback=False`` drops
    the residual (pure unbiased quantization noise — the A/B knob the
    convergence tests pin down). The reducer is exposed as
    ``step.grad_reducer`` (``None`` on the implicit path) and the wire
    accounting as ``step.comm_stats(params)``.

    ``telemetry=True`` folds the in-step health metrics into the compiled
    program (tpudist.telemetry): global grad-norm, param-norm (pre-update),
    update-norm, and the non-finite gradient element count ride the metrics
    pytree out — a handful of reductions XLA fuses into the existing
    backward/psum path, measured <2% of step time by the bench's
    ``telemetry_overhead_pct`` leg. ``guard_nonfinite=True`` additionally
    SKIPS a poisoned update inside the same program: when the loss or any
    gradient is non-finite, params/opt-state/batch-stats keep their
    pre-step values (the step counter still advances, so data position and
    resume math stay exact) and ``metrics["update_skipped"]`` reports 1.
    The in-graph skip is what makes the host-side NaN sentry's event
    "after the fact" harmless — by the time the host sees the anomaly the
    state was never corrupted. Both default off: the step's programs (and
    HLO) are bit-identical to previous rounds when unused.

    ``input_transform``: optional in-graph function applied to
    ``batch[input_key]`` before the model — e.g.
    :func:`tpudist.data.transforms.device_normalize`, which lets the loader
    ship uint8 pixels (4× less host→device traffic and host float work than
    staging float32) and runs the ToTensor+normalize affine on device, where
    XLA fuses it into the first conv's input read.

    ``forward_loss``: optional fused ``(params, batch_stats, batch) →
    (loss, new_stats)`` replacing the default logits+loss_fn composition —
    e.g. :func:`tpudist.models.gpt2.chunked_lm_forward`, which keeps the LM
    head's logits from ever materializing.

    ``dropout_seed`` keys the per-step dropout stream for models whose
    ``dropout`` field is > 0 (the key is folded with the step counter, so
    masks differ every step but agree across replicas/processes).

    ``state_sharding``: a TrainState-shaped pytree of NamedShardings (see
    :func:`state_shardings_of`) for TP/FSDP runs where params are NOT fully
    replicated; defaults to the replicated DDP model.

    ``plan`` (:class:`tpudist.parallel.plan.ParallelPlan`): the composed
    3-D/4-D configuration this step runs under. The plan does not replace
    ``state_sharding`` (build the state with ``create_train_state(...,
    plan=plan)`` and pass ``state_shardings_of(state)`` — ``fit(plan=...)``
    does both); it validates the composition loudly instead: the mesh must
    match, the state must arrive plan-sharded, and an explicit ``reduce``
    request on a model-sharded plan raises naming the fix (the explicit
    reducer reduces over ``data`` only; composed plans keep the implicit
    GSPMD reduction). Carried as ``step.plan`` for telemetry/bench
    attribution.

    ``batch_spec``: per-key PartitionSpec overrides for the staged batch —
    e.g. ``{"tokens": P(('data','fsdp'), 'seq')}`` shards the sequence dim
    over the ``seq`` axis for context-parallel (ring/Ulysses) models. Keys
    not listed keep the default batch-dim-over-data sharding. With
    ``grad_accum > 1`` the spec must include the leading microbatch dim.

    ``grad_accum > 1`` scans over ``grad_accum`` microbatches
    (batch leading dims ``[grad_accum, micro_batch, ...]``, microbatch dim
    sharded over ``data``) accumulating gradients in fp32 — the
    BASELINE.json config-5 extension; XLA still emits a single fused program
    with one logical all-reduce per step.

    ``remat`` selects an activation-rematerialization policy by name
    (:mod:`tpudist.remat`): ``"none"``, ``"full"``, ``"dots_saveable"``
    (save MXU outputs, recompute the elementwise tail — usually the best
    TPU trade), ``"save_nothing"``; the legacy bool still works
    (``True`` ≡ ``"full"``). This wraps the WHOLE forward; per-block
    checkpointing — the stronger memory lever for deep models — is the
    model zoo's ``remat_policy`` field, same policy names.

    ``fused`` selects the step-fusion layer attacking the measured
    non-GEMM tail (docs/PERF.md §4c): ``"ln"`` clones the model with
    ``fused_ln=True`` (the Pallas fused residual-add+LayerNorm kernel in
    every block, ``tpudist.ops.layernorm``), ``"optimizer"`` routes the
    forward through the compute-dtype param copy a
    ``tpudist.optim.fused_adamw`` keeps in its state (deleting the
    per-step fp32→bf16 param casts; gradients then arrive in the compute
    dtype — the standard mixed-precision trade, exact when the compute
    dtype IS fp32), ``"all"`` both, ``"auto"`` whatever the model/tx
    support, ``None`` (default) nothing — programs bit-identical to
    before. The resolved set rides ``step.fused`` / ``step.fused_info``
    (fit's telemetry ``fusion`` row). With a custom ``forward_loss``,
    ``"ln"`` needs the loss builder's ``rebuild`` hook
    (``chunked_lm_forward`` carries one) so the fused clone actually
    reaches the forward.
    """
    batch_axes = (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)

    if plan is not None:
        # composed-parallelism validation (tpudist.parallel.plan): the
        # plan must describe THIS mesh, the state must arrive with the
        # plan's shardings (never the replicated default), and an explicit
        # reduce request routes — data-axis-only, with the fix named —
        # before the reducer's own narrower refusals fire
        if plan.mesh != mesh:
            raise ValueError(
                f"make_train_step got plan {plan.describe()} over a "
                "different mesh than the step's — build the plan over the "
                "run's mesh (ParallelPlan(mesh))"
            )
        plan.validate_state_sharding(state_sharding)
        plan.validate_reduce(
            reduce if isinstance(reduce, str) or reduce is None
            else getattr(reduce, "method", None)
        )

    from tpudist.parallel import dp as dp_mod

    reducer = dp_mod.make_reducer(
        reduce, mesh,
        **({} if reduce_bucket_size is None
           else {"bucket_size": reduce_bucket_size}),
        error_feedback=error_feedback, seed=dropout_seed,
    )
    if reducer is not None:
        if batch_spec is not None:
            raise ValueError(
                "reduce=... is pure-DP and incompatible with batch_spec "
                "overrides (context/sequence-parallel models keep the "
                "implicit XLA reduction)"
            )
        if state_sharding is not None:
            def _sharded_for_real(s):
                # Megatron annotations on size-1 axes (the model zoo's
                # inert TP specs) are replication in fact — only a spec
                # naming an axis with >1 devices actually splits params
                spec = getattr(s, "spec", P())
                for part in spec:
                    names = part if isinstance(part, tuple) else (part,)
                    for name in names:
                        if name is not None and mesh.shape[name] > 1:
                            return True
                return False

            bad = [
                s.spec for s in jax.tree_util.tree_leaves(
                    getattr(state_sharding, "params", state_sharding)
                )
                if _sharded_for_real(s)
            ]
            if bad:
                raise ValueError(
                    "reduce=... requires fully-replicated params (the "
                    "explicit bucketed/quantized reducer reduces over the "
                    f"'data' axis only); got param shardings {bad[:3]} — "
                    "keep reduce='none' (GSPMD reduce-scatters over "
                    "fsdp/tensor in-graph), or move those devices to the "
                    "data axis (make_train_step(plan=ParallelPlan.build("
                    "data=-1)) / MeshConfig(data=-1)) before asking for "
                    "the explicit wire format"
                )

    fused_set = resolve_fused(fused, model, tx)
    if ("ln" in fused_set and not getattr(model, "fused_ln", False)
            and forward_loss is not None
            and getattr(forward_loss, "rebuild", None) is None):
        # a custom forward_loss closure captured the UNFUSED model and
        # exposes no way to re-close over the fused clone. Under "auto"
        # (best-effort by contract) the LN side simply isn't available —
        # decline it with a warning; an explicit request must not
        # silently run unfused, so it raises.
        if fused in ("auto", True):
            import warnings

            warnings.warn(
                "fused='auto': declining LN fusion — forward_loss has no "
                ".rebuild(model) hook, so the fused model clone cannot "
                "reach the forward (chunked_lm_forward carries the hook; "
                "or build forward_loss from a fused_ln=True model)"
            )
            fused_set = fused_set - {"ln"}
        else:
            raise ValueError(
                "fused LN needs the forward to run the CLONED model, "
                "but this forward_loss closure captured the unfused "
                "one and exposes no .rebuild(model) hook — build it "
                "from a fused_ln=True model yourself, or use "
                "chunked_lm_forward (which carries the hook)"
            )
    if "ln" in fused_set and not getattr(model, "fused_ln", False):
        # same params, same names — fused_ln only swaps the LN modules for
        # their kernel twins, so the state built from the unfused model
        # drives this clone unchanged
        model = model.clone(fused_ln=True)
        if forward_loss is not None:
            forward_loss = forward_loss.rebuild(model)
    if "optimizer" in fused_set:
        from tpudist.optim import find_fused as _find_fused

        _fused_tx = _find_fused(tx)
        fused_info = {
            "ln": "ln" in fused_set,
            "optimizer": True,
            "compute_dtype": (
                None if _fused_tx.compute_dtype is None
                else jnp.dtype(_fused_tx.compute_dtype).name
            ),
        }
    else:
        fused_info = {
            "ln": "ln" in fused_set, "optimizer": False,
            "compute_dtype": None,
        }

    # models that sow auxiliary losses (e.g. MoE load-balance,
    # parallel/ep.py) declare it via ``has_aux_loss``; duck-typed models
    # without the attribute keep the plain (non-mutable) apply path
    wants_aux = bool(getattr(model, "has_aux_loss", False))
    # MoE router observability (docs/OBSERVABILITY.md §1): when telemetry
    # is on and the model sows router stats (tpudist.parallel.ep's
    # 'moe_stats' collection), the forward also returns them and they ride
    # the step metrics into the telemetry "moe" rows. Only on the plain
    # single-pass path: the explicit reducer's grad_fn contract and the
    # micro-scan's carry both fix the forward's return shape to
    # (loss, stats), and router stats are a health signal, not gradient
    # math — the restricted paths simply don't emit the rows.
    moe_telemetry = bool(
        telemetry and wants_aux and reducer is None and grad_accum == 1
        and forward_loss is None
    )
    # models with a dropout field > 0 need a 'dropout' rng each step; the
    # key is derived from the step counter so every step (and every process,
    # identically — the mask must agree across replicas) draws fresh noise.
    # router_jitter (MoE router-input noise, parallel/ep.py) rides the same
    # stream under the same derivation.
    dropout_rate = float(getattr(model, "dropout", 0.0) or 0.0)
    jitter_rate = float(getattr(model, "router_jitter", 0.0) or 0.0)
    dropout_base = jax.random.key(dropout_seed)

    def _moe_metrics(sown) -> dict:
        """Sown 'moe_stats' tree → flat metric keys: the dict path joined
        with '/', the MoEMlp module's own 'moe' segment elided, prefixed
        'moe/' — e.g. ``{'h_1': {'moe': {'load': (arr,)}}}`` →
        ``{'moe/h_1/load': arr}``."""
        out = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(sown)[0]:
            segs = [
                p.key for p in path if hasattr(p, "key") and p.key != "moe"
            ]
            out["moe/" + "/".join(segs)] = leaf
        return out

    def forward(params, batch_stats, batch, step):
        variables = {"params": params, "batch_stats": batch_stats}
        has_stats = len(batch_stats) > 0
        inputs = _apply_input_transform(
            input_transform, batch[input_key], batch, step
        )
        mutable = (["batch_stats"] if has_stats else []) + (
            ["losses"] if wants_aux else []
        ) + (["moe_stats"] if moe_telemetry else [])
        kwargs = {}
        if dropout_rate > 0 or jitter_rate > 0:
            key = jax.random.fold_in(dropout_base, step)
            if reducer is not None:
                # inside the explicit path's shard_map each replica sees
                # only its local batch rows; the step-derived key alone
                # would draw the SAME mask on every replica (row i of every
                # shard sharing noise — W-fold less mask diversity than the
                # implicit path's one global-batch draw). Folding in the
                # replica index restores independent per-rank masks — DDP's
                # exact dropout semantics.
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(mesh_lib.DATA_AXIS)
                )
            kwargs["rngs"] = {"dropout": key}
        if mutable:
            logits, updates = model.apply(
                variables, inputs, train=True, mutable=mutable, **kwargs
            )
            new_stats = updates.get("batch_stats", batch_stats)
            aux = sum(jax.tree_util.tree_leaves(updates.get("losses", {})), 0.0)
        else:
            logits = model.apply(variables, inputs, train=True, **kwargs)
            new_stats = batch_stats
            aux = 0.0
        loss = loss_fn(logits, batch[label_key]) + aux
        if moe_telemetry:
            return loss, (new_stats, _moe_metrics(
                updates.get("moe_stats", {})
            ))
        return loss, new_stats

    if forward_loss is not None:
        # fused losses don't take the step arg (no dropout on that path) —
        # refuse rather than silently train without the configured dropout
        if dropout_rate > 0:
            raise ValueError(
                f"model.dropout={dropout_rate} but forward_loss has no rng "
                "stream; use the default forward or a dropout-free model"
            )
        if jitter_rate > 0:
            raise ValueError(
                f"model.router_jitter={jitter_rate} but forward_loss has "
                "no rng stream; use the default forward or router_jitter=0"
            )
        forward = lambda params, stats, batch, step: forward_loss(params, stats, batch)
    from tpudist.remat import checkpoint as _remat_checkpoint

    forward = _remat_checkpoint(forward, remat)

    grad_fn = jax.value_and_grad(forward, has_aux=True)

    def step_fn(state: TrainState, batch):
        new_residual = state.comm_residual
        # fused-optimizer forward wiring: the forward reads the compute-
        # dtype copy fused_adamw wrote in LAST step's update sweep (==
        # compute_dtype(current params), never stale), deleting the
        # per-op fp32→compute casts and halving the forward's param-read
        # bytes. Declined (masters used) whenever the copy is absent or
        # not params-shaped — e.g. ZeRO-1 pad-stored leaves.
        fwd_params = state.params
        if "optimizer" in fused_set:
            from tpudist.optim import fused_compute_params

            copy = fused_compute_params(state.opt_state, state.params)
            if copy is not None:
                fwd_params = copy
        if reducer is not None:
            bad_keys = sorted(k for k in batch if k.startswith("_"))
            if bad_keys:
                raise ValueError(
                    f"batch carries device-resident operands {bad_keys}, "
                    "which the explicit-reduction path does not stage into "
                    "its shard_map — use the implicit path (reduce='none') "
                    "with DeviceCachedLoader"
                )
            loss, grads, new_stats, ef_res = reducer.compute(
                grad_fn, fwd_params, state.batch_stats, batch, state.step,
                state.comm_residual, grad_accum,
            )
            if ef_res is not None:
                new_residual = ef_res
        elif grad_accum == 1:
            (loss, fwd_aux), grads = grad_fn(
                fwd_params, state.batch_stats, batch, state.step
            )
            if moe_telemetry:
                new_stats, moe_metrics = fwd_aux
            else:
                new_stats = fwd_aux
        else:
            # "_"-prefixed keys are per-step operands (e.g. the
            # DeviceCachedLoader's "_cache"), not row data: they have no
            # microbatch dim, so they ride into every microbatch unscanned
            # instead of being scanned over (whose leading-axis check they
            # would fail)
            operands = {k: v for k, v in batch.items() if k.startswith("_")}
            rows = {k: v for k, v in batch.items() if not k.startswith("_")}

            def micro(carry, xs):
                mb, i = xs
                gsum, stats, lsum = carry
                # distinct dropout stream per microbatch
                (l, stats), g = grad_fn(
                    fwd_params, stats, {**mb, **operands},
                    state.step * grad_accum + i
                )
                gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
                return (gsum, stats, lsum + l), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
            (gsum, new_stats, lsum), _ = jax.lax.scan(
                micro,
                (zeros, state.batch_stats, jnp.zeros((), jnp.float32)),
                (rows, jnp.arange(grad_accum)),
            )
            grads = jax.tree_util.tree_map(lambda g: g / grad_accum, gsum)
            loss = lsum / grad_accum

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if reducer is not None and reducer.error_feedback:
            # a non-finite step (bf16 spike, data glitch) must not bank its
            # garbage into the error-feedback residual: whether the update
            # itself is rejected by guard_nonfinite, amp.skip_nonfinite, or
            # nothing at all, the residual reverts — detection on the
            # DEQUANTIZED grads, the same values every other consumer sees
            from tpudist.amp import all_finite as _all_finite

            res_ok = jnp.isfinite(loss) & _all_finite(grads)
            new_residual = jnp.where(
                res_ok, new_residual, state.comm_residual
            )
        # loss is the global-batch mean — the in-graph equivalent of the
        # reference's post-step reduce_loss (main.py:105)
        metrics = {"loss": loss}
        if moe_telemetry:
            metrics.update(moe_metrics)
        if reducer is not None:
            # wire bytes this step's reductions move per replica — a static
            # constant, but carried as a metric so it rides the existing
            # one-step-delayed fetch with the other step scalars. fp32's
            # 24-bit mantissa rounds GB-scale counts; exact-integer
            # consumers (the telemetry rows) read comm_stats() instead
            metrics["comm_bytes"] = jnp.asarray(
                reducer.layout_for(state.params).wire_bytes(
                    reducer.method,
                    reductions=reducer.reductions_per_step(grad_accum),
                ),
                jnp.float32,
            )
        if telemetry:
            # health metrics inside the same compiled program: these are
            # full-tree reductions over values the step already holds, so
            # XLA schedules them alongside the backward pass and the only
            # addition to the metrics fetch is four more scalars on the
            # existing one-step-delayed async path. On the explicit-
            # reduction path `grads` is the dequantized cross-replica mean,
            # so the count sees exactly what the optimizer sees.
            from tpudist.amp import nonfinite_count

            nonfinite = nonfinite_count(grads)
            metrics.update(
                grad_norm=optax.global_norm(grads),
                param_norm=optax.global_norm(state.params),
                update_norm=optax.global_norm(updates),
                nonfinite_grad_count=nonfinite,
            )
        if guard_nonfinite:
            if telemetry:
                ok = jnp.isfinite(loss) & (metrics["nonfinite_grad_count"] == 0)
            else:
                from tpudist.amp import all_finite

                ok = jnp.isfinite(loss) & all_finite(grads)
            keep = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, old
            )
            from tpudist.amp import is_skip_state

            new_params = keep(new_params, state.params)
            new_opt = keep(new_opt, state.opt_state)
            if is_skip_state(new_opt):
                # amp.skip_nonfinite's (inner_state, int32 counter) shape,
                # static at trace time: the counter is run metadata (how
                # many updates were rejected), not optimizer state — the
                # freeze must not revert its increment, or
                # amp.skipped_steps / the telemetry run-summary read 0
                # whenever the guard is on. Under the guard "rejected"
                # means exactly ~ok, whichever check (the wrapper's own
                # updates scan or the guard's loss/grad one) caught it.
                new_opt = (new_opt[0], jnp.where(
                    ok, new_opt[1], state.opt_state[1] + 1
                ))
            new_stats = keep(new_stats, state.batch_stats)
            metrics["update_skipped"] = (~ok).astype(jnp.int32)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt,
            comm_residual=new_residual,
        )
        return new_state, metrics

    repl = mesh_lib.replicated_sharding(mesh)
    out_state_sharding = state_sharding if state_sharding is not None else repl
    if reducer is not None and reducer.error_feedback:
        # the residual is PER-REPLICA state — forcing it under the default
        # replicated sharding would all-gather world× copies onto every
        # chip; pin its leaf to the data-sharded layout it was born with
        res_sh = reducer.residual_sharding()
        if state_sharding is None:
            out_state_sharding = TrainState(
                step=repl, params=repl, batch_stats=repl, opt_state=repl,
                comm_residual=res_sh,
            )
        else:
            out_state_sharding = state_sharding.replace(comm_residual=res_sh)

    def batch_sh(key, x):
        if batch_spec is not None and key in batch_spec:
            return NamedSharding(mesh, batch_spec[key])
        if grad_accum == 1:
            return mesh_lib.batch_sharding(mesh, extra_dims=x.ndim - 1)
        # leading microbatch dim replicated (scanned over), second dim sharded
        return NamedSharding(mesh, P(None, batch_axes, *([None] * (x.ndim - 2))))

    def stage(batch):
        """Host batch (flat leading dim [global_batch, ...]) → device batch.

        With grad accumulation the flat dim is folded to
        ``[grad_accum, micro, ...]`` *before* staging, so each device keeps
        contiguous rows of every microbatch and no resharding is needed.
        """
        mesh_lib.check_reserved_device_keys(batch)
        out = {}
        for k, v in batch.items():
            if isinstance(v, jax.Array):
                out[k] = v
                continue
            v = np.asarray(v)
            if grad_accum > 1:
                v = v.reshape(grad_accum, -1, *v.shape[1:])
            out[k] = mesh_lib.put_sharded(v, batch_sh(k, v))
        return out

    def compiled(state, batch):
        return _jitted(state, stage(batch))

    _jitted = jax.jit(
        step_fn, out_shardings=(out_state_sharding, repl), donate_argnums=(0,)
    )
    compiled.jitted = _jitted
    compiled.stage = stage
    compiled.grad_reducer = reducer
    compiled.comm_stats = (
        None if reducer is None
        else lambda params: reducer.comm_stats(params, grad_accum)
    )
    compiled.fused = fused_set
    compiled.fused_info = fused_info
    compiled.plan = plan
    return compiled


def fit(
    model,
    tx: optax.GradientTransformation,
    train_loader,
    *,
    epochs: int,
    mesh: Mesh | None = None,
    plan=None,
    seed: int = 0,
    job_id: str = "Job0",
    batch_size: int | None = None,
    world_size: int | None = None,
    global_rank: int | None = None,
    loss_fn: Callable = cross_entropy_loss,
    input_key: str = "image",
    label_key: str = "label",
    grad_accum: int = 1,
    remat: bool | str = False,
    shard_opt_state: bool = False,
    reduce: str = "none",
    fused: str | None = None,
    batch_spec: Mapping[str, P] | None = None,
    forward_loss: Callable | None = None,
    input_transform: Callable | None = None,
    profile: bool = True,
    prefetch_depth: int = 2,
    log_dir: str = ".",
    telemetry: bool | Any = False,
    memory_log_every: int | None = None,
    metrics_logger: MetricsLogger | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    checkpoint_every_s: float | None = None,
    keep_last: int | None = None,
    resume: bool = True,
    elastic: bool = False,
    compile_cache: str | None = None,
    preempt: bool | str = "auto",
    repair=None,
    chaos=None,
    init_params=None,
    init_input=None,
    metrics_port: int | None = None,
) -> tuple[TrainState, list[float]]:
    """The reference's whole training program (/root/reference/main.py:86-117)
    as a function: epochs × batches, per-epoch sampler re-shuffle, windowed
    profiler, TSV metrics, TrainTime footer. Returns final state and the
    per-step loss history.

    ``checkpoint_dir`` enables periodic async checkpointing (every
    ``checkpoint_every`` steps plus once at the end); with ``resume`` the
    latest checkpoint is restored and training continues from the exact
    step it stopped at (same epoch, same position in the sampler's
    deterministic order) — a capability the reference lacks entirely
    (SURVEY.md §5: no save/load; crash = start over).
    ``checkpoint_every_s`` adds a WALL-CLOCK cadence alongside the
    step-based one: a save triggers when either knob is due. The time
    knob is what bounds preemption loss on runs with variable step times
    — "at most N steps of work lost" is meaningless when steps range
    from 0.3 s to 30 s, "at most M minutes" is the contract operators
    actually want; the step knob keeps saves aligned to deterministic
    step numbers for A/B debugging. Interaction when both are set: every
    save (whichever knob triggered it) resets the time knob's clock, but
    the step knob stays pinned to absolute multiples of
    ``checkpoint_every`` — a time-triggered save between multiples does
    NOT postpone the next step-aligned save (alignment is the step
    knob's whole point), so the worst-case save frequency is the SUM of
    the two cadences, not the denser one.

    ``elastic=True`` lets a resume proceed when the checkpoint's recorded
    geometry differs from the live run by a WORLD RESIZE only
    (``tpudist.resilience.elastic``, docs/MULTIHOST.md "Resuming on a
    different world size"): ZeRO-1's pad-and-reshape optimizer leaves are
    re-laid onto the new mesh, the quantized reducer's error-feedback
    residual restarts zeroed (one step of uncompensated quantization
    noise, recorded by a one-shot telemetry ``reshard`` row), and
    ``state.step`` is remapped into the new world's step units so the
    sampler cursor lands on the same data position. The resharded state
    is committed immediately — a synchronous save in new-step units plus
    an atomic meta flip, with the old-geometry steps quarantined until
    both are durable — so a crash mid-commit always leaves a restorable
    directory. Mismatches that are NOT a pure resize (reduction method,
    shard_opt_state) still refuse loudly. The newest-checkpoint
    deserialization failure fallback (walk back one saved step, tagged
    ``checkpoint_fallback`` warning row) is active on every resume,
    elastic or not.

    ``compile_cache`` names a directory of serialized AOT step
    executables (``tpudist.compile_cache``): bring-up starts
    deserializing the matching executable WHILE the checkpoint restore
    streams, so a relaunched generation skips tracing entirely on a hit
    (the dominant term in ``restart_overhead_s``); on a miss the step is
    AOT-compiled at bring-up and stored for the next life. Keyed by
    (device topology, state/batch geometry, step config, jax versions);
    any mismatch or deserialization failure falls through to ordinary
    tracing with a ``warning`` row — the cache can cost a recompile,
    never a wrong program. Goodput attributes a warm first iteration to
    ``cache_load_s``, not ``compile_s``.

    ``preempt`` (default ``"auto"``) traps SIGTERM/SIGINT as a
    signal-safe flag checked at step boundaries (``tpudist.resilience``):
    on trip the in-flight step finishes, a *synchronous* emergency
    checkpoint is written (when ``checkpoint_dir`` is set), telemetry and
    the run report flush with ``exit_reason="preempted"``, and
    :class:`tpudist.resilience.Preempted` is raised — a ``SystemExit``
    carrying exit code 75, the code ``tpudist.launch`` restarts on.
    ``"auto"`` installs only where possible (main thread); ``False``
    keeps the default signal dispositions (the pre-resilience behavior).

    ``chaos`` injects a deterministic fault at a step boundary for
    recovery testing (``tpudist.resilience.chaos``): a spec string like
    ``"sigterm@12"`` / ``"crash@5@*"`` / ``"hang:600@8"`` /
    ``"corrupt@12"`` (truncate the newest checkpoint, then crash — the
    die-mid-write drill the fallback restore absorbs) /
    ``"bitflip@12"`` (flip one mantissa bit in one data-replica's param
    copy — the SDC drill the divergence probe + repair loop absorb) /
    ``"nanburst:3@12"`` (poison three consecutive steps' batches with
    NaNs, defeating the single-step guard), a comma-separated
    composition of several specs, a ``ChaosSpec`` (or list), or a
    prebuilt ``ChaosInjector``. ``None`` (default) injects nothing.

    ``repair`` (``None``/``False`` off; ``True`` = default
    :class:`tpudist.resilience.repair.RepairPolicy`; a policy or a dict
    of overrides to tune) turns detector verdicts into the self-healing
    escalation ladder (docs/MULTIHOST.md "Recovering from loss spikes
    and SDCs"): on a replica-divergence verdict, a ``skip_streak`` of
    consecutive guard-skipped steps, or a sustained NanSentry spike, fit
    rolls state back to the last-known-good ANCHORED checkpoint (a save
    promoted only after ``anchor_clean_steps`` clean health steps),
    advances the data cursor ``skip_window`` batches past the trigger,
    folds a repair-generation salt into the step RNG so dropout/
    stochastic-rounding redraw, and continues — in-process, no
    supervisor involved. A repeat trigger inside the just-repaired
    window persists a rollback-and-skip directive and raises
    :class:`tpudist.resilience.RepairRestart` (SystemExit 77, the
    restartable code the supervisor relaunches; bring-up consumes the
    directive); a rolling ``max_repairs``/``budget_window_s`` budget
    circuit-breaks a deterministic poison with
    :class:`tpudist.resilience.RepairExhausted` instead of spinning.
    Requires ``checkpoint_dir`` plus a save cadence; implies
    ``telemetry=True`` when telemetry is off (the detectors live
    there), and an SDC trigger additionally needs
    ``divergence_every``. Every action books honestly: a ``repair``
    JSONL row, the report's ``repairs`` history, and the goodput
    ``repair_s``/``repair_replay_s`` components.

    ``keep_last`` bounds checkpoint retention to the newest N step dirs
    (``Checkpointer.keep_last``) so long runs with a tight save cadence
    stop accumulating unbounded step dirs — the health-ANCHORED step is
    exempt from pruning (it is the repair loop's rollback target).
    ``None`` keeps the legacy orbax ``max_to_keep=3`` behavior, except
    under ``repair`` where anchor-protecting retention is forced
    (``keep_last=3``).

    ``telemetry`` (False | True | ``tpudist.telemetry.TelemetryConfig``)
    turns on the observability subsystem (docs/OBSERVABILITY.md): in-step
    health metrics and the non-finite update guard inside the compiled
    step, the NaN/divergence sentry + on-demand profiler flight recorder,
    per-step data-wait/dispatch/device time attribution, MFU rows for
    models that advertise a ``flops_counter``, and per-process heartbeat
    rows — all into a ``{job_id}_telemetry_{rank}.jsonl`` stream next to
    the TSV, which stays byte-identical to the reference contract when
    telemetry is off. The run-health layer rides the same config
    (``tpudist.telemetry.health``, docs/OBSERVABILITY.md §7): cross-process
    straggler aggregation, a replica-divergence probe, a hang watchdog
    with crash forensics, and a ``{job_id}_report.json`` end-of-run report
    written on normal exit AND from the crash/watchdog paths — the health
    detectors are off unless their config fields are set
    (``tpudist.telemetry.health.health_config`` is the production preset).

    ``memory_log_every`` cadences ``MetricsLogger.log_memory`` (live HBM
    rows) during training: ``None`` (default) auto-selects ``log_every·10``
    steps on backends that report allocator stats and off on those that
    don't (CPU); ``0`` disables; ``N`` forces a cadence.

    ``reduce`` selects the gradient-reduction path (see
    :func:`make_train_step`): ``"none"`` (default, implicit XLA psum),
    ``"bucketed"`` / ``"quantized"`` (explicit bucketed all-reduce, fp32 or
    int8-on-the-wire with error feedback — the DCN-bound data-parallel
    lever, docs/PERF.md §11), ``"auto"`` (quantized on a multi-slice
    attach). fit() attaches the error-feedback residual to the train state,
    records the method in the checkpoint geometry meta, and — with
    telemetry on — streams per-step comm bytes plus a one-time measured
    comm-time probe into the JSONL sink (a ``comm`` column on the step-time
    breakdown rows; rows are unchanged when the feature is off).

    ``fused`` selects the step-fusion layer (see :func:`make_train_step`
    and docs/PERF.md §4c): ``"ln"`` / ``"optimizer"`` / ``"all"`` /
    ``"auto"``; ``None`` (default) keeps the compiled programs
    bit-identical to previous rounds. With telemetry on, the resolved
    configuration is recorded as a one-time ``fusion`` JSONL row so bench
    records and run reports stay attributable to the kernels that
    actually ran.

    ``shard_opt_state=True`` wraps ``tx`` in ZeRO-1 cross-replica
    optimizer-state sharding (``tpudist.optim.shard_state``): the Adam
    mirrors live sharded over the ``data`` replicas (~1/world_size per
    chip, born sharded at init) and XLA decomposes the gradient all-reduce
    into reduce-scatter → sharded update → params all-gather inside the
    same compiled step. Combine with ``remat`` (named policy or the
    models' per-block ``remat_policy``) for the full memory-discipline
    recipe — the pair is what moves the trainable-size frontier on a
    16 GB chip (docs/PERF.md §10).

    ``plan`` (:class:`tpudist.parallel.plan.ParallelPlan`) runs the whole
    loop under one composed ``(data, fsdp, pipe, tensor)`` configuration
    (docs/PERF.md "Choosing a parallelism plan"): the state is born with
    the plan's placements (Megatron/pipe metadata kept, replicated leaves
    fsdp-scattered, ZeRO-1 overlaid when ``shard_opt_state=True`` — via
    ``plan.wrap_zero1``, which never double-shards an fsdp leaf), the
    step validates the composition loudly (explicit ``reduce`` routes to
    the data axis only, with the fix named), checkpoint geometry meta
    records the model-axis worlds (``fsdp_world``/``tensor_world``/
    ``pipe_world`` — a non-data-axis resize is default-denied with a
    precise hint, ``tpudist.resilience.elastic``), and telemetry's MFU
    rows divide model FLOPs by the plan's FULL chip count. ``mesh`` may
    be omitted (the plan carries it) or must match the plan's.
    """
    import itertools

    from tpudist.data.loader import prefetch_to_mesh

    if plan is not None:
        if mesh is not None and mesh != plan.mesh:
            raise ValueError(
                f"fit got both a mesh and a plan ({plan.describe()}) over "
                "a different mesh — build the plan over the run's mesh "
                "(ParallelPlan(mesh)) or drop the mesh argument"
            )
        mesh = plan.mesh
    mesh = mesh or mesh_lib.create_mesh()
    world_size = world_size if world_size is not None else jax.device_count()
    global_rank = (
        global_rank if global_rank is not None else jax.process_index()
    )
    if batch_size is None:
        # loader batch is per-process; the logged batch_size is per-replica
        # (the reference's per-GPU --batch_size, main.py:25)
        batch_size = train_loader.batch_size // jax.local_device_count()

    # init sample batch = the mesh's replica count, not 1: models with manual
    # (shard_map) axes — ring/Ulysses attention — refuse traces whose batch
    # doesn't divide the mesh; zeros keep init cheap and content-independent.
    # ``init_input`` overrides the probe-derived shape for models whose
    # init takes more than batch[input_key] (e.g. T5's (enc, dec) tuple) —
    # and skips the probe entirely (its only consumer).
    if init_input is None:
        # shape/dtype probe: one gathered sample where the loader supports
        # it (a full first batch would e.g. JPEG-decode the whole thing
        # twice)
        sample = (
            train_loader.probe()
            if hasattr(train_loader, "probe")
            else next(iter(train_loader))
        )
        sample_in = np.asarray(sample[input_key])
        init_input = jnp.zeros(
            (mesh_lib.data_parallel_size(mesh), *sample_in.shape[1:]),
            sample_in.dtype,
        )
    if shard_opt_state:
        if plan is not None:
            # ZeRO-1 composed with the plan: skip the leaves the plan
            # scatters over fsdp (no double-sharding — parallel/plan.py).
            # On an expert plan the skip rule also needs the expert-sharded
            # leaf SHAPES (the rule is shape-only), identified from an
            # abstract trace of the init's partitioning metadata.
            boxed = None
            if plan.expert > 1:
                boxed = jax.eval_shape(
                    lambda: model.init(
                        jax.random.PRNGKey(0), init_input, train=False
                    )
                )["params"]
            tx = plan.wrap_zero1(tx, params=boxed)
        else:
            from tpudist.optim import shard_state as _zero1

            tx = _zero1(tx, mesh)
    state = create_train_state(model, seed, init_input, tx, mesh, plan=plan)
    if init_params is not None:
        # warm-start (e.g. an HF checkpoint through tpudist.interop):
        # replace the random init leaf-for-leaf, keeping each leaf's mesh
        # placement and dtype; optimizer state stays fresh
        placed = jax.tree_util.tree_map(
            lambda ref, new: jax.device_put(
                jnp.asarray(new, ref.dtype), ref.sharding
            ),
            state.params, init_params,
        )
        from tpudist.optim import refresh_fused_compute

        # a fused_adamw compute copy was cast from the DISCARDED random
        # init — re-cast it from the warm-start weights (no-op for states
        # without a usable copy, which the forward also never reads)
        state = state.replace(
            params=placed,
            opt_state=refresh_fused_compute(state.opt_state, placed),
        )
    # DDP verifies rank param consistency at wrap time (main.py:83); same
    # check here — same seed must have produced identical params (no-op
    # single-process)
    from tpudist.distributed import verify_replicas

    verify_replicas(state.params)
    from tpudist.resilience import (
        GoodputTracker,
        Preempted,
        PreemptionGuard,
        make_injector,
        restart_generation,
    )
    from tpudist.resilience import repair as repair_mod

    generation = restart_generation()
    repair_policy = repair_mod.resolve_policy(repair)
    repair_ctl = None
    if repair_policy is not None:
        if checkpoint_dir is None:
            raise ValueError(
                "fit(repair=...) needs checkpoint_dir: the escalation "
                "ladder's first rung is a rollback to the last-known-good "
                "checkpoint (docs/MULTIHOST.md)"
            )
        if not checkpoint_every and not checkpoint_every_s:
            raise ValueError(
                "fit(repair=...) needs a save cadence (checkpoint_every "
                "and/or checkpoint_every_s): without periodic saves the "
                "rollback target never advances past bring-up"
            )
        if keep_last is None:
            # anchor-protecting retention: orbax's newest-N policy would
            # prune the rollback target out from under the repair loop
            keep_last = 3
        # built BEFORE the step so the directive's RNG salt (and the
        # repair-generation salt of a resumed post-repair trajectory)
        # reaches the compiled program's dropout/SR streams
        repair_ctl = repair_mod.RepairController(
            repair_policy, checkpoint_dir, generation=generation
        )
        if not telemetry:
            # the triggers ARE telemetry verdicts; a repair request with
            # telemetry off would watch nothing
            telemetry = True
    tel_cfg = None
    if telemetry:
        from tpudist.telemetry import TelemetryConfig

        tel_cfg = (
            telemetry if isinstance(telemetry, TelemetryConfig)
            else TelemetryConfig()
        )

    def build_step(step_seed):
        return make_train_step(
            model, tx, mesh,
            loss_fn=loss_fn, input_key=input_key, label_key=label_key,
            grad_accum=grad_accum, remat=remat, batch_spec=batch_spec,
            forward_loss=forward_loss, dropout_seed=step_seed,
            input_transform=input_transform, reduce=reduce, fused=fused,
            **(tel_cfg.step_kwargs() if tel_cfg else {}),
            # keep whatever sharding create_train_state produced
            # (replicated for plain DP, sharded for TP-annotated models
            # and plan-composed runs) — forcing replicated here would
            # all-gather a TP model's params on the first step
            state_sharding=state_shardings_of(state),
            plan=plan,
        )

    eff_seed = (
        repair_policy.salted_seed(seed, repair_ctl.salt)
        if repair_ctl is not None else seed
    )
    step = build_step(eff_seed)
    if step.grad_reducer is not None:
        # error-feedback residual born sharded over the data replicas
        # (no-op for methods that carry none)
        state = step.grad_reducer.attach_residual(state)

    # sized loaders only matter for resume math; a re-iterable loader without
    # __len__ still trains as long as checkpointing is off
    steps_per_epoch = len(train_loader) if hasattr(train_loader, "__len__") else None
    if checkpoint_dir is not None and steps_per_epoch is None:
        raise ValueError(
            "checkpointing needs a sized train_loader (len() maps state.step "
            "to an epoch/batch position for exact resume)"
        )
    run_meta = {
        "steps_per_epoch": steps_per_epoch,
        "batch_size": batch_size,
        "world_size": world_size,
        "grad_accum": grad_accum,
        # the model-axis worlds the state's placements are bound to
        # (composable-parallelism geometry): appended keys — metas
        # written before this layer carried none and default to 1, and
        # a NON-data-axis resize is default-denied with a precise hint
        # (tpudist.resilience.elastic.refusal_reason)
        "fsdp_world": int(mesh.shape[mesh_lib.FSDP_AXIS]),
        "tensor_world": int(mesh.shape[mesh_lib.TENSOR_AXIS]),
        "pipe_world": int(mesh.shape[mesh_lib.PIPELINE_AXIS]),
    }
    if shard_opt_state:
        # ZeRO-1 changes the opt-state LAYOUT on disk (padded [world, cols]
        # leaves): resuming it replicated (or at another world size) would
        # die in orbax with a shape mismatch — make the geometry guard say
        # so instead. Only recorded when on, so replicated runs' meta (and
        # their resumability) is unchanged.
        run_meta["shard_opt_state"] = True
    if step.grad_reducer is not None:
        # same geometry rule for the explicit-reduction path: the
        # error-feedback residual's [world, ...] layout (and the stochastic
        # rounding stream) is world-size-bound — resuming a quantized run
        # replicated (or vice versa) must refuse, not silently diverge
        run_meta["reduce"] = step.grad_reducer.method
    if shard_opt_state or step.grad_reducer is not None:
        # the world the stored layouts are actually bound to is the MESH's
        # data-axis size, not the (process-count-shaped) world_size above:
        # a device-count resize with an unchanged process count would
        # otherwise slip past the geometry guard and die in orbax with a
        # bare shape mismatch instead of a validated reshard/refusal
        run_meta["data_world"] = int(mesh.shape[mesh_lib.DATA_AXIS])
    chaos_inj = make_injector(chaos)
    # goodput spans only surface through the run report, so the tracker
    # rides the telemetry switch; its per-boundary cost is two clock reads
    gp = GoodputTracker(generation=generation) if tel_cfg is not None else None
    # SIGTERM/SIGINT → a signal-safe flag checked at step boundaries — the
    # graceful-preemption path (docs/MULTIHOST.md "Surviving preemption").
    # Installed here (post state-init, before checkpoint bring-up and the
    # whole loop — the step compile included): a preemption anywhere past
    # this line exits 75 after persisting whatever had become restorable.
    guard = PreemptionGuard(enabled=bool(preempt)).__enter__()
    preempt_signum = None
    repair_exit = None  # the ladder's rung-3 action, raised as exit 77
    ckpt = None
    start_step = 0
    losses: list[float] = []
    logger = None
    tel = None
    # bring-up diagnoses that happen BEFORE the telemetry sink exists
    # (reshard record, checkpoint-fallback warnings, compile-cache
    # outcome) — replayed into the sink once it is up
    bringup_events: list[dict] = []
    # AOT executable cache (tpudist.compile_cache): start deserializing
    # the cached step executable NOW, on a side thread, so the load
    # overlaps the checkpoint restore below instead of serializing with it
    cc = cc_key = cc_handle = cc_staged = None
    cc_info: dict | None = None
    tel_box: list = []  # late-bound telemetry ref for the AOT fallback
    if compile_cache is not None:
        try:
            from tpudist import compile_cache as cc_mod

            cc = cc_mod.CompileCache(compile_cache)
            cc_staged = cc_mod.staged_example(step, train_loader)
            if cc_staged is None:
                bringup_events.append({
                    "tag": "compile_cache_unsupported",
                    "reason": "loader cannot be probed into a shaped "
                    "batch (device-resident operands or unsized stream) "
                    "— falling through to ordinary tracing",
                })
                cc = None
            else:
                tel_knobs = tel_cfg.step_kwargs() if tel_cfg else {}
                model_id = cc_mod.model_identity(model)
                if ":" not in model_id:
                    # type-only identity (address-bearing default repr):
                    # the key cannot see model-code edits — say so once
                    bringup_events.append({
                        "tag": "compile_cache_weak_key",
                        "reason": "model repr is the default "
                        "address-bearing one, so the cache key sees only "
                        "the model TYPE — code edits with identical "
                        "geometry would reuse a stale executable; bump "
                        "the compile_cache dir after changing model code",
                    })
                cc_key = cc_mod.step_key(
                    mesh=mesh, state=state, batch=cc_staged,
                    config={
                        "reduce": getattr(
                            step.grad_reducer, "method", "none"
                        ),
                        "fused": sorted(step.fused),
                        "grad_accum": grad_accum,
                        "remat": str(remat),
                        "telemetry": bool(tel_knobs.get("telemetry")),
                        "guard_nonfinite": bool(
                            tel_knobs.get("guard_nonfinite")
                        ),
                        "shard_opt_state": bool(shard_opt_state),
                        "loss_fn": getattr(
                            loss_fn, "__qualname__", str(loss_fn)
                        ),
                        "forward_loss": (
                            getattr(forward_loss, "__qualname__",
                                    str(forward_loss))
                            if forward_loss is not None else None
                        ),
                        "input_key": input_key,
                        "label_key": label_key,
                        # the SALTED seed: a post-repair trajectory's
                        # program differs exactly when its RNG streams do
                        "dropout_seed": eff_seed,
                        "model": model_id,
                    },
                )
                cc_handle = cc.begin_load(cc_key)
        except Exception as exc:
            bringup_events.append({
                "tag": "compile_cache_unsupported",
                "reason": f"{type(exc).__name__}: {exc}"[:300],
            })
            cc = None
    try:
        if checkpoint_dir is not None:
            from tpudist.checkpoint import Checkpointer

            # inside try/finally so the manager's async-checkpointing threads
            # are torn down even when bring-up below raises
            ckpt = Checkpointer(checkpoint_dir, keep_last=keep_last)
            if chaos_inj is not None:
                # the corrupt@step drill truncates the newest checkpoint:
                # bind the target and the settle hook so it corrupts a
                # deterministic, already-committed step
                chaos_inj.bind(checkpoint_dir, wait=ckpt.wait)
            if repair_ctl is not None:
                # anchor persistence + rollback-target enumeration +
                # the retention protect hook (candidates must outlive
                # keep_last pruning until they promote or demote)
                repair_ctl.bind(ckpt)

                def apply_rollback(state, rollback_step, skip_to, *,
                                   on_event=None):
                    """The ONE rollback-apply — the exit-77 bring-up
                    directive and the in-process ladder share it: settle
                    async saves, restore the target step, flush the
                    reducer's error-feedback banks (trajectory state —
                    the same reset elastic.py performs), set aside newer
                    (suspect) saves so a crash right after resumes from
                    the anchor, and jump the data cursor past the
                    skipped window (state.step IS the cursor, so resume
                    math and later checkpoints stay consistent)."""
                    rollback_step = int(rollback_step)
                    ckpt.wait()
                    state = ckpt.restore(
                        like=state, step=rollback_step, on_event=on_event
                    )
                    if step.grad_reducer is not None:
                        state = step.grad_reducer.attach_residual(state)
                    for s in ckpt.all_steps():
                        if s > rollback_step:
                            ckpt.quarantine_failed_step(s)
                    return state.replace(
                        step=jax.device_put(
                            jnp.asarray(int(skip_to), state.step.dtype),
                            state.step.sharding,
                        )
                    )
            # finish or roll back an elastic commit a previous life
            # crashed mid-way: adopt the committed new-world save (its
            # marker meta becomes THE meta — without this, a crash
            # between the barrier-save and the meta flip would re-reshard
            # an already-resharded checkpoint, double-remapping the
            # cursor) or rename the quarantined old steps back
            ckpt.recover_interrupted_reshard()
            resharded = False
            did_restore = False
            repair_directive = (
                repair_ctl.pending if repair_ctl is not None else None
            )
            if ckpt.latest_step() is not None:
                if not resume:
                    raise ValueError(
                        f"checkpoint_dir {checkpoint_dir} already holds "
                        "checkpoints but resume=False; refusing to mix runs "
                        "(the old steps + overwritten meta would corrupt a "
                        "later resume) — use a fresh checkpoint_dir"
                    )
                saved_meta = ckpt.read_meta()
                from tpudist.resilience import elastic as elastic_mod

                if saved_meta is not None and not elastic_mod.meta_matches(
                    saved_meta, run_meta
                ):
                    reason = elastic_mod.refusal_reason(
                        saved_meta, run_meta
                    )
                    if not elastic or reason is not None:
                        hint = (
                            " — this is a pure world resize; pass "
                            "fit(elastic=True) to reshard onto the live "
                            "mesh (docs/MULTIHOST.md)"
                            if reason is None else f" — {reason}"
                        )
                        raise ValueError(
                            f"checkpoint at {checkpoint_dir} was written by "
                            f"a run with different geometry ({saved_meta} "
                            f"!= {run_meta}); state.step would map to the "
                            "wrong data position — resume with the "
                            "original settings or start a fresh "
                            f"checkpoint_dir{hint}"
                        )
                    resharded = True
                if repair_directive is not None and resharded:
                    raise ValueError(
                        "a pending repair directive (exit-77 rollback-and-"
                        "skip) cannot compose with an elastic world resize "
                        "in the same bring-up — resume on the original "
                        "world first, or clear tpudist_repair.json"
                    )
                t_restore = time.perf_counter()
                if repair_directive is not None:
                    # exit-77 relaunch: rung 3 of the repair ladder left a
                    # rollback-and-skip directive — restore the ANCHORED
                    # step, not the (suspect) newest, and apply the skip
                    state = apply_rollback(
                        state, repair_directive["rollback_step"],
                        repair_directive["skip_to"],
                        on_event=bringup_events.append,
                    )
                else:
                    state = ckpt.restore(
                        like=state, reshard=resharded, run_meta=run_meta,
                        mesh=mesh, fallback=True,
                        on_event=bringup_events.append,
                    )
                if gp is not None:
                    gp.add("restore_s", time.perf_counter() - t_restore)
                did_restore = True
                if repair_directive is not None:
                    start_step = int(repair_directive["skip_to"])
                    repair_ctl.consume_pending()
                    resume_row = dict(repair_directive)
                    resume_row["action"] = "resume"
                    resume_row["resumed_generation"] = generation
                    bringup_events.append({"tag": "repair", **resume_row})
                else:
                    start_step = int(state.step)
                for ev in bringup_events:
                    # a step the fallback walked past failed to
                    # deserialize: set it aside (never delete — the
                    # failure may be transient I/O and the dir may still
                    # hold the healthy newest state), or it keeps
                    # shadowing latest_step AND blocks orbax's monotonic
                    # save order for every cadence save below its number
                    if ev.get("tag") == "checkpoint_fallback":
                        ckpt.quarantine_failed_step(ev["failed_step"])
                if resharded:
                    # commit the resharded world: the old-geometry step
                    # dirs are uninterpretable under the remapped counter
                    # (and may collide with its numbering), so quarantine
                    # them, barrier-save the new-world state, flip the
                    # meta atomically, and only then purge — a crash at
                    # any point leaves a restorable directory (see
                    # Checkpointer's reshard-commit protocol)
                    t_save = time.perf_counter()
                    ckpt.quarantine_steps(commit_meta=run_meta)
                    ckpt.save(state, wait=True)
                    if gp is not None:
                        gp.add(
                            "checkpoint_s", time.perf_counter() - t_save
                        )
            ckpt.write_meta(run_meta)
            ckpt.purge_quarantined()
            if repair_ctl is not None and ckpt.latest_step() is None:
                # a rollback target must exist from step one: a trigger
                # before the first cadence save would otherwise have
                # nothing to roll back to. Synchronous — a repairable run
                # is durable before it trains.
                t_save = time.perf_counter()
                ckpt.save(state, wait=True)
                if gp is not None:
                    gp.add("checkpoint_s", time.perf_counter() - t_save)
                repair_ctl.on_save(int(state.step))

        if cc is not None:
            from tpudist import compile_cache as cc_mod

            # join the background deserialization (it overlapped the
            # restore above); a miss AOT-compiles HERE — bring-up, where
            # goodput attributes it as compile_s — and stores the
            # executable for the next generation. Either way iteration 1
            # becomes an ordinary step.
            exe, cc_info = cc.finish(
                cc_handle, step, state, cc_staged, cc_key,
                meta={"job_id": job_id},
            )
            if exe is not None:
                if ckpt is not None and did_restore:
                    # jax 0.4.x XLA:CPU compat: an AOT executable must
                    # not donate orbax-restored buffers (heap corruption;
                    # no-op off the wart platform — see launder_restored).
                    # Keyed on the RESTORE having happened, not on the
                    # step number: an emergency save at step 0 restores
                    # orbax buffers all the same.
                    state = cc_mod.launder_restored(state)

                def _aot_fallback(exc):
                    # first-call validation failed (a geometry the key
                    # could not see): permanent fall-through to tracing,
                    # surfaced in the stream — never a silent wrong
                    # guess. Iteration 1 now pays a REAL trace+compile,
                    # so goodput reverts to the cold attribution too.
                    if gp is not None:
                        gp.clear_precompiled()
                    if tel_box:
                        tel_box[0].warn(
                            "compile_cache_fallback",
                            error=f"{type(exc).__name__}: {exc}"[:300],
                        )

                step = cc_mod.wrap_step(
                    step, exe, on_fallback=_aot_fallback,
                    expected_batch=cc_staged,
                )
                if gp is not None:
                    gp.set_precompiled(warm=bool(cc_info.get("hit")))
                    if cc_info.get("hit"):
                        # only the NON-overlapped wait: the load ran
                        # concurrently with the restore, and the goodput
                        # partition is disjoint by contract
                        gp.add(
                            "cache_load_s",
                            cc_info.get("load_wait_s", 0.0),
                        )
                    else:
                        gp.add("compile_s", cc_info.get("compile_s", 0.0))

        # the logger truncates ("w") its TSV on construction, so it must not
        # exist until checkpoint bring-up has succeeded — a refused resume
        # above would otherwise clobber the previous run's metrics
        logger = metrics_logger or MetricsLogger(
            job_id, batch_size, global_rank, world_size, log_dir=log_dir
        )
        # logger as context manager: the TrainTime footer is written even if a
        # step raises mid-training
        with logger, WindowedProfiler(
            job_id, enabled=profile, log_dir=f"{log_dir}/log_{job_id}"
        ) as p:
            print("Start")
            from tpudist.telemetry import TimedIterator, build_telemetry
            from tpudist.telemetry.flops import mesh_chips as flops_chips

            # sink attached BEFORE the first log_memory: the dual-sink
            # contract mirrors every logger row, including the bring-up
            # HBM baseline the live cadence rows are compared against
            tel = build_telemetry(
                tel_cfg or False,
                job_id=job_id, log_dir=log_dir, rank=global_rank,
                world_size=world_size, log_every=logger.log_every,
                # the MESH's chip count, not jax.device_count(): the MFU
                # denominator must count every chip the model program
                # actually spans (tensor/pipe splits included) and ONLY
                # those — a sub-mesh run on a shared attach would
                # otherwise divide by chips it never used
                n_chips=flops_chips(mesh),
                profiler=p, model=model,
                input_key=input_key, mesh=mesh,
            )
            if tel is not None:
                tel.goodput = gp
                if metrics_port is not None and global_rank == 0:
                    # opt-in live scrape endpoint (rank 0 only — the rank
                    # that owns the report): host-side counters the loop
                    # already computes, no extra device syncs. Closed by
                    # tel.shutdown() in the finally below.
                    from tpudist.telemetry.trace import MetricsExporter

                    tel.exporter = MetricsExporter(metrics_port)
                if repair_ctl is not None:
                    # detector → event-bus → repair controller: sentry and
                    # divergence verdicts become triggers; the report's
                    # `repairs` section reads the controller's live
                    # cross-generation history
                    tel.add_listener(repair_ctl.on_detection)
                    tel.repair_history = repair_ctl.history
                if tel.health is not None and ckpt is not None:
                    # hang_action="exit" tears the process down from the
                    # watchdog thread: give an in-flight async checkpoint
                    # commit a bounded chance to finalize first, or the
                    # relaunch restores an older step than exit-76 promises
                    tel.health.set_exit_drain(ckpt.wait)
                if gp is not None and generation and tel.health is not None:
                    # aggregate goodput across the lives of this job: the
                    # previous generation left its entries in the report
                    # this generation will overwrite
                    gp.load_previous(tel.health.report_path)
                logger.attach_sink(tel.sink)
                tel_box.append(tel)
                # replay bring-up diagnoses that predate the sink: the
                # elastic reshard record, checkpoint-fallback warnings,
                # and the AOT-cache outcome
                for ev in bringup_events:
                    ev = dict(ev)
                    tag = ev.pop("tag")
                    if tag == "reshard":
                        tel.set_reshard(ev)
                    elif tag == "repair":
                        tel.set_repair(ev)
                    else:
                        tel.warn(tag, **ev)
                if cc_info is not None:
                    tel.set_compile_cache(cc_info)
                if fused is not None:
                    # one-time fusion config row: which kernels this run's
                    # compiled step actually engaged — the attribution a
                    # bench record or run report needs next to its numbers
                    tel.set_fusion(step.fused_info)
                if step.grad_reducer is not None:
                    # one-time comm accounting + a measured standalone
                    # probe of the reduce-only program: the `comm` column
                    # the step-time breakdown rows carry (an unoverlapped
                    # upper bound; per-step comm BYTES additionally ride
                    # the compiled step's metrics through the delayed
                    # fetch)
                    tel.set_comm(
                        step.comm_stats(state.params),
                        probe_s=step.grad_reducer.time_probe(
                            state.params, grad_accum
                        ),
                    )
                if jax.default_backend() != "cpu":
                    # H2D link probe: one 8 MB staged buffer measures what
                    # the attach link sustains, so a link-bound run gets a
                    # tagged warning row pointing at DeviceCachedLoader
                    # instead of failing silently slow (docs/PERF.md §3)
                    from tpudist.comm import measure_h2d_mbps

                    tel.h2d_mbps = measure_h2d_mbps()
                if tel.config.anatomy:
                    # program anatomy at bring-up (docs/OBSERVABILITY.md
                    # §9): ask XLA what it actually compiled — FLOPs,
                    # bytes, static HBM — and cross-check the analytic
                    # MFU counter against it. The AOT path reuses the
                    # compile-cache executable for free; the jit path
                    # pays one lowering (no compile). Entirely fail-soft:
                    # introspection must never take a training run down.
                    try:
                        from tpudist import compile_cache as cc_mod
                        from tpudist.telemetry import anatomy as anat_mod

                        anat_staged = cc_staged
                        if anat_staged is None:
                            anat_staged = cc_mod.staged_example(
                                step, train_loader
                            )
                        if anat_staged is None:
                            tel.warn(
                                "anatomy_unavailable",
                                reason="loader cannot be probed into a "
                                "shaped batch — no program to lower",
                            )
                        else:
                            tel.set_anatomy(anat_mod.analyze_train_step(
                                step, state, anat_staged, model=model,
                                input_key=input_key,
                                grad_accum=grad_accum,
                            ))
                    except Exception as exc:
                        tel.warn(
                            "anatomy_failed",
                            error=f"{type(exc).__name__}: {exc}"[:300],
                        )
            breakdown = tel is not None and tel.config.breakdown

            # live HBM snapshot post-bring-up (params+opt state placed,
            # no activations yet): the measured side of the pre-compile
            # budget tpudist.memory reports; silent no-op on backends
            # without memory_stats (CPU)
            from tpudist.memory import device_memory_stats

            mem_stats = device_memory_stats()
            logger.log_memory(mem_stats)
            # automatic HBM-row cadence (None = auto: on only where the
            # allocator reports stats — the probe above doubles as the
            # capability check; 0 = off; N = every N steps)
            mem_every = memory_log_every
            if mem_every is None:
                mem_every = logger.log_every * 10 if mem_stats else 0
            # per-interval peak tracking for the cadence rows: the
            # allocator's peak_bytes_in_use is a LIFETIME high-water mark
            # — it plateaus after the first big step and hides later
            # spikes. Watching whether it ADVANCED since the previous
            # sample recovers the interval's peak (the spike value when
            # it moved, the current bytes otherwise), appended to the
            # memory row after the existing fields.
            mem_peak_seen = (mem_stats or {}).get("peak_bytes_in_use")

            global_step = start_step
            logger.start_timer()
            if gp is not None:
                gp.loop_started()
            last_save_t = time.monotonic()

            # one-step-delayed metric resolution: step k's scalars (loss +
            # the in-step health metrics) are FETCHED while step k+1
            # executes (copy_to_host_async starts the D2H as soon as the
            # values exist). A synchronous per-step fetch would insert one
            # host↔device round trip into every step — fine on a local PCIe
            # attach (~0.1 ms), a throughput cliff on a remote/tunnel attach
            # (~100 ms RTT measured). One step stays in flight, which also
            # throttles dispatch to the device rate. Rows land in the TSV
            # (and JSONL) in step order, one iteration later; the logged
            # duration is the inter-step interval (the sustained rate the
            # reference's clock measures, /root/reference/main.py:95-111).
            pending = None  # (step, epoch, idx, start, metrics, breakdown)
            # device-time probe staging (see the barrier below): the probe
            # runs 2 steps before each logged row so neither the logged
            # interval (barrier stall inflates it) nor the one right before
            # it (the post-barrier bubble deflates it — the resolve-side
            # backpressure needs one step to re-establish) is perturbed.
            # Cadences too short to stagger keep the probe on the logged
            # step itself.
            probe_offset = (
                2 if breakdown and tel.log_every >= 3 else 0
            )
            device_probe = None

            def resolve(now):
                g, pe, pidx, pstart, dev_metrics, waits = pending
                # integer metrics (nonfinite_grad_count, update_skipped)
                # stay ints — float() here would defeat the sink's
                # Integral-preserving serialization and land 3.0 in rows
                # documented as integer counts
                host = {
                    k: (v.tolist() if jnp.ndim(v) > 0
                        else int(v) if jnp.issubdtype(v.dtype, jnp.integer)
                        else float(v))
                    for k, v in dev_metrics.items()
                }
                loss_value = host["loss"]
                losses.append(loss_value)
                logger.log_step(g, loss_value, now - pstart)
                logger.print_progress(pe, pidx, loss_value)
                if tel is not None:
                    data_wait_s, dispatch_s, device_s = waits
                    tel.on_step(
                        g, host, epoch=pe, interval_s=now - pstart,
                        data_wait_s=data_wait_s, dispatch_s=dispatch_s,
                        device_s=device_s,
                    )
                if repair_ctl is not None:
                    # skip-streak arithmetic, anchor promotion clock, and
                    # replay pricing — after tel.on_step, whose sentry/
                    # divergence publications may already have set a
                    # trigger this same resolve
                    repair_ctl.observe_step(
                        g, host, interval_s=now - pstart
                    )

            # a SIGTERM that lands while the consumer is BLOCKED on a
            # stalled input pipeline must still reach the graceful path:
            # the prefetch wait polls this flag and ends the stream early
            # (staged batches drain first), and the epoch loop's own check
            # below then takes the preemption branch
            stop_check = (
                (lambda: guard.tripped is not None) if guard.active else None
            )
            try:
              # the repair loop: one pass per trajectory segment. A
              # repair trigger breaks out of the epoch loop, the handler
              # below rolls back / skips / escalates, and the while
              # re-enters the epoch loop at the repaired cursor. A
              # repair-less run takes exactly one pass.
              while True:
                repair_request = None
                start_epoch = (
                    global_step // steps_per_epoch if steps_per_epoch else 0
                )
                skip_batches = (
                    global_step % steps_per_epoch if steps_per_epoch else 0
                )
                for e in range(start_epoch, epochs):
                    if guard.tripped is not None:
                        preempt_signum = guard.tripped
                        break
                    if hasattr(train_loader, "sampler"):
                        train_loader.sampler.set_epoch(e)
                    first_idx = skip_batches if e == start_epoch else 0
                    # the sampler order is deterministic per epoch, so starting
                    # at the first unconsumed batch resumes mid-epoch at the
                    # exact position the checkpoint was taken; iter_from skips
                    # at the index level (no discarded gather/transform work),
                    # islice is the fallback for foreign loaders
                    if first_idx and hasattr(train_loader, "iter_from"):
                        batches = train_loader.iter_from(first_idx)
                    elif first_idx:
                        batches = itertools.islice(iter(train_loader), first_idx, None)
                    else:
                        batches = iter(train_loader)
                    if chaos_inj is not None:
                        # the nanburst drill poisons batches by STEP
                        # position — the wrapper maps this epoch's stream
                        # onto the steps it will train
                        batches = chaos_inj.wrap_batches(
                            batches, global_step + 1
                        )
                    staged = prefetch_to_mesh(
                        batches, mesh,
                        depth=prefetch_depth, stage_fn=step.stage,
                        stop_check=stop_check,
                    )
                    if breakdown or gp is not None:
                        # data-wait attribution: seconds this loop blocked
                        # on the prefetch queue (≈0 while the pipeline keeps
                        # up; → step time when the run is input-bound).
                        # Goodput needs the same number even when the
                        # breakdown rows are off.
                        staged = TimedIterator(staged)
                    for idx, batch in enumerate(staged, start=first_idx):
                        # step-boundary resilience hooks, BEFORE the next
                        # dispatch: chaos first (an injected SIGTERM must
                        # be visible to the guard check in this same
                        # iteration), then the graceful-preemption flag —
                        # so the last dispatched step is the one the
                        # emergency checkpoint persists
                        if chaos_inj is not None:
                            chaos_inj.maybe_fire(global_step)
                            state = chaos_inj.maybe_flip(
                                global_step, state, mesh
                            )
                        if guard.tripped is not None:
                            preempt_signum = guard.tripped
                            break
                        start = time.time()
                        global_step += 1
                        if tel is not None:
                            tel.observe_batch(batch)
                        dispatch_t0 = time.perf_counter()
                        with p.annotate(global_step):
                            state, metrics = step(state, batch)
                        dispatch_s = time.perf_counter() - dispatch_t0
                        for v in metrics.values():
                            v.copy_to_host_async()
                        if tel is not None:
                            # run-health hooks (no-ops unless configured):
                            # the watchdog beat marks "the loop is alive"
                            # once per iteration — placed AFTER dispatch so
                            # bring-up's first compile sits before the
                            # first beat and can't false-trip the deadline
                            # — and the divergence probe dispatches on the
                            # fresh state at its cadence (async; resolved
                            # one cadence later on the delayed pipeline)
                            tel.beat(global_step)
                            tel.observe_state(global_step, state)
                        device_s = None
                        if breakdown:
                            if (global_step + probe_offset) % tel.log_every == 0:
                                # cadenced device-time attribution: block
                                # until THIS step's result exists (includes
                                # any queued predecessor — the pipeline is
                                # 1 deep). Once per cadence, staggered off
                                # the logged step (probe_offset above): a
                                # per-step barrier would serialize the very
                                # pipeline it measures, and a barrier inside
                                # a logged step's interval would inflate
                                # exactly the throughput/MFU rows that
                                # advertise the sustained rate.
                                jax.block_until_ready(metrics["loss"])
                                device_probe = (
                                    time.perf_counter() - dispatch_t0
                                )
                            if global_step % tel.log_every == 0:
                                device_s = device_probe
                        # profiler schedule advances BEFORE resolve: resolve
                        # may arm the anomaly window, and arming after this
                        # iteration's step() means the window's countdown
                        # only starts at the NEXT annotated step — the full
                        # capture_steps budget lands on annotated steps
                        # (arming before it would burn one tick on the
                        # already-dispatched current iteration)
                        p.step()
                        if pending is not None:
                            resolve(start)
                        pending = (
                            global_step, e, idx, start, metrics,
                            (
                                staged.last_wait_s if breakdown else None,
                                dispatch_s,
                                device_s,
                            ),
                        )
                        if (repair_ctl is not None
                                and repair_ctl.triggered is not None):
                            # a detector verdict became a trigger (set by
                            # the resolve above or by a probe verdict
                            # resolved during observe_state): break to the
                            # repair handler BEFORE the cadence save — the
                            # current state is suspect and must not become
                            # a checkpoint
                            repair_request = repair_ctl.take_trigger()
                            break
                        if mem_every and global_step % mem_every == 0:
                            m = device_memory_stats()
                            interval_peak = None
                            if m:
                                lp = m.get("peak_bytes_in_use")
                                if lp is not None and (
                                        mem_peak_seen is None
                                        or lp > mem_peak_seen):
                                    interval_peak = lp
                                    mem_peak_seen = lp
                                else:
                                    interval_peak = m.get("bytes_in_use")
                            logger.log_memory(
                                m, peak_bytes_in_use=interval_peak
                            )
                        if ckpt is not None and (
                            (checkpoint_every
                             and global_step % checkpoint_every == 0)
                            or (checkpoint_every_s
                                and time.monotonic() - last_save_t
                                >= checkpoint_every_s)
                        ):
                            t_save = time.perf_counter()
                            if ckpt.save(state):
                                if repair_ctl is not None:
                                    # a new anchor CANDIDATE — promoted
                                    # only after anchor_clean_steps clean
                                    # steps (tpudist.resilience.repair)
                                    repair_ctl.on_save(global_step)
                            if gp is not None:
                                gp.add(
                                    "checkpoint_s",
                                    time.perf_counter() - t_save,
                                )
                            if tel is not None and tel.tracer is not None:
                                tel.tracer.span(
                                    "checkpoint",
                                    time.perf_counter() - t_save,
                                    step=global_step,
                                )
                            last_save_t = time.monotonic()
                        if gp is not None:
                            gp.step_boundary(staged.last_wait_s)
                    # a trip during a stalled prefetch wait ends the batch
                    # stream early WITHOUT running the in-loop check —
                    # re-check here so a last-epoch stall still takes the
                    # preemption branch instead of reporting "completed"
                    if preempt_signum is None and guard.tripped is not None:
                        preempt_signum = guard.tripped
                    if preempt_signum is not None or repair_request is not None:
                        break
                if (repair_request is None and preempt_signum is None
                        and repair_ctl is not None
                        and repair_ctl.triggered is not None):
                    # a verdict resolved on the run's very last iteration:
                    # still repair (the rollback discards the poisoned
                    # tail; the clamped skip_to ends the run at the clean
                    # cursor) rather than report a poisoned "completed"
                    repair_request = repair_ctl.take_trigger()
                if repair_request is None or preempt_signum is not None:
                    break
                # ---- the repair ladder (tpudist.resilience.repair) ----
                # the in-flight delayed-fetch step belongs to the
                # discarded trajectory: drop it before anything else
                pending = None
                device_probe = None
                t_rep = time.perf_counter()
                total_steps = epochs * steps_per_epoch
                action = repair_ctl.plan(
                    repair_request, global_step, max_step=total_steps
                )  # raises RepairExhausted when the budget is spent
                if action.kind == "restart":
                    # rung 3: repeat trigger inside the window just
                    # repaired — persist the directive and ask the
                    # supervisor for a fresh process (exit 77). No save
                    # of the current (suspect) state.
                    repair_ctl.record(action)
                    if tel is not None:
                        tel.set_repair(action.row())
                    repair_exit = action
                    break
                # rungs 1+2: roll back to the last-known-good anchor
                # and skip the offending window (the shared
                # apply_rollback: restore, residual flush, suspect-save
                # quarantine, cursor jump)
                state = apply_rollback(
                    state, action.rollback_step, action.skip_to
                )
                global_step = action.skip_to
                # repair-generation salt: rebuild the step so dropout
                # masks and stochastic-rounding draws REDRAW on the
                # replayed span — a spike caused by one unlucky draw
                # heals on the redraw alone. Skipped when no stochastic
                # consumer exists: the rebuild would retrace for a
                # bit-identical program.
                needs_salt = (
                    float(getattr(model, "dropout", 0.0) or 0.0) > 0
                    or (step.grad_reducer is not None
                        and step.grad_reducer.method == "quantized")
                )
                if needs_salt:
                    step = build_step(
                        repair_policy.salted_seed(seed, action.salt)
                    )
                    if step.grad_reducer is not None:
                        state = step.grad_reducer.attach_residual(state)
                repair_ctl.record(action)
                if chaos_inj is not None:
                    # deterministic-bug drills (@*) re-arm: a bug that
                    # survives a rollback must keep biting until the
                    # budget circuit-breaks
                    chaos_inj.rearm()
                if tel is not None:
                    # sentry baseline/cooldown and pending health
                    # gathers describe the discarded trajectory
                    tel.reset_for_repair()
                    tel.set_repair(action.row())
                if gp is not None:
                    gp.add_repair(
                        time.perf_counter() - t_rep, action.replay_s
                    )
                last_save_t = time.monotonic()
            except BaseException as crash_exc:
                # flush the last completed step before the exception leaves:
                # the loss history and TSV then end at the step that actually
                # finished, not one row short — but never mask the original
                # exception with a fetch failure (e.g. the device itself died)
                if tel is not None:
                    # BEFORE the resolve: its on_step must not fetch a
                    # pending health gather that may sit queued behind
                    # the very collective that hung
                    tel.mark_crashing()
                if pending is not None:
                    try:
                        resolve(time.time())
                    except Exception:
                        pass
                    pending = None
                if tel is not None:
                    # crash-path run report (tpudist.telemetry.health):
                    # status + everything observed so far; never raises
                    tel.on_crash(crash_exc)
                raise
            else:
                if pending is not None:
                    resolve(time.time())
                    pending = None
                if preempt_signum is not None:
                    # graceful preemption: durability FIRST (the grace
                    # window can expire any second — the emergency
                    # checkpoint is synchronous, wait=True), then the run
                    # report with exit_reason="preempted"
                    if ckpt is not None and global_step > start_step:
                        t_save = time.perf_counter()
                        ckpt.save(state, wait=True)
                        if gp is not None:
                            gp.add_emergency_save(
                                time.perf_counter() - t_save
                            )
                    if tel is not None:
                        tel.finish(state.opt_state, status="preempted")
                elif repair_exit is not None:
                    # rung-3 exit: the directive is durable, the current
                    # state is suspect — no save; the report records the
                    # escalation before exit 77
                    if tel is not None:
                        tel.finish(state.opt_state, status="repair_restart")
                elif tel is not None:
                    tel.finish(state.opt_state)
            if (ckpt and preempt_signum is None and repair_exit is None
                    and global_step > start_step):
                ckpt.save(state)
    finally:
        # closed here, OUTSIDE the logger's context: the logger's __exit__
        # mirrors its TrainTime footer into the sink (dual-sink mode), so
        # the sink must outlive it (shutdown also stops the hang-watchdog
        # thread before the sink goes away)
        guard.__exit__(None, None, None)
        if tel is not None:
            tel.shutdown()
        if ckpt:
            ckpt.close()
    if preempt_signum is not None:
        # everything durable (emergency checkpoint flushed, report
        # written, sink closed): hand the supervisor its exit code.
        # Preempted is a SystemExit(75) — scripts exit restartable with
        # no handler; library callers catch it for .state/.losses (the
        # checkpoint-less notebook run keeps its trained state)
        raise Preempted(preempt_signum, global_step,
                        state=state, losses=losses)
    if repair_exit is not None:
        # same discipline for the repair ladder's rung 3: directive and
        # report durable, exit with the restartable repair code (77) so
        # the supervisor relaunches and bring-up consumes the directive
        raise repair_mod.RepairRestart(repair_exit, global_step)
    return state, losses


def _padded_batches(loader, mesh: Mesh, key: str):
    """Yield ``(staged_batch, staged_row_mask, n_real_rows)`` with every
    batch padded (repeating the last row) to one constant row count and the
    padding masked — the one home for the ragged-final-batch math that both
    eval paths (:func:`evaluate`, :func:`evaluate_lm`) share.

    The pad target is the FIRST batch's row count (rounded up to the mesh's
    replica count), not merely the replica multiple: a ragged tail padded
    only to the replica count would present a new shape and trigger a fresh
    jit compile per distinct tail size per call — harmless locally, minutes
    per shape on a remote-compile attach. With a constant target the eval
    program compiles exactly once; the mask keeps the accounting exact.
    """
    dp = mesh_lib.data_parallel_size(mesh)
    target = None
    for batch in loader:
        # "_"-prefixed keys are per-step operands (e.g. the
        # DeviceCachedLoader's "_cache"), not row data: pass them through
        # to the compiled program untouched instead of fetching them to
        # host and "padding" them. Only the reserved prefix is exempt — a
        # foreign loader yielding jax.Arrays for ordinary row data keeps
        # the old np.asarray path.
        mesh_lib.check_reserved_device_keys(batch)
        passthrough = {
            k: v for k, v in batch.items() if k.startswith("_")
        }
        batch = {
            k: np.asarray(v)
            for k, v in batch.items()
            if k not in passthrough
        }
        n = batch[key].shape[0]
        if target is None:
            target = n + (-n % dp)
        # an oversize batch (foreign loader growing mid-stream) still pads to
        # its own replica multiple — one extra compile, never an error
        t = target if n <= target else n + (-n % dp)
        pad = t - n
        if pad:
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in batch.items()
            }
        mask = np.arange(t) < n
        batch = mesh_lib.shard_batch(batch, mesh)
        batch.update(passthrough)
        mask = mesh_lib.put_sharded(
            mask, mesh_lib.batch_sharding(mesh, extra_dims=0)
        )
        yield batch, mask, n


def evaluate_lm(
    model, state: TrainState, loader, mesh: Mesh | None = None,
    *, input_key: str = "tokens", chunk: int | None = None,
    input_transform: Callable | None = None,
) -> dict[str, float]:
    """Next-token CE and perplexity over a token-window loader — the LM
    counterpart of :func:`evaluate` (the reference's eval loop is
    classification-only and dormant, /root/reference/main.py:119-130).

    Scores EVERY window: a ragged final batch is padded to the mesh's
    replica count and masked out of both numerator and denominator.
    Multi-process accounting follows the global mask (see
    :func:`evaluate`), so per-process loaders may be identical full copies
    or disjoint shards — both score correctly, as long as every process
    yields the same number of batches (collectives run in lockstep).
    ``chunk`` scans the LM head over sequence chunks
    (:func:`tpudist.models.lm_utils.chunked_ce_sum`) so the [B,S,V] fp32
    logits never materialize — pass it whenever training needed
    ``chunked_lm_forward`` for the same reason, or eval will re-create the
    very HBM peak the training path avoided.
    ``input_transform`` mirrors :func:`make_train_step`'s hook (applied to
    the model INPUT only, never the CE targets) so a model trained through
    an in-graph transform evals through the same one.
    Returns ``{"loss": mean per-token CE, "perplexity": exp(loss)}``.
    """
    import math

    mesh = mesh or mesh_lib.create_mesh()

    if chunk:
        from tpudist.models.lm_utils import chunked_ce_sum, lm_head_weight

        @jax.jit
        def batch_ce(params, batch, mask):
            tokens = batch[input_key]
            inputs = _apply_input_transform(input_transform, tokens, batch)
            hidden = model.apply(
                {"params": params}, inputs, train=False, return_hidden=True
            )
            b, s = tokens.shape
            ce_sum = chunked_ce_sum(
                lm_head_weight(params), hidden[:, :-1], tokens[:, 1:],
                mask[:, None] * jnp.ones((b, s - 1)), chunk,
            )
            return ce_sum, jnp.sum(mask)
    else:

        @jax.jit
        def batch_ce(params, batch, mask):
            tokens = batch[input_key]
            inputs = _apply_input_transform(input_transform, tokens, batch)
            logits = model.apply({"params": params}, inputs, train=False)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tokens[:, 1:]
            )
            return jnp.sum(jnp.where(mask[:, None], ce, 0.0)), jnp.sum(mask)

    total, positions = 0.0, 0
    for batch, mask, _ in _padded_batches(loader, mesh, input_key):
        s = batch[input_key].shape[1]
        # windows counted from the global mask, in-graph — same
        # replicated-or-sharded-safe accounting as evaluate()
        ce_sum, windows = batch_ce(state.params, batch, mask)
        total += float(ce_sum)
        positions += int(windows) * (s - 1)
    loss = total / max(positions, 1)
    # no silent clamp: a diverged model reports its true (astronomical)
    # perplexity, or inf past float range — never a cap masquerading as a
    # measurement
    ppl = math.exp(loss) if loss < 700.0 else float("inf")
    return {"loss": loss, "perplexity": ppl}


def evaluate(model, state: TrainState, loader, mesh: Mesh | None = None,
             *, input_key: str = "image", label_key: str = "label",
             input_transform: Callable | None = None) -> float:
    """Top-1 accuracy over a loader — the reference's dormant eval pass
    (/root/reference/main.py:119-130), alive and tested here.

    Scores EVERY sample: a final batch that doesn't divide the mesh's
    replica count is padded (repeating the last row) and the padding is
    masked out of the correct-count, so no val tail is silently dropped.

    Multi-process: both the hit-count and the denominator are sums over the
    global mask inside the compiled program, so each process's loader may
    be an identical full copy of the val set (the reference's convention,
    /root/reference/main.py:56-63) or its own disjoint shard (e.g. via
    ``DistributedSampler``) — both produce the correct global accuracy.
    The one requirement is lockstep: every process must yield the same
    number of batches, which both conventions satisfy.
    """
    mesh = mesh or mesh_lib.create_mesh()

    @jax.jit
    def count_correct(params, batch_stats, batch, mask):
        variables = {"params": params, "batch_stats": batch_stats}
        # same in-graph hook as make_train_step: a model trained on
        # device_normalize'd uint8 would otherwise silently score raw
        # 0..255 inputs here (ADVICE r2)
        inputs = _apply_input_transform(input_transform, batch[input_key], batch)
        logits = model.apply(variables, inputs, train=False)
        hit = jnp.argmax(logits, axis=-1) == batch[label_key]
        # the denominator comes from the SAME global mask as the numerator,
        # in-graph: correct whether each process feeds an identical full val
        # loader (the reference's convention — every row counted
        # process_count times, in both sums) or its own disjoint shard. A
        # host-side `n × process_count` denominator would silently mis-scale
        # the sharded case.
        return jnp.sum(jnp.where(mask, hit, False)), jnp.sum(mask)

    cnt, total = 0, 0
    for batch, mask, _ in _padded_batches(loader, mesh, label_key):
        c, t = count_correct(state.params, state.batch_stats, batch, mask)
        cnt += int(c)
        total += int(t)
    return cnt / max(total, 1)
