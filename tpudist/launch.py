"""Process launcher — the ``torch.distributed.launch`` equivalent.

The reference is launched as ``python -m torch.distributed.launch
--nproc_per_node=N [--nnode --node_rank --master_addr --master_port]
main.py args...`` (/root/reference/README.md:12-35). This module preserves
that CLI shape:

    python -m tpudist.launch --nproc_per_node=N \
        [--nnode=M --node_rank=r --master_addr=A --master_port=P] \
        main.py --batch_size 128 --JobID Job0

and reproduces the launcher contract (SURVEY.md §2.2): it spawns
``nproc_per_node`` local processes, exports ``MASTER_ADDR``,
``MASTER_PORT``, ``RANK``, ``WORLD_SIZE``, ``LOCAL_RANK`` to each, and
injects ``--local_rank=i`` into argv — which ``tpudist.distributed
.init_from_env`` consumes the way ``dist.init_process_group('env://')``
does.

On TPU pods the natural topology is ONE process per host driving all local
chips (so ``--nproc_per_node`` defaults to 1 and ``--nnode/--node_rank``
describe hosts); ``--nproc_per_node>1`` exists for local CPU emulation of a
multi-process world (each process gets a disjoint slice of fake CPU devices
via ``--emulate-devices``).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpudist.launch", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    # flag names match torch.distributed.launch as used in README.md:14,28,34
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--nnode", "--nnodes", type=int, default=1, dest="nnode")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master_addr", type=str, default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument(
        "--emulate-devices", type=int, default=0,
        help="give each spawned process this many fake CPU devices "
        "(sets JAX_PLATFORMS=cpu + xla_force_host_platform_device_count); "
        "for TPU-less testing of the multi-process path",
    )
    p.add_argument("--no_python", action="store_true",
                   help="run the script as an executable instead of `python script`")
    p.add_argument(
        "--max_restarts", type=int, default=0,
        help="relaunch this node's processes up to N times after a non-zero "
        "exit — elastic-style recovery beyond the reference's fail-fast "
        "(SURVEY.md §5); pair with the trainer's --checkpoint_dir so the "
        "relaunched run resumes from the last checkpoint. 0 = fail fast.",
    )
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    attempt = 0
    # one handler for the launcher's whole life, closing over the CURRENT
    # generation's procs: a SIGTERM landing between generations (previous
    # world dead, next one mid-spawn) still sets the stop flag and
    # terminates whatever is alive, so the restart loop can never spawn or
    # keep a world past an operator stop
    stop = {"terminated": False, "procs": []}

    def _kill(signum, frame):
        stop["terminated"] = True
        for p in stop["procs"]:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGTERM, _kill)
    while True:
        rc = _run_world(args, stop)
        # never auto-restart over an operator stop: 130 = Ctrl-C, and a
        # SIGTERM delivered to the launcher itself (scheduler preemption /
        # supervisor shutdown) sets stop["terminated"] — the children's
        # resulting non-zero exits are launcher-initiated, not failures
        if rc == 0 or rc == 130 or stop["terminated"] or attempt >= args.max_restarts:
            return rc
        attempt += 1
        print(
            f"tpudist.launch: world exited rc={rc}; restarting "
            f"({attempt}/{args.max_restarts})",
            file=sys.stderr,
        )


def _run_world(args, stop: dict | None = None) -> int:
    """Spawn and supervise one generation of this node's processes."""
    if stop is None:
        stop = {"terminated": False, "procs": []}
    world_size = args.nnode * args.nproc_per_node
    procs: list[subprocess.Popen] = stop["procs"]
    procs.clear()
    for local_rank in range(args.nproc_per_node):
        if stop["terminated"]:
            break  # operator stop arrived mid-spawn; don't widen the world
        rank = args.node_rank * args.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(
            MASTER_ADDR=args.master_addr,
            MASTER_PORT=str(args.master_port),
            RANK=str(rank),
            WORLD_SIZE=str(world_size),
            LOCAL_RANK=str(local_rank),
        )
        if args.emulate_devices:
            env["JAX_PLATFORMS"] = "cpu"
            env["TPUDIST_FORCE_CPU"] = "1"
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={args.emulate_devices}"
            ).strip()
        cmd = [] if args.no_python else [sys.executable, "-u"]
        cmd = cmd + [args.script, f"--local_rank={local_rank}"] + args.script_args
        procs.append(subprocess.Popen(cmd, env=env))

    rc = 0
    try:
        # poll all children: the first non-zero exit terminates the rest so
        # a dead rank can't leave the world hung in a collective
        # (SURVEY.md §5 failure detection: static world, fail-fast)
        import time as _time

        live = list(procs)
        while live:
            if stop["terminated"]:
                # operator stop may have raced a mid-Popen child past the
                # handler's terminate sweep; re-sweep here so no child
                # outlives the stop
                for q in live:
                    if q.poll() is None:
                        q.terminate()
            for p in list(live):
                code = p.poll()
                if code is None:
                    continue
                live.remove(p)
                if code != 0 and rc == 0:
                    rc = code
                    for q in live:
                        q.terminate()
            if live:
                _time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            p.wait()
        rc = 130
    return rc


if __name__ == "__main__":
    sys.exit(main())
