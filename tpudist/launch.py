"""Process launcher — the ``torch.distributed.launch`` equivalent.

The reference is launched as ``python -m torch.distributed.launch
--nproc_per_node=N [--nnode --node_rank --master_addr --master_port]
main.py args...`` (/root/reference/README.md:12-35). This module preserves
that CLI shape:

    python -m tpudist.launch --nproc_per_node=N \
        [--nnode=M --node_rank=r --master_addr=A --master_port=P] \
        main.py --batch_size 128 --JobID Job0

and reproduces the launcher contract (SURVEY.md §2.2): it spawns
``nproc_per_node`` local processes, exports ``MASTER_ADDR``,
``MASTER_PORT``, ``RANK``, ``WORLD_SIZE``, ``LOCAL_RANK`` to each, and
injects ``--local_rank=i`` into argv — which ``tpudist.distributed
.init_from_env`` consumes the way ``dist.init_process_group('env://')``
does.

On TPU pods the natural topology is ONE process per host driving all local
chips (so ``--nproc_per_node`` defaults to 1 and ``--nnode/--node_rank``
describe hosts); ``--nproc_per_node>1`` exists for local CPU emulation of a
multi-process world (each process gets a disjoint slice of fake CPU devices
via ``--emulate-devices``).

Beyond the reference's fail-fast, the launcher is a SUPERVISOR
(``tpudist.resilience.supervisor``): exit codes 75 (preempted) / 76
(watchdog hang) / 77 (repair-restart) mean the trainer persisted its
state and asked to be
relaunched — those restart promptly regardless of ``--max_restarts``,
bounded by the ``--restart_budget``/``--restart_window`` rolling window;
any other non-zero exit is a crash, restarted only within
``--max_restarts`` attempts with exponential backoff + jitter. Every
generation gets ``TPUDIST_RESTART_GENERATION`` exported so telemetry is
attributable across the lives of the job. The preemption recipe:
docs/MULTIHOST.md "Surviving preemption".
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpudist.launch", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    # flag names match torch.distributed.launch as used in README.md:14,28,34
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--nnode", "--nnodes", type=int, default=1, dest="nnode")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master_addr", type=str, default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument(
        "--emulate-devices", type=str, default="0",
        help="give each spawned process this many fake CPU devices "
        "(sets JAX_PLATFORMS=cpu + xla_force_host_platform_device_count); "
        "for TPU-less testing of the multi-process path. A comma list "
        "gives one value PER RESTART GENERATION ('8,4': the first world "
        "gets 8 devices, every relaunch gets 4) — the emulated form of "
        "an elastic resize, where the relaunched world comes up on "
        "whatever hardware is left and the trainer reshards via "
        "fit(elastic=True) (docs/MULTIHOST.md)",
    )
    p.add_argument("--no_python", action="store_true",
                   help="run the script as an executable instead of `python script`")
    p.add_argument(
        "--max_restarts", type=int, default=0,
        help="relaunch this node's processes up to N times after a CRASH "
        "(any non-zero exit other than the restartable codes 75/76/77) — "
        "elastic-style recovery beyond the reference's fail-fast "
        "(SURVEY.md §5); pair with the trainer's --checkpoint_dir so the "
        "relaunched run resumes from the last checkpoint. 0 = fail fast "
        "on crashes. Restartable exits (preempted=75, watchdog hang=76, repair-restart=77) "
        "restart regardless, bounded only by the restart budget.",
    )
    p.add_argument(
        "--restart_budget", type=int, default=10,
        help="circuit breaker: at most N restarts (of any kind) per "
        "--restart_window seconds, then give up with the world's exit "
        "code — a deterministically-crashing or instantly-re-preempted "
        "job exhausts its budget instead of spinning. 0 = unlimited.",
    )
    p.add_argument(
        "--restart_window", type=float, default=600.0,
        help="the rolling window (seconds) the restart budget counts in",
    )
    p.add_argument(
        "--backoff_base", type=float, default=1.0,
        help="first crash-restart delay (seconds); doubles per consecutive "
        "crash up to --backoff_max, with ±50%% jitter so a fleet of "
        "launchers never stampedes the rendezvous port in lockstep. "
        "Restartable exits (75/76/77) relaunch without backoff.",
    )
    p.add_argument(
        "--backoff_max", type=float, default=60.0,
        help="crash-restart backoff ceiling (seconds, pre-jitter)",
    )
    p.add_argument(
        "--term_grace", type=float, default=30.0,
        help="seconds to wait for a terminated child to exit before "
        "SIGKILL. Also the voluntary-exit window granted to siblings when "
        "a rank exits with a restartable code: they likely received the "
        "same preemption signal and are mid-emergency-checkpoint — a "
        "SIGTERM now would escalate past their graceful handler. Raise "
        "it for models whose emergency save takes longer.",
    )
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _emulated_devices(args, generation: int) -> int:
    """The fake-CPU device count generation ``generation`` gets: the
    launcher re-probes the device world at every relaunch — on real
    hardware the relaunched process re-enumerates its own attach, and
    under emulation the per-generation ``--emulate-devices`` list plays
    the part of hardware that shrank (or returned)."""
    values = [int(v) for v in str(args.emulate_devices).split(",") if v != ""]
    if not values:
        return 0
    return values[min(generation, len(values) - 1)]


def main(argv: list[str] | None = None) -> int:
    from tpudist.resilience.exitcodes import ensure_run_id
    from tpudist.resilience.supervisor import (
        BackoffPolicy, RestartBudget, Supervisor,
    )

    args = build_parser().parse_args(argv)
    # one stable run id for the job's whole life: minted here (or inherited
    # from an outer launcher), exported via the environment every child —
    # all ranks, all restart generations — is spawned with, so telemetry
    # rows from one logical job stitch without filename heuristics
    ensure_run_id(os.environ)
    # one handler for the launcher's whole life, closing over the CURRENT
    # generation's procs: a SIGTERM landing between generations (previous
    # world dead, next one mid-spawn) still sets the stop flag and
    # terminates whatever is alive, so the restart loop can never spawn or
    # keep a world past an operator stop. The children's SIGTERM is their
    # graceful-preemption trigger (tpudist.resilience.preempt) — they get
    # --term_grace to write their emergency checkpoints before any KILL.
    stop = {"terminated": False, "procs": []}

    def _kill(signum, frame):
        stop["terminated"] = True
        for p in stop["procs"]:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGTERM, _kill)
    sup = Supervisor(
        lambda generation: _run_world(args, stop, generation=generation),
        max_restarts=args.max_restarts,
        budget=RestartBudget(args.restart_budget, args.restart_window),
        backoff=BackoffPolicy(args.backoff_base, args.backoff_max),
        stop=lambda: stop["terminated"],
    )
    return sup.run()


def _drain_world(procs: list[subprocess.Popen], grace_s: float, *,
                 voluntary_s: float = 0.0) -> None:
    """Reap EVERY child before returning — the launcher must never hand
    the next restart generation a world whose predecessors still hold
    ``MASTER_PORT`` or the checkpoint-dir locks (a terminated child is
    not a dead child until ``wait()`` says so).

    ``voluntary_s`` first waits that long for children to exit on their
    own with NO signal sent: a preempted world's siblings received the
    same SIGTERM the exiting rank did and are mid-emergency-checkpoint —
    terminating them now would escalate past their graceful handler and
    lose exactly the state the preemption path exists to save. Then the
    sweep: SIGTERM, up to ``grace_s`` to finish, SIGKILL stragglers, and
    an unconditional ``wait()`` on every child.
    """
    if voluntary_s > 0:
        deadline = time.monotonic() + voluntary_s
        while (any(p.poll() is None for p in procs)
               and time.monotonic() < deadline):
            time.sleep(0.05)
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + max(grace_s, 0.0)
    while (any(p.poll() is None for p in procs)
           and time.monotonic() < deadline):
        time.sleep(0.05)
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        p.wait()


def _run_world(args, stop: dict | None = None, generation: int = 0) -> int:
    """Spawn, supervise, and fully REAP one generation of this node's
    processes (every exit path drains the world — no child outlives the
    return)."""
    from tpudist.resilience.exitcodes import GENERATION_ENV, is_restartable

    if stop is None:
        stop = {"terminated": False, "procs": []}
    world_size = args.nnode * args.nproc_per_node
    procs: list[subprocess.Popen] = stop["procs"]
    procs.clear()
    for local_rank in range(args.nproc_per_node):
        if stop["terminated"]:
            break  # operator stop arrived mid-spawn; don't widen the world
        rank = args.node_rank * args.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(
            MASTER_ADDR=args.master_addr,
            MASTER_PORT=str(args.master_port),
            RANK=str(rank),
            WORLD_SIZE=str(world_size),
            LOCAL_RANK=str(local_rank),
        )
        # which life of the job this is: telemetry stamps heartbeats and
        # the run report with it, goodput aggregates across it
        env[GENERATION_ENV] = str(generation)
        emulate = _emulated_devices(args, generation)
        if emulate:
            env["JAX_PLATFORMS"] = "cpu"
            env["TPUDIST_FORCE_CPU"] = "1"
            # the re-probed world, exported so tooling can tell what this
            # generation was granted without parsing XLA flags
            env["TPUDIST_WORLD_DEVICES"] = str(emulate)
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={emulate}"
            ).strip()
        cmd = [] if args.no_python else [sys.executable, "-u"]
        cmd = cmd + [args.script, f"--local_rank={local_rank}"] + args.script_args
        procs.append(subprocess.Popen(cmd, env=env))

    rc = 0
    try:
        # poll all children: the first non-zero exit drains the rest so a
        # dead rank can't leave the world hung in a collective (SURVEY.md
        # §5 failure detection: static world, fail-fast) — and the drain
        # WAITS on every terminated child, so the next restart generation
        # can never race still-dying processes for MASTER_PORT or the
        # checkpoint-dir locks
        live = list(procs)
        while live:
            if stop["terminated"]:
                # operator stop: the signal handler already SIGTERM'd the
                # world (the children's graceful trigger); grant the grace
                # window before the kill sweep, and reap everything
                _drain_world(procs, args.term_grace,
                             voluntary_s=args.term_grace)
                for p in procs:
                    if p.returncode and rc == 0:
                        rc = p.returncode
                return rc
            for p in list(live):
                code = p.poll()
                if code is None:
                    continue
                live.remove(p)
                if code != 0 and rc == 0:
                    rc = code
            if rc != 0 and live:
                # restartable exit: the siblings most likely trapped the
                # same preemption signal and are writing their own
                # emergency checkpoints — give them the voluntary window
                # before any terminate. A crash exit keeps fail-fast:
                # terminate immediately (grace, then kill).
                _drain_world(
                    live, args.term_grace,
                    voluntary_s=args.term_grace if is_restartable(rc) else 0.0,
                )
                live = []
            if live:
                time.sleep(0.2)
    except KeyboardInterrupt:
        _drain_world(procs, args.term_grace)
        rc = 130
    return rc


if __name__ == "__main__":
    sys.exit(main())
