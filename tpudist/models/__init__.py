"""Model zoo: the reference's model (ResNet-50, /root/reference/main.py:40)
plus the BASELINE.json ladder (ResNet-18, ViT-B/16, GPT-2 124M), depth
variants (ResNet-34/101/152), the Llama decoder family (RoPE/GQA/SwiGLU),
the BERT encoder family (bidirectional + masked-LM objective), and the T5
encoder-decoder family (relative-position-bias attention + span
corruption)."""

from tpudist.models.resnet import (
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
)
from tpudist.models.vit import ViT, vit_b16
from tpudist.models.gpt2 import GPT2, gpt2_124m, gpt2_medium, gpt2_large
from tpudist.models.llama import (
    Llama, llama_125m, llama2_7b, llama3_8b, mixtral_8x7b,
)
from tpudist.models.bert import (
    Bert, BertClassifier, bert_base, bert_large, classifier_params_from_mlm,
)
from tpudist.models.t5 import T5, t5_small

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "ViT", "vit_b16", "GPT2", "gpt2_124m", "gpt2_medium", "gpt2_large",
    "Llama", "llama_125m", "llama2_7b", "llama3_8b", "mixtral_8x7b",
    "Bert", "BertClassifier", "bert_base", "bert_large",
    "classifier_params_from_mlm", "T5", "t5_small",
]
