"""ViT-B/16 in Flax — BASELINE.json config 4 (ViT-B/16, DP + bfloat16).

No reference counterpart exists (the reference is ResNet-only,
/root/reference/main.py:40); this covers the "transformer grads over ICI"
target. TPU-first: bf16 activations with fp32 params, patchify as a single
strided conv (one big MXU matmul), attention via tpudist.ops. Encoder
kernels carry the same Megatron ``tensor``-axis partitioning metadata as
GPT-2 (qkv/mlp-in column-parallel, out/mlp-out row-parallel) — inert on a
``tensor=1`` mesh, GSPMD-sharded otherwise.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from tpudist.mesh import TENSOR_AXIS
from tpudist.ops.attention import multi_head_attention
from tpudist.parallel.tp import partitioned as _partitioned


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        dense_init = nn.initializers.lecun_normal()
        x = nn.Dense(
            self.mlp_dim, dtype=self.dtype,
            kernel_init=_partitioned(dense_init, None, TENSOR_AXIS),
            bias_init=_partitioned(nn.initializers.zeros_init(), TENSOR_AXIS),
        )(x)
        x = nn.gelu(x)
        return nn.Dense(
            d, dtype=self.dtype,
            kernel_init=_partitioned(dense_init, TENSOR_AXIS, None),
        )(x)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    dropout: float = 0.0
    mesh: Any = None  # multi-chip Pallas attention (shard_map wrap)
    # fused_ln=True: both pre-LNs run the Pallas fused residual-add+LN
    # kernel (tpudist.ops.layernorm) under the flax auto-names
    # ("LayerNorm_0"/"LayerNorm_1"), so the param tree is unchanged
    fused_ln: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, s, d = x.shape
        h = self.num_heads
        drop = lambda y: (
            nn.Dropout(self.dropout, deterministic=not train)(y)
            if self.dropout else y
        )
        dense_init = nn.initializers.lecun_normal()
        if self.fused_ln:
            from tpudist.ops.layernorm import FusedLayerNorm

            # explicit names pin the flax auto-numbering the unfused
            # modules would have received
            ln = lambda name: FusedLayerNorm(
                epsilon=1e-6, dtype=self.dtype, mesh=self.mesh, name=name
            )
        else:
            ln = lambda name: nn.LayerNorm(dtype=self.dtype, name=name)
        y = ln("LayerNorm_0")(x)
        qkv = nn.DenseGeneral(
            (3, h, d // h), dtype=self.dtype, name="qkv",
            kernel_init=_partitioned(dense_init, None, None, TENSOR_AXIS, None),
            bias_init=_partitioned(nn.initializers.zeros_init(), None, TENSOR_AXIS, None),
        )(y)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = multi_head_attention(q, k, v, impl=self.attn_impl,
                                    mesh=self.mesh)
        y = nn.DenseGeneral(
            d, axis=(-2, -1), dtype=self.dtype, name="out",
            kernel_init=_partitioned(dense_init, TENSOR_AXIS, None, None),
        )(attn)
        if self.fused_ln:
            # residual add + LN in one kernel sweep (pre-norm composition)
            y, x = ln("LayerNorm_1")(drop(y), residual=x)
        else:
            x = x + drop(y)
            y = ln("LayerNorm_1")(x)
        return x + drop(MlpBlock(self.mlp_dim, dtype=self.dtype)(y))


class ViT(nn.Module):
    num_classes: int = 1000
    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    dropout: float = 0.0  # residual dropout; rng plumbed by tpudist.train
    mesh: Any = None  # multi-chip Pallas attention (shard_map wrap)
    # fused_ln=True: every encoder LN + the final LN run the Pallas fused
    # residual-add+LN kernel (tpudist.ops.layernorm); param tree unchanged.
    # Usually set via make_train_step(fused="ln"|"all") / main.py --fused.
    fused_ln: bool = False

    @property
    def flops_counter(self) -> str | None:
        """Analytic-FLOPs family tag (tpudist.telemetry.flops). The vit
        counter assumes the standard 4·H MLP; a custom mlp_dim gets no
        tag (no MFU row) rather than a wrong numerator."""
        return "vit" if self.mlp_dim == 4 * self.hidden_dim else None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        p = self.patch_size
        x = nn.Conv(
            self.hidden_dim, (p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, name="embedding",
        )(x)
        b, gh, gw, d = x.shape
        x = x.reshape(b, gh * gw, d)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, d), jnp.float32)
        x = jnp.concatenate([jnp.tile(cls.astype(self.dtype), (b, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embedding", nn.initializers.normal(0.02), (1, x.shape[1], d), jnp.float32
        )
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = EncoderBlock(
                self.num_heads, self.mlp_dim, dtype=self.dtype,
                attn_impl=self.attn_impl, dropout=self.dropout,
                mesh=self.mesh, fused_ln=self.fused_ln, name=f"block_{i}",
            )(x, train=train)
        if self.fused_ln:
            from tpudist.ops.layernorm import FusedLayerNorm

            x = FusedLayerNorm(
                epsilon=1e-6, dtype=self.dtype, mesh=self.mesh,
                name="LayerNorm_0",
            )(x)
        else:
            x = nn.LayerNorm(dtype=self.dtype, name="LayerNorm_0")(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x[:, 0])


def vit_b16(**kw) -> ViT:
    return ViT(**kw)
