"""BERT-style bidirectional encoder with a masked-LM objective.

No reference counterpart (the reference is a single ResNet DDP script,
SURVEY.md §2.12); built as a capability extension: the encoder complement
of the GPT-2/Llama decoder families, sharing the framework's contracts —
the same Megatron TP metadata scheme over the ``tensor`` axis
(``tpudist.parallel.tp``), the same attention ops (``tpudist.ops``), the
``return_hidden`` hook, and the ``forward_loss`` train-step interface
(:func:`mlm_forward` plugs into ``make_train_step`` exactly like
``chunked_lm_forward``).

Architecture follows BERT-base conventions: learned token+position (+
segment) embeddings with post-embedding LayerNorm, post-LN transformer
blocks with bidirectional attention and GELU MLPs, and a weight-tied MLM
head behind BERT's dense+LN "transform".

The MLM corruption runs host-side as a loader ``transform``
(:func:`mlm_transform`) with the standard 80/10/10 recipe — integer ops on
the host keep the device step static-shaped, and the transform slots into
the existing DataLoader/TokenWindowLoader pipeline like any augmentation.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from tpudist.mesh import TENSOR_AXIS
from tpudist.ops.attention import multi_head_attention
from tpudist.parallel.tp import partitioned as _partitioned


class EncoderBlock(nn.Module):
    """Post-LN bidirectional transformer block (BERT convention: the
    residual sum is normalized, rather than the branch input)."""

    num_heads: int
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    dropout: float = 0.0
    mesh: Any = None
    # fused_ln=True: both post-LNs run the Pallas fused residual-add+LN
    # kernel (tpudist.ops.layernorm) — the post-norm composition is the
    # ideal fusion target (the sum never needs a separate HBM round trip;
    # only the normed value is written). Same param names as nn.LayerNorm.
    fused_ln: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True, attention_mask=None):
        b, s, d = x.shape
        h = self.num_heads
        drop = lambda y: (
            nn.Dropout(self.dropout, deterministic=not train)(y)
            if self.dropout else y
        )
        if self.fused_ln:
            from tpudist.ops.layernorm import FusedLayerNorm

            post_ln = lambda name, res, y: FusedLayerNorm(
                epsilon=1e-12, dtype=self.dtype, mesh=self.mesh, name=name
            )(y, residual=res, return_residual=False)
        else:
            post_ln = lambda name, res, y: nn.LayerNorm(
                epsilon=1e-12, dtype=self.dtype, name=name
            )(res + y)
        dense_init = nn.initializers.lecun_normal()
        # column-parallel qkv / row-parallel out — same TP scheme as the
        # decoder Block (tpudist/models/gpt2.py), no causal mask
        qkv = nn.DenseGeneral(
            (3, h, d // h), dtype=self.dtype, name="qkv",
            kernel_init=_partitioned(dense_init, None, None, TENSOR_AXIS, None),
            bias_init=_partitioned(
                nn.initializers.zeros_init(), None, TENSOR_AXIS, None
            ),
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.attn_impl in ("ring", "ulysses", "ulysses_flash"):
            # context-parallel bidirectional attention over the 'seq' mesh
            # axis (tpudist.parallel.cp, causal=False) — long-document
            # encoder training with sequence-sharded activations
            if attention_mask is not None:
                raise ValueError(
                    f"attention_mask is not supported with attn_impl="
                    f"{self.attn_impl!r} (the context-parallel paths assume "
                    "dense fixed-length windows); pad-free batches or the "
                    "xla/flash impls"
                )
            if self.mesh is None:
                raise ValueError(
                    f"attn_impl={self.attn_impl!r} needs the model's mesh= "
                    "field set (the shard_map runs over its 'seq' axis)"
                )
            from tpudist.parallel.cp import ring_attention, ulysses_attention

            if self.attn_impl == "ring":
                attn = ring_attention(q, k, v, self.mesh, causal=False)
            else:
                attn_fn = None
                if self.attn_impl == "ulysses_flash":
                    from tpudist.ops.attention import kernel_attention

                    attn_fn = kernel_attention
                attn = ulysses_attention(
                    q, k, v, self.mesh, causal=False, attn_fn=attn_fn
                )
        else:
            # [b, s] key-padding mask (1 = real token) → broadcast over
            # heads and query positions: padded KEYS are excluded from every
            # softmax; padded query rows produce garbage that downstream
            # consumers never read (BERT reads [CLS] / masked positions only)
            key_mask = (
                None if attention_mask is None
                else attention_mask[:, None, None, :].astype(bool)
            )
            attn = multi_head_attention(
                q, k, v, causal=False, mask=key_mask, impl=self.attn_impl,
                mesh=self.mesh,
            )
        y = nn.DenseGeneral(
            d, axis=(-2, -1), dtype=self.dtype, name="out",
            kernel_init=_partitioned(dense_init, TENSOR_AXIS, None, None),
        )(attn)
        x = post_ln("ln_attn", x, drop(y))
        y = nn.Dense(
            4 * d, dtype=self.dtype, name="mlp_fc",
            kernel_init=_partitioned(dense_init, None, TENSOR_AXIS),
            bias_init=_partitioned(nn.initializers.zeros_init(), TENSOR_AXIS),
        )(x)
        # exact (erf) GELU — BERT's convention, and what HF BertForMaskedLM
        # computes; the tanh approximation is GPT-2's flavor
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(
            d, dtype=self.dtype, name="mlp_proj",
            kernel_init=_partitioned(dense_init, TENSOR_AXIS, None),
        )(y)
        return post_ln("ln_mlp", x, drop(y))


class MlmHead(nn.Module):
    """BERT's MLM head: transform (dense + gelu + LN) then the weight-tied
    decode against the embedding table with a free output bias. A submodule
    (its own param scope) so :func:`mlm_forward`'s chunked path can apply it
    per sequence chunk without duplicating the math."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, wte):
        d = wte.shape[1]
        y = nn.Dense(d, dtype=self.dtype, name="transform")(x)
        y = nn.gelu(y, approximate=False)  # erf GELU, the BERT convention
        y = nn.LayerNorm(epsilon=1e-12, dtype=self.dtype, name="ln")(y)
        logits = jnp.einsum(
            "...d,vd->...v", y, wte.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (wte.shape[0],), jnp.float32
        )
        return logits + bias


class _CarryEncoderBlock(nn.Module):
    """:class:`EncoderBlock` with the (carry, xs) → (carry, ys) signature
    ``nn.scan`` maps over (``train`` rides as a field)."""

    num_heads: int
    train: bool = True
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    mesh: Any = None
    dropout: float = 0.0
    fused_ln: bool = False

    @nn.compact
    def __call__(self, x, attention_mask):
        x = EncoderBlock(
            self.num_heads, dtype=self.dtype, attn_impl=self.attn_impl,
            mesh=self.mesh, dropout=self.dropout, fused_ln=self.fused_ln,
            name="block",
        )(x, train=self.train, attention_mask=attention_mask)
        return x, None


class Bert(nn.Module):
    vocab_size: int = 30522
    max_seq_len: int = 512
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    type_vocab: int = 2
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    dropout: float = 0.0
    mesh: Any = None
    # scan_layers/remat_layers: nn.scan'd depth with optional per-layer
    # checkpointing — same fields and semantics as the decoder families
    # (one traced layer at any depth; params stack [depth, ...])
    scan_layers: bool = False
    remat_layers: bool = False
    # fused_ln=True: the embedding LN and every block's post-LNs run the
    # Pallas fused residual-add+LN kernel (tpudist.ops.layernorm). Same
    # param tree; usually set via make_train_step(fused="ln"|"all").
    fused_ln: bool = False

    @property
    def flops_counter(self) -> str:
        """Analytic-FLOPs family tag (tpudist.telemetry.flops): encoder
        blocks + the MLM head's transform and tied projection."""
        return "bert"

    @nn.compact
    def __call__(self, tokens, train: bool = True, return_hidden: bool = False,
                 token_types=None, attention_mask=None):
        b, s = tokens.shape
        if s > self.max_seq_len:
            raise ValueError(
                f"sequence {s} exceeds max_seq_len {self.max_seq_len}"
            )
        wte = self.param(
            "wte",
            _partitioned(nn.initializers.normal(0.02), TENSOR_AXIS, None),
            (self.vocab_size, self.hidden_dim), jnp.float32,
        )
        wpe = self.param(
            "wpe", nn.initializers.normal(0.02),
            (self.max_seq_len, self.hidden_dim), jnp.float32,
        )
        x = wte[tokens] + wpe[:s]
        if self.type_vocab:
            wty = self.param(
                "wty", nn.initializers.normal(0.02),
                (self.type_vocab, self.hidden_dim), jnp.float32,
            )
            types = (
                jnp.zeros_like(tokens) if token_types is None else token_types
            )
            x = x + wty[types]
        if self.fused_ln:
            from tpudist.ops.layernorm import FusedLayerNorm

            x = FusedLayerNorm(
                epsilon=1e-12, dtype=self.dtype, mesh=self.mesh,
                name="ln_embed",
            )(x.astype(self.dtype))
        else:
            x = nn.LayerNorm(
                epsilon=1e-12, dtype=self.dtype, name="ln_embed"
            )(x.astype(self.dtype))
        if self.dropout:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        if self.scan_layers:
            body = (
                nn.remat(_CarryEncoderBlock)
                if self.remat_layers else _CarryEncoderBlock
            )
            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=self.depth,
                # the padding mask is layer-invariant: broadcast, not mapped
                in_axes=nn.broadcast,
                # stacked depth axis carries no partition name (unsharded);
                # per-layer TENSOR_AXIS metadata shifts right intact
                metadata_params={nn.PARTITION_NAME: None},
            )(
                num_heads=self.num_heads, train=train, dtype=self.dtype,
                attn_impl=self.attn_impl, mesh=self.mesh,
                dropout=self.dropout, fused_ln=self.fused_ln, name="hs",
            )
            x, _ = scanned(x, attention_mask)
        elif self.remat_layers:
            raise ValueError("remat_layers requires scan_layers=True "
                             "(use make_train_step(remat=True) to checkpoint "
                             "an unrolled forward)")
        else:
            for i in range(self.depth):
                x = EncoderBlock(
                    self.num_heads, dtype=self.dtype,
                    attn_impl=self.attn_impl, mesh=self.mesh,
                    dropout=self.dropout, fused_ln=self.fused_ln,
                    name=f"h_{i}",
                )(x, train=train, attention_mask=attention_mask)
        if return_hidden:
            return x
        return MlmHead(dtype=self.dtype, name="mlm_head")(x, wte)


class BertClassifier(nn.Module):
    """Sequence classification on the encoder — the fine-tuning surface.

    BERT's recipe: the first token's hidden state through the tanh pooler,
    then a ``num_labels`` head. The encoder lives under the ``bert`` param
    scope so :func:`classifier_params_from_mlm` can graft pretrained
    weights (from :class:`Bert` MLM pretraining or an HF import) leaf-for-
    leaf into a fresh classifier tree.
    """

    num_labels: int
    vocab_size: int = 30522
    max_seq_len: int = 512
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    type_vocab: int = 2
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    dropout: float = 0.0

    @nn.compact
    def __call__(self, tokens, train: bool = True, token_types=None,
                 attention_mask=None):
        # attention_mask ([b, s], 1 = real token): padded variable-length
        # classification batches must pass it, or pad tokens join every
        # softmax (HF BERT semantics require the mask — ADVICE r2)
        hidden = Bert(
            vocab_size=self.vocab_size, max_seq_len=self.max_seq_len,
            hidden_dim=self.hidden_dim, depth=self.depth,
            num_heads=self.num_heads, type_vocab=self.type_vocab,
            dtype=self.dtype, attn_impl=self.attn_impl,
            dropout=self.dropout, name="bert",
        )(tokens, train=train, return_hidden=True, token_types=token_types,
          attention_mask=attention_mask)
        pooled = jnp.tanh(
            nn.Dense(self.hidden_dim, dtype=self.dtype, name="pooler")(
                hidden[:, 0]
            )
        )
        if self.dropout:
            pooled = nn.Dropout(self.dropout, deterministic=not train)(pooled)
        # fp32 head: classification logits are cheap and the loss is
        # precision-sensitive
        return nn.Dense(self.num_labels, dtype=jnp.float32, name="classifier")(
            pooled
        )


def classifier_params_from_mlm(classifier_params, pretrained):
    """Graft a pretrained encoder (MLM params, tpudist or HF-imported) into
    a freshly-initialized :class:`BertClassifier` tree: every encoder leaf
    is replaced, the pooler/classifier head keeps its fresh init (HF's
    fine-tuning convention). ``mlm_head`` is dropped."""
    import jax

    encoder = {k: v for k, v in pretrained.items() if k != "mlm_head"}
    out = dict(classifier_params)
    # leaf-for-leaf replacement with a structure check: a geometry mismatch
    # fails loudly instead of training a half-grafted model
    out["bert"] = jax.tree_util.tree_map(
        lambda fresh, pre: pre.astype(fresh.dtype)
        if hasattr(pre, "astype") else pre,
        dict(classifier_params["bert"]), encoder,
    )
    return out


def bert_base(**kw) -> Bert:
    return Bert(**kw)


def bert_large(**kw) -> Bert:
    kw.setdefault("hidden_dim", 1024)
    kw.setdefault("depth", 24)
    kw.setdefault("num_heads", 16)
    return Bert(**kw)


def mlm_transform(
    vocab_size: int, mask_id: int, *, mask_rate: float = 0.15,
    random_rate: float = 0.1, keep_rate: float = 0.1, seed: int = 0,
    key: str = "tokens",
):
    """Loader transform applying BERT's MLM corruption on the host.

    Each position is selected with probability ``mask_rate``; of the
    selected, 80% become ``mask_id``, 10% a uniformly random id, 10% stay
    unchanged (the 80/10/10 recipe — ``random_rate``/``keep_rate`` are
    fractions OF the selected positions). Produces
    ``{"tokens": corrupted, "targets": originals, "mlm_mask": bool}``.
    Randomness is a seeded per-loader stream, like the augmentation
    transforms (tpudist/data/transforms.py) — deterministic order, not
    replayed across a mid-epoch resume.
    """
    rng = np.random.Generator(np.random.PCG64(seed))

    def run(batch):
        tokens = np.asarray(batch[key])
        u = rng.random(tokens.shape)
        selected = u < mask_rate
        # carve the selected mass into mask/random/keep sub-ranges of u
        to_random = selected & (u < mask_rate * random_rate)
        to_keep = selected & (u >= mask_rate * (1.0 - keep_rate))
        to_mask = selected & ~to_random & ~to_keep
        corrupted = tokens.copy()
        corrupted[to_mask] = mask_id
        # draw "random token" from the vocab EXCLUDING mask_id: draw over
        # vocab_size-1 ids and shift the ones at/above mask_id up by one, so
        # [MASK] can never appear as a target-bearing random id (ADVICE r2)
        draw = rng.integers(0, vocab_size - 1, int(to_random.sum()))
        corrupted[to_random] = draw + (draw >= mask_id)
        out = dict(batch)
        out[key] = corrupted
        out["targets"] = tokens
        out["mlm_mask"] = selected
        return out

    return run


def mlm_forward(model: Bert, chunk: int | None = None):
    """``forward_loss`` for :func:`tpudist.train.make_train_step`: mean CE
    over the corrupted positions only — the MLM objective. Expects batches
    from :func:`mlm_transform` (``tokens``/``targets``/``mlm_mask``).

    ``chunk`` scans the MLM head over sequence chunks with a checkpointed
    body, bounding live logits to [B, chunk, V] in forward AND backward —
    the same HBM discipline as ``chunked_lm_forward`` (at bert-base shapes,
    batch 32 × seq 512 × V=30522 fp32 logits are ~2 GB otherwise). The
    chunk path rides the shared :func:`~tpudist.models.lm_utils.
    chunked_head_reduce` skeleton with :func:`mlm_head_logits_fn`.
    """
    import optax

    from tpudist.models.lm_utils import chunked_head_reduce

    if getattr(model, "dropout", 0.0):
        raise ValueError(
            "mlm_forward has no rng stream; use dropout=0 (match "
            "chunked_lm_forward's contract) or extend the default forward"
        )

    head = MlmHead(dtype=model.dtype)

    def masked_ce_sum(logits, targets, mask):
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return jnp.sum(ce * mask)

    def forward_loss(params, batch_stats, batch):
        mask = batch["mlm_mask"].astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        if chunk is None:
            logits = model.apply(
                {"params": params}, batch["tokens"], train=True
            )
            loss = masked_ce_sum(logits, batch["targets"], mask) / denom
            return loss, batch_stats

        hidden = model.apply(
            {"params": params}, batch["tokens"], train=True,
            return_hidden=True,
        )
        total = chunked_head_reduce(
            mlm_head_logits_fn(head, params), hidden, batch["targets"],
            mask, chunk,
        )
        return total / denom, batch_stats

    return forward_loss


def mlm_head_logits_fn(head: MlmHead, params):
    """``logits_fn`` for ``chunked_head_reduce``: BERT's transform + tied
    decode, applied per hidden chunk through the :class:`MlmHead` module
    (no duplicated head math)."""
    wte = nn.meta.unbox(params["wte"])
    head_params = {"params": nn.meta.unbox(params["mlm_head"])}
    return lambda hc: head.apply(head_params, hc, wte)
