"""T5-style encoder-decoder with a span-corruption objective.

No reference counterpart (the reference is a single ResNet DDP script,
SURVEY.md §2.12); built as a capability extension completing the
framework's architecture classes: decoders (GPT-2/Llama), encoder (BERT),
vision (ResNet/ViT) — and now the encoder-decoder. Shares the framework
contracts: Megatron TP metadata over the ``tensor`` axis on qkv/out/MLP
kernels, the ``forward_loss`` train-step interface
(:func:`seq2seq_forward` plugs into ``make_train_step`` like
``mlm_forward``), and a host-side loader transform for the objective
(:func:`span_corrupt_transform`, the T5 counterpart of BERT's
``mlm_transform``).

Architecture follows the T5 v1.1 conventions: pre-RMSNorm blocks, NO
biases anywhere, bucketed relative position bias on self-attention
(shared across the stack's layers, bidirectional buckets in the encoder,
causal buckets in the decoder; none on cross-attention), gated-GELU MLP,
un-tied LM head, and un-scaled attention scores (the 1/sqrt(d) factor is
folded into initialization instead).

Span corruption runs host-side with FIXED counts per window (exactly
``noise`` corrupted tokens in exactly ``spans`` spans), so every example
in a batch has the same encoder/decoder lengths and the device step stays
static-shaped with no padding or masks at all.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from tpudist.mesh import TENSOR_AXIS
from tpudist.parallel.tp import partitioned as _partitioned


def _rms_norm(dtype, name):
    """T5's LayerNorm: scale-only RMS normalization (flax's nn.RMSNorm —
    the same module llama.py uses for the identical convention)."""
    return nn.RMSNorm(epsilon=1e-6, dtype=dtype, name=name)


def relative_position_buckets(q_len: int, k_len: int, *, bidirectional: bool,
                              num_buckets: int = 32, max_distance: int = 128):
    """[q_len, k_len] int32 bucket ids for the learned relative bias.

    Log-binned distance buckets: half the buckets cover exact small
    offsets, the rest log-space out to ``max_distance``; bidirectional
    stacks split the budget between past and future. (The bucketing
    function class of relative-attention biases, computed here on static
    iota so XLA folds it to a constant.)
    """
    ctx = jnp.arange(q_len)[:, None]
    mem = jnp.arange(k_len)[None, :]
    rel = mem - ctx  # >0 = future key
    buckets = 0
    n = num_buckets
    if bidirectional:
        n = n // 2
        buckets = jnp.where(rel > 0, n, 0)
        rel = jnp.abs(rel)
    else:
        rel = -jnp.minimum(rel, 0)  # causal: only past distances
    max_exact = n // 2
    is_small = rel < max_exact
    log_pos = max_exact + (
        jnp.log(jnp.maximum(rel, 1) / max_exact)
        / np.log(max_distance / max_exact) * (n - max_exact)
    ).astype(jnp.int32)
    log_pos = jnp.minimum(log_pos, n - 1)
    return buckets + jnp.where(is_small, rel, log_pos)


def _attention(q, k, v, *, bias=None, causal=False):
    """Un-scaled dot-product attention with an additive [H, Sq, Sk] bias —
    T5's flavor (no 1/sqrt(d); the bias carries the relative positions).
    Routed through the shared oracle (tpudist.ops.attention) so the
    softmax/masking numerics have one home. Shapes: q [B, Sq, H, Dh],
    k/v [B, Sk, H, Dh]."""
    from tpudist.ops.attention import dot_product_attention

    return dot_product_attention(
        q, k, v, causal=causal, scale=1.0,
        bias=None if bias is None else bias[None],
    )


class _Attention(nn.Module):
    """qkv/out projections (no biases) with the shared Megatron TP scheme;
    ``kv`` defaults to the query stream (self-attention) or takes the
    encoder output (cross-attention).

    ``decode=True`` (self-attention only): single-token KV-cache step —
    keys/values append into the module's decode cache
    (:func:`tpudist.ops.decode.cached_kv`, head-major buffers) and
    attention runs over valid slots with the caller's position-sliced
    relative bias. Cross-attention in a decode loop stays on the plain
    path: its K/V come from the (fixed) encoder output, recomputed per
    step — two [Se, D]·[D, D] GEMMs per layer per token, negligible at
    the model scales this family ships (0.2 ms/step at t5-small shapes)
    and free of a second cache contract."""

    num_heads: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, kv=None, *, bias=None, causal=False,
                 decode=False, max_len: int = 0):
        d = x.shape[-1]
        h = self.num_heads
        kv = x if kv is None else kv
        init = nn.initializers.lecun_normal()
        proj = lambda name, src: nn.DenseGeneral(
            (h, d // h), dtype=self.dtype, use_bias=False, name=name,
            kernel_init=_partitioned(init, None, TENSOR_AXIS, None),
        )(src)
        q, k, v = proj("q", x), proj("k", kv), proj("v", kv)
        if decode:
            from tpudist.ops.decode import cached_kv, decode_attention

            keys, values, mask, pos = cached_kv(self, k, v, max_len)
            attn = decode_attention(
                q, keys, values, mask, pos,
                # T5 flavor: un-scaled scores + additive relative bias
                # (bias forces the dense path — the fused kernel takes none)
                bias=None if bias is None else bias[None], scale=1.0,
            )
        else:
            attn = _attention(q, k, v, bias=bias, causal=causal)
        return nn.DenseGeneral(
            d, axis=(-2, -1), dtype=self.dtype, use_bias=False, name="out",
            kernel_init=_partitioned(init, TENSOR_AXIS, None, None),
        )(attn)


class _GatedMlp(nn.Module):
    """T5 v1.1 MLP: gelu(wi_0(x)) * wi_1(x) -> wo, no biases."""

    ffn_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        init = nn.initializers.lecun_normal()
        col = lambda name: nn.Dense(
            self.ffn_dim, dtype=self.dtype, use_bias=False, name=name,
            kernel_init=_partitioned(init, None, TENSOR_AXIS),
        )
        # tanh-approximate gelu = the published T5 v1.1 "gated-gelu"
        # (transformers' gelu_new) — keeps HF interop numerics exact
        y = nn.gelu(col("wi_0")(x), approximate=True) * col("wi_1")(x)
        return nn.Dense(
            d, dtype=self.dtype, use_bias=False, name="wo",
            kernel_init=_partitioned(init, TENSOR_AXIS, None),
        )(y)


class _EncoderBlock(nn.Module):
    num_heads: int
    ffn_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, bias):
        y = _rms_norm(self.dtype, "ln_attn")(x)
        x = x + _Attention(self.num_heads, dtype=self.dtype, name="attn")(
            y, bias=bias
        )
        y = _rms_norm(self.dtype, "ln_mlp")(x)
        return x + _GatedMlp(self.ffn_dim, dtype=self.dtype, name="mlp")(y)


class _DecoderBlock(nn.Module):
    num_heads: int
    ffn_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, enc, bias, *, decode=False, max_len: int = 0):
        y = _rms_norm(self.dtype, "ln_self")(x)
        x = x + _Attention(self.num_heads, dtype=self.dtype, name="self_attn")(
            y, bias=bias, causal=not decode, decode=decode, max_len=max_len
        )
        y = _rms_norm(self.dtype, "ln_cross")(x)
        # cross-attention carries no relative bias (T5 convention)
        x = x + _Attention(self.num_heads, dtype=self.dtype, name="cross_attn")(
            y, kv=enc
        )
        y = _rms_norm(self.dtype, "ln_mlp")(x)
        return x + _GatedMlp(self.ffn_dim, dtype=self.dtype, name="mlp")(y)


class T5(nn.Module):
    """Encoder-decoder transformer (T5 v1.1 conventions).

    ``__call__(enc_tokens [B, Se], dec_tokens [B, Sd])`` → fp32 logits
    ``[B, Sd, vocab]``. ``return_hidden=True`` returns the decoder's final
    hidden states (the chunked-head hook, mirroring the other families).

    Generation entry points (:func:`tpudist.generate.generate_seq2seq`
    drives both):

    - ``encode_only=True``: run just the encoder → ``[B, Se, D]`` (once
      per generation, outside the decode loop);
    - ``decode=True``: one single-token decoder step — the first
      positional arg is the current decoder token ``[B, 1]``, ``enc`` is
      the precomputed encoder output, self-attention appends into the
      per-layer KV cache (buffer length ``max_decode_len``), and the
      causal relative bias row for the current position is sliced from
      the full static table. Returns ``[B, 1, vocab]`` fp32 logits.
    """

    vocab_size: int = 512
    hidden_dim: int = 256
    ffn_dim: int = 512
    enc_depth: int = 4
    dec_depth: int = 4
    num_heads: int = 4
    rel_buckets: int = 32
    rel_max_distance: int = 128
    # decoder KV-cache buffer length for decode=True (generation)
    max_decode_len: int = 128
    dtype: Any = jnp.float32

    @property
    def flops_counter(self) -> str:
        """Analytic-FLOPs family tag (tpudist.telemetry.flops)."""
        return "t5"

    @nn.compact
    def __call__(self, enc_tokens, dec_tokens=None, train: bool = True,
                 return_hidden: bool = False, encode_only: bool = False,
                 decode: bool = False, enc=None):
        wte = self.param(
            "wte",
            _partitioned(nn.initializers.normal(1.0), TENSOR_AXIS, None),
            (self.vocab_size, self.hidden_dim), jnp.float32,
        )

        def rel_bias(name, q_len, k_len, bidirectional):
            table = self.param(
                name, nn.initializers.normal(0.4),
                (self.rel_buckets, self.num_heads), jnp.float32,
            )
            buckets = relative_position_buckets(
                q_len, k_len, bidirectional=bidirectional,
                num_buckets=self.rel_buckets,
                max_distance=self.rel_max_distance,
            )
            return jnp.transpose(table[buckets], (2, 0, 1))  # [H, Sq, Sk]

        def lm_head(y):
            # un-tied head (v1.1), fp32 logits
            return nn.Dense(
                self.vocab_size, dtype=self.dtype, use_bias=False,
                name="lm_head",
                kernel_init=_partitioned(
                    nn.initializers.normal(0.05), None, TENSOR_AXIS
                ),
            )(y).astype(jnp.float32)

        if decode:
            # single-token decoder step against the KV cache; the first
            # positional arg is the CURRENT decoder token [B, 1]
            tok = enc_tokens
            dmax = self.max_decode_len
            # the top-level position cursor (the per-layer caches advance
            # in lockstep with it); the init trace only creates it
            initialized = self.has_variable("cache", "position")
            pos_var = self.variable(
                "cache", "position", lambda: jnp.zeros((), jnp.int32)
            )
            pos = pos_var.value
            if initialized:
                pos_var.value = pos + tok.shape[1]
            # OVERRUN GUARD: past max_decode_len, dynamic_slice (the bias
            # rows below) and the caches' dynamic_update_slice CLAMP
            # silently — wrong relative biases and a clobbered last cache
            # slot. generate_seq2seq bounds-checks at entry; a direct
            # incremental-decode caller must fail loudly instead of
            # decoding garbage: eagerly (concrete cursor) that's a
            # ValueError; under jit the step's logits are NaN-poisoned —
            # deterministic, unmissable, and free when the bound holds.
            if tok.shape[1] > dmax:
                raise ValueError(
                    f"decode chunk of {tok.shape[1]} tokens exceeds "
                    f"max_decode_len {dmax} (the decoder KV-cache buffer)"
                )
            overrun = pos + tok.shape[1] > dmax
            if not isinstance(pos, jax.core.Tracer):
                if bool(overrun):
                    raise ValueError(
                        f"incremental decode past max_decode_len {dmax} "
                        f"(cursor {int(pos)} + chunk {tok.shape[1]}); "
                        "grow max_decode_len or stop the decode loop"
                    )
            # full static [H, Dmax, Dmax] causal bias table (XLA folds the
            # bucket iota); rows pos..pos+s-1 sliced at the traced
            # position — one row per chunk token, so multi-token chunks
            # (bulk prefill) see each row's own relative distances
            table = rel_bias("dec_rel_bias", dmax, dmax, False)
            bias = jax.lax.dynamic_slice(
                table, (0, pos, 0), (self.num_heads, tok.shape[1], dmax)
            )
            bias = jnp.where(overrun, jnp.nan, bias)
            y = wte[tok].astype(self.dtype)
            for i in range(self.dec_depth):
                y = _DecoderBlock(
                    self.num_heads, self.ffn_dim, dtype=self.dtype,
                    name=f"dec_{i}",
                )(y, enc, bias, decode=True, max_len=dmax)
            y = _rms_norm(self.dtype, "ln_dec")(y)
            return lm_head(y)

        if not encode_only and dec_tokens is None:
            # the single-sample-input convention of create_train_state:
            # two-stream models take an (enc, dec) tuple as the one input
            enc_tokens, dec_tokens = enc_tokens
        se = enc_tokens.shape[1]

        # ---- encoder (bias shared by every layer — T5 convention) ----
        x = wte[enc_tokens].astype(self.dtype)
        enc_bias = rel_bias("enc_rel_bias", se, se, True)
        for i in range(self.enc_depth):
            x = _EncoderBlock(
                self.num_heads, self.ffn_dim, dtype=self.dtype,
                name=f"enc_{i}",
            )(x, enc_bias)
        enc = _rms_norm(self.dtype, "ln_enc")(x)
        if encode_only:
            return enc

        # ---- decoder ----
        sd = dec_tokens.shape[1]
        y = wte[dec_tokens].astype(self.dtype)
        dec_bias = rel_bias("dec_rel_bias", sd, sd, False)
        for i in range(self.dec_depth):
            y = _DecoderBlock(
                self.num_heads, self.ffn_dim, dtype=self.dtype,
                name=f"dec_{i}",
            )(y, enc, dec_bias)
        y = _rms_norm(self.dtype, "ln_dec")(y)
        if return_hidden:
            return y
        return lm_head(y)


def t5_small(**kw) -> T5:
    """t5-v1.1-small geometry: 512 hidden, 8 enc + 8 dec layers, 6 heads,
    1024 ffn."""
    kw.setdefault("hidden_dim", 512)
    kw.setdefault("ffn_dim", 1024)
    kw.setdefault("enc_depth", 8)
    kw.setdefault("dec_depth", 8)
    kw.setdefault("num_heads", 6)
    return T5(**kw)


def span_corruption_plan(length: int, *, density: float = 0.15,
                        mean_span: float = 3.0):
    """(noise_tokens, n_spans, enc_len, dec_len) for a window of
    ``length`` tokens — FIXED counts, so every example shares one shape."""
    noise = max(1, int(round(length * density)))
    spans = max(1, int(round(noise / mean_span)))
    spans = min(spans, noise)  # every span holds >= 1 token
    enc_len = length - noise + spans
    dec_len = noise + spans + 1  # sentinels + spans + EOS
    return noise, spans, enc_len, dec_len


def span_corrupt_transform(
    vocab_size: int, *, density: float = 0.15, mean_span: float = 3.0,
    seed: int = 0, key: str = "tokens", start_id: int = 0,
):
    """Loader transform applying T5 span corruption on the host.

    Exactly ``noise`` tokens in exactly ``spans`` contiguous spans are
    removed from each window and replaced by one sentinel each (ids
    ``vocab_size-1`` downward); the decoder target is the concatenation
    ``sentinel_0, span_0, sentinel_1, span_1, ..., EOS`` (EOS =
    ``vocab_size - spans - 1``), and the decoder input is the target
    shifted right behind ``start_id``. Fixed counts → fixed shapes → no
    padding, no masks. Produces ``{"enc_tokens", "dec_tokens",
    "targets"}``; data vocab ids must stay below the sentinel/EOS range.

    The corruption stream follows the framework's (seed, epoch, position)
    keying: the transform declares ``wants_position``, so position-aware
    loaders (``TokenWindowLoader``) pass ``(epoch, start)`` and the
    per-batch RNG is a pure function of ``(seed, epoch, start)`` — every
    epoch draws FRESH corruptions for the same window, and a mid-epoch
    checkpoint resume (``fit(resume=True)`` + ``iter_from``, which passes
    the true start) replays exactly the corruptions of the original run.
    A foreign loader that calls the transform without position falls back
    to keying on a digest of the batch's tokens — still deterministic and
    resume-stable, but then identical repeated batches repeat their
    corruption (no epoch freshness); use a position-aware loader for
    multi-epoch training.
    """
    import zlib

    def run(batch, epoch=None, start=None):
        tokens = np.asarray(batch[key])
        if epoch is not None:
            entropy = [seed, int(epoch), int(start)]
        else:
            entropy = [
                seed, zlib.crc32(np.ascontiguousarray(tokens).tobytes())
            ]
        rng = np.random.Generator(np.random.PCG64(
            np.random.SeedSequence(entropy)
        ))
        b, length = tokens.shape
        noise, spans, enc_len, dec_len = span_corruption_plan(
            length, density=density, mean_span=mean_span
        )
        sentinels = vocab_size - 1 - np.arange(spans)
        eos = vocab_size - spans - 1
        enc = np.empty((b, enc_len), tokens.dtype)
        dec = np.empty((b, dec_len), tokens.dtype)
        tgt = np.empty((b, dec_len), tokens.dtype)
        for i in range(b):
            # random composition: `noise` into `spans` positive parts,
            # `length - noise` into `spans + 1` non-negative gaps
            span_cuts = np.sort(
                rng.choice(noise - 1, size=spans - 1, replace=False)
            ) + 1 if spans > 1 else np.empty(0, np.int64)
            span_lens = np.diff(np.r_[0, span_cuts, noise])
            free = length - noise
            gap_cuts = np.sort(rng.integers(0, free + 1, size=spans))
            gaps = np.diff(np.r_[0, gap_cuts, free])
            e, t, pos = [], [], 0
            for s in range(spans):
                e.append(tokens[i, pos:pos + gaps[s]])
                pos += gaps[s]
                e.append(sentinels[s:s + 1].astype(tokens.dtype))
                t.append(sentinels[s:s + 1].astype(tokens.dtype))
                t.append(tokens[i, pos:pos + span_lens[s]])
                pos += span_lens[s]
            e.append(tokens[i, pos:])
            t.append(np.asarray([eos], tokens.dtype))
            enc[i] = np.concatenate(e)
            tgt[i] = np.concatenate(t)
            dec[i, 0] = start_id
            dec[i, 1:] = tgt[i, :-1]
        out = dict(batch)
        out.pop(key, None)
        out["enc_tokens"] = enc
        out["dec_tokens"] = dec
        out["targets"] = tgt
        return out

    run.wants_position = True
    return run


def seq2seq_forward(model: T5):
    """``forward_loss`` for ``make_train_step``: mean CE of the decoder
    logits against the span targets (every target position is real — the
    fixed-count corruption produces no padding). Expects batches from
    :func:`span_corrupt_transform`."""
    import optax

    def forward_loss(params, batch_stats, batch):
        logits = model.apply(
            {"params": params}, batch["enc_tokens"], batch["dec_tokens"],
            train=True,
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["targets"]
        ).mean()
        return loss, batch_stats

    return forward_loss
