"""Family-neutral LM utilities shared by the decoder models (GPT-2, Llama).

No reference counterpart (the reference's model is a CNN,
/root/reference/main.py:40); these serve the LM leg of the BASELINE ladder
for any model exposing the ``return_hidden`` contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


def stack_layers(params, depth: int, *, prefix: str, dest: str) -> dict:
    """Unrolled ``{prefix}{i}/...`` params → the ``scan_layers`` layout
    (``{dest}/block/...`` with a leading depth axis). One implementation
    for both decoder families (GPT-2: ``h_``/``hs``; Llama:
    ``layer_``/``layers``)."""
    plain = nn.meta.unbox(params)
    found = sorted(k for k in plain if k.startswith(prefix))
    if len(found) != depth:
        raise ValueError(
            f"params hold {len(found)} {prefix}* layers but depth={depth} "
            "was requested — refusing to silently truncate/misstack"
        )
    out = {k: v for k, v in plain.items() if not k.startswith(prefix)}
    out[dest] = {
        "block": jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves),
            *(plain[f"{prefix}{i}"] for i in range(depth)),
        )
    }
    return out


def unstack_layers(params, *, prefix: str, dest: str) -> dict:
    """Inverse of :func:`stack_layers` — back to the unrolled layout that
    decode/generation and the HF exporters use."""
    plain = nn.meta.unbox(params)
    block = plain[dest]["block"]
    depth = jax.tree_util.tree_leaves(block)[0].shape[0]
    out = {k: v for k, v in plain.items() if k != dest}
    for i in range(depth):
        out[f"{prefix}{i}"] = jax.tree_util.tree_map(lambda a: a[i], block)
    return out


def lm_head_weight(params):
    """The [V, D] output-projection weight of an LM, whichever family:
    GPT-2's tied ``wte``, Llama's untied ``lm_head`` (falling back to its
    ``embed`` when tied). Accepts boxed (fresh ``model.init``) and unboxed
    (train-state) params."""
    for key in ("lm_head", "wte", "embed"):
        if key in params:
            return nn.meta.unbox(params[key])
    raise ValueError(f"no LM head weight among params: {list(params)}")


def chunked_head_reduce(
    logits_fn, h, targets, pos_mask, chunk: int, *, hits: bool = False
):
    """Scan an arbitrary position-wise head over sequence chunks with a
    checkpointed body, so live logits are bounded by [B, chunk, V] in
    forward AND backward.

    ``logits_fn``: [B, chunk, D] hidden chunk → [B, chunk, V] logits (any
    head: a tied-matmul, BERT's transform+decode, ...). ``h``: [B, S, D];
    ``targets``/``pos_mask``: [B, S]. Returns the masked softmax-CE sum,
    plus the masked argmax-hit count when ``hits`` (for accuracy-style
    eval). The one home for the chunked-head skeleton — every chunked
    train loss and eval path rides it, so HBM behavior can't diverge
    between them.
    """
    import optax

    b, s, d = h.shape
    pad = -s % chunk
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
    pos_mask = jnp.pad(
        jnp.broadcast_to(pos_mask, (b, s)).astype(jnp.float32),
        ((0, 0), (0, pad)),
    )
    nc = (s + pad) // chunk
    hs = h.reshape(b, nc, chunk, d).transpose(1, 0, 2, 3)
    ts = targets.reshape(b, nc, chunk).transpose(1, 0, 2)
    ms = pos_mask.reshape(b, nc, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, xs):
        hc, tc, mc = xs
        logits = logits_fn(hc)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
        ce_sum = carry[0] + jnp.sum(ce * mc)
        hit_sum = carry[1]
        if hits:
            hit = jnp.argmax(logits, axis=-1) == tc
            hit_sum = hit_sum + jnp.sum(jnp.where(mc > 0, hit, False))
        return (ce_sum, hit_sum), None

    (total, hit_total), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (hs, ts, ms),
    )
    return (total, hit_total) if hits else total


def tied_head_logits_fn(head_w):
    """``logits_fn`` for :func:`chunked_head_reduce`: the weight-tied decode
    against a [V, D] table (GPT-2's ``wte``, Llama's head)."""

    def logits_fn(hc):
        return jnp.einsum(
            "bcd,vd->bcv", hc, head_w.astype(hc.dtype),
            preferred_element_type=jnp.float32,
        )

    return logits_fn


def chunked_ce_sum(head_w, h, targets, pos_mask, chunk: int):
    """Masked softmax-CE sum under the weight-tied head — the decoder
    families' instantiation of :func:`chunked_head_reduce` (training via
    :func:`chunked_lm_forward`, eval via :func:`tpudist.train.evaluate_lm`).
    """
    return chunked_head_reduce(
        tied_head_logits_fn(head_w), h, targets, pos_mask, chunk
    )


def chunked_lm_forward(model, chunk: int = 256):
    """Fused next-token loss that never materializes the [B,S,V] logits.

    The plain path's fp32 logits are the HBM high-water mark at realistic
    shapes (B=32, S=1024, V=50257 → 6.6 GB) and cap the per-chip batch.
    This forward runs the blocks once, then ``lax.scan``s the weight-tied
    head + softmax-CE over sequence chunks with ``jax.checkpoint`` on the
    body, so live logits are bounded by [B, chunk, V] in both passes (the
    backward recomputes each chunk's logits instead of storing them).

    Works for any model with the ``return_hidden`` contract (GPT-2, Llama),
    including MoE variants: their sowed load-balance losses (the ``losses``
    collection, tpudist.parallel.ep) are collected from the blocks pass and
    added to the chunked CE — the aux loss survives the chunked path.
    Returns a ``forward_loss`` for :func:`tpudist.train.make_train_step`:
    ``(params, batch_stats, batch) -> (loss, batch_stats)``. Mean CE over
    all positions — identical math to ``lm_loss`` on full logits.
    """
    if getattr(model, "dropout", 0.0):
        raise ValueError(
            "chunked_lm_forward does not support dropout (the fused path "
            "has no rng stream); use the default forward"
        )
    if getattr(model, "router_jitter", 0.0):
        raise ValueError(
            "chunked_lm_forward does not support router_jitter (the fused "
            "path has no rng stream); use the default forward"
        )
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    wants_aux = bool(getattr(model, "has_aux_loss", False))

    def forward_loss(params, batch_stats, batch):
        tokens = batch["tokens"]
        aux = 0.0
        if wants_aux:
            hidden, updates = model.apply(
                {"params": params}, tokens, train=True, return_hidden=True,
                mutable=["losses"],
            )
            aux = sum(
                jax.tree_util.tree_leaves(updates.get("losses", {})), 0.0
            )
        else:
            hidden = model.apply(
                {"params": params}, tokens, train=True, return_hidden=True
            )
        h = hidden[:, :-1]
        targets = tokens[:, 1:]
        b, s, _ = h.shape
        total = chunked_ce_sum(
            lm_head_weight(params), h, targets, jnp.ones((b, s)), chunk
        )
        return total / (b * s) + aux, batch_stats

    # the hook make_train_step(fused="ln") uses to re-close this loss over
    # its fused_ln model clone (the closure above captured `model`; a
    # cloned model would otherwise never reach the forward)
    forward_loss.rebuild = lambda m: chunked_lm_forward(m, chunk=chunk)
    forward_loss.model = model
    return forward_loss
