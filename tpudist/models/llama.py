"""Llama-family decoder in Flax — the modern-LM member of the model zoo.

No reference counterpart (the reference's only model is ResNet-50,
/root/reference/main.py:40); built so the framework covers the
architecture most large-scale TPU training targets today: pre-norm RMSNorm,
rotary position embeddings (RoPE — no learned position table), grouped-query
attention (GQA: fewer K/V heads than Q heads), SwiGLU MLP, no biases,
untied LM head (tying optional).

TPU-first choices mirror :mod:`tpudist.models.gpt2`:

- Megatron tensor-parallel partitioning metadata over the ``tensor`` mesh
  axis (qkv/gate/up column-parallel, out/down row-parallel, embedding and
  head vocab-sharded); GSPMD inserts the two all-reduces per block.
- ``attn_impl`` selects XLA einsum attention, the Pallas flash kernel, or
  the context-parallel paths (ring / Ulysses over the ``seq`` axis) from
  :mod:`tpudist.parallel.cp` — RoPE is applied at the global sequence view,
  so sequence sharding composes without per-shard offset bookkeeping.
- GQA K/V heads are broadcast up to the Q-head count right before the
  attention op: one cheap ``repeat`` that XLA fuses, keeping every attention
  impl (flash kernel included) oblivious to the grouping.
- RoPE angles are computed in fp32 and cast once, keeping bf16 runs stable.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from tpudist.mesh import TENSOR_AXIS
from tpudist.ops.attention import multi_head_attention
from tpudist.parallel.tp import partitioned as _partitioned


def apply_rope(x, *, theta: float = 10000.0, positions=None):
    """Rotary position embedding over ``x: [B, S, H, D]`` (rotate-half
    convention). Angles in fp32; output in ``x.dtype``. ``positions`` is
    ``[S]`` (shared across the batch) or ``[B, S]`` (per-row absolute
    positions — slot-pooled decode, where every cache slot sits at its own
    sequence length)."""
    b, s, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.float32)
    angles = positions[..., :, None] * freqs  # [S, half] or [B, S, half]
    if angles.ndim == 3:
        cos = jnp.cos(angles)[:, :, None, :]              # [B, S, 1, half]
        sin = jnp.sin(angles)[:, :, None, :]
    else:
        cos = jnp.cos(angles)[None, :, None, :]           # [1, S, 1, half]
        sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class LlamaBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    ffn_dim: int
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    rope_theta: float = 10000.0
    mesh: Any = None
    norm_eps: float = 1e-5
    # num_experts > 0 swaps the SwiGLU MLP for a Mixtral-style MoE of
    # SwiGLU experts (tpudist.parallel.ep), expert-sharded over 'expert'
    num_experts: int = 0
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    # expert dispatch impl + router hardening (tpudist.parallel.ep.MoEMlp)
    moe_dispatch: str = "einsum"
    router_z_loss: float = 0.0
    router_jitter: float = 0.0
    # fused_ln=True runs both RMSNorms through the Pallas fused
    # residual-add+norm kernel (tpudist.ops.layernorm, rms=True — same
    # "scale" param as nn.RMSNorm). Decode keeps the reference composition.
    fused_ln: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True, decode: bool = False,
                 max_len: int = 0, positions=None, block_tables=None):
        b, s, d = x.shape
        h, kv = self.num_heads, self.num_kv_heads
        if h % kv:
            raise ValueError(f"num_heads {h} not divisible by num_kv_heads {kv}")
        dh = d // h
        dense_init = nn.initializers.lecun_normal()
        fused = self.fused_ln and not decode
        if fused:
            from tpudist.ops.layernorm import FusedLayerNorm

            norm = lambda name: FusedLayerNorm(
                epsilon=self.norm_eps, dtype=self.dtype, rms=True,
                mesh=self.mesh, name=name,
            )
        else:
            norm = lambda name: nn.RMSNorm(
                epsilon=self.norm_eps, dtype=self.dtype, name=name
            )

        y = norm("attn_norm")(x)
        # column-parallel projections: head dim sharded over 'tensor'
        q = nn.DenseGeneral((h, dh), use_bias=False, dtype=self.dtype,
                            name="q_proj",
                            kernel_init=_partitioned(dense_init, None, TENSOR_AXIS, None))(y)
        k = nn.DenseGeneral((kv, dh), use_bias=False, dtype=self.dtype,
                            name="k_proj",
                            kernel_init=_partitioned(dense_init, None, TENSOR_AXIS, None))(y)
        v = nn.DenseGeneral((kv, dh), use_bias=False, dtype=self.dtype,
                            name="v_proj",
                            kernel_init=_partitioned(dense_init, None, TENSOR_AXIS, None))(y)
        if decode:
            # KV-cache decode (tpudist.ops.decode): keys are rotated at
            # their absolute positions BEFORE caching, so the cache holds
            # position-encoded keys; q rotates at the same offset
            if self.attn_impl in ("ring", "ulysses", "ulysses_flash"):
                raise ValueError(
                    f"attn_impl={self.attn_impl!r} has no decode path; "
                    "generate with the xla/flash model"
                )
            from tpudist.ops.decode import (
                cached_kv, decode_attention, paged_decode_attention,
            )

            def rope_positions(pos):
                # scalar cursor: the chunk rows sit at pos..pos+s-1; per-row
                # cursors ([B], slot-pooled decode): row b's chunk rows at
                # pos_b..pos_b+s-1 (s > 1 is the speculative verify chunk;
                # RoPE has no table to overrun, so no tail clamp is needed)
                if jnp.ndim(pos) == 0:
                    return (pos + jnp.arange(s)).astype(jnp.float32)
                return (
                    pos[:, None] + jnp.arange(s)[None, :]
                ).astype(jnp.float32)  # [B, s]

            def rotate_k(k, v, pos):
                return apply_rope(k, theta=self.rope_theta,
                                  positions=rope_positions(pos)), v

            keys, values, mask, pos = cached_kv(
                self, k, v, max_len, pre_update=rotate_k,
                positions=positions, block_tables=block_tables,
            )
            q = apply_rope(q, theta=self.rope_theta,
                           positions=rope_positions(pos))
            if block_tables is not None:
                # paged decode: keys/values are the shared block pool and
                # `mask` the per-row block tables (tpudist.serve.blocks);
                # keys were RoPE-rotated at their absolute positions
                # before the paged write, same as the contiguous path
                attn = paged_decode_attention(
                    q, keys, values, mask, pos,
                    impl="xla" if self.attn_impl == "xla" else "paged",
                    mesh=self.mesh,
                )
            else:
                # fused path reads grouped K/V heads natively (no repeat in
                # HBM); the dense oracle repeats inside decode_attention
                attn = decode_attention(
                    q, keys, values, mask, pos,
                    impl="xla" if self.attn_impl == "xla" else "fused",
                )
        else:
            q = apply_rope(q, theta=self.rope_theta)
            k = apply_rope(k, theta=self.rope_theta)
            if kv != h and self.attn_impl in ("ring", "ulysses", "ulysses_flash"):
                # the context-parallel bodies shard/rotate full head sets;
                # broadcast K/V heads up front there. The multi_head_attention
                # dispatch below takes grouped K/V as-is — the vmem kernel
                # reads each K/V head once per query group (no repeat in
                # HBM), and its dense/flash fallbacks repeat internally.
                from tpudist.ops.attention import repeat_kv

                k, v = repeat_kv(q, k, v)
            if self.attn_impl in ("ring", "ulysses", "ulysses_flash"):
                if self.mesh is None:
                    raise ValueError(
                        f"attn_impl={self.attn_impl!r} needs the model's "
                        "mesh= field set (the shard_map runs over its 'seq' "
                        "axis)"
                    )
                from tpudist.parallel.cp import ring_attention, ulysses_attention

                if self.attn_impl == "ring":
                    attn = ring_attention(q, k, v, self.mesh, causal=True)
                else:
                    attn_fn = None
                    if self.attn_impl == "ulysses_flash":
                        from tpudist.ops.attention import kernel_attention

                        attn_fn = kernel_attention
                    attn = ulysses_attention(
                        q, k, v, self.mesh, causal=True, attn_fn=attn_fn
                    )
            else:
                attn = multi_head_attention(
                    q, k, v, causal=True, impl=self.attn_impl,
                    mesh=self.mesh,
                )
        # row-parallel output projection; GSPMD all-reduces over 'tensor'
        o = nn.DenseGeneral(
            d, axis=(-2, -1), use_bias=False, dtype=self.dtype, name="o_proj",
            kernel_init=_partitioned(dense_init, TENSOR_AXIS, None, None),
        )(attn)
        if fused:
            # residual add + RMSNorm in one kernel sweep; the updated
            # residual stream rides back from the same HBM pass
            y, x = norm("mlp_norm")(o, residual=x)
        else:
            x = x + o
            y = norm("mlp_norm")(x)
        if self.num_experts > 0:
            from tpudist.parallel.ep import MoEMlp

            y = MoEMlp(
                num_experts=self.num_experts, top_k=self.moe_top_k,
                capacity_factor=self.capacity_factor,
                ffn_dim=self.ffn_dim, expert_act="swiglu",
                dispatch_impl=self.moe_dispatch,
                router_z_loss=self.router_z_loss,
                router_jitter=self.router_jitter,
                dtype=self.dtype, mesh=self.mesh, name="moe",
            )(y, deterministic=not train)
        else:
            # SwiGLU: silu(gate)·up, both column-parallel; down row-parallel
            gate = nn.Dense(self.ffn_dim, use_bias=False, dtype=self.dtype,
                            name="gate_proj",
                            kernel_init=_partitioned(dense_init, None, TENSOR_AXIS))(y)
            up = nn.Dense(self.ffn_dim, use_bias=False, dtype=self.dtype,
                          name="up_proj",
                          kernel_init=_partitioned(dense_init, None, TENSOR_AXIS))(y)
            y = nn.Dense(d, use_bias=False, dtype=self.dtype, name="down_proj",
                         kernel_init=_partitioned(dense_init, TENSOR_AXIS, None))(
                nn.silu(gate) * up
            )
        return x + y


class _CarryBlock(nn.Module):
    """:class:`LlamaBlock` with the (carry, xs) -> (carry, ys) signature
    ``nn.scan`` maps over; ``train`` rides as a module field because scan
    broadcasts call-time kwargs awkwardly."""

    num_heads: int
    num_kv_heads: int
    ffn_dim: int
    train: bool = True
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    rope_theta: float = 10000.0
    mesh: Any = None
    norm_eps: float = 1e-5
    fused_ln: bool = False

    @nn.compact
    def __call__(self, x, _):
        x = LlamaBlock(
            self.num_heads, self.num_kv_heads, self.ffn_dim,
            dtype=self.dtype, attn_impl=self.attn_impl,
            rope_theta=self.rope_theta, mesh=self.mesh,
            norm_eps=self.norm_eps, fused_ln=self.fused_ln, name="block",
        )(x, train=self.train)
        return x, None


def default_ffn_dim(hidden_dim: int) -> int:
    """The SwiGLU sizing ``ffn_dim=None`` resolves to: 8/3·d rounded up to
    a multiple of 256 (Llama convention). One home for the formula — the
    model's forward and the analytic FLOPs dispatcher
    (tpudist.telemetry.flops) must agree on the parameter count."""
    return -(-8 * hidden_dim // 3 // 256) * 256


class Llama(nn.Module):
    vocab_size: int = 32000
    max_seq_len: int = 2048
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    num_kv_heads: int | None = None   # None → MHA (kv == heads)
    ffn_dim: int | None = None        # None → SwiGLU sizing: 8/3·d, /256 ceil
    rope_theta: float = 10000.0
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    tie_embeddings: bool = False
    mesh: Any = None
    norm_eps: float = 1e-5
    # scan_layers=True runs the depth as ONE nn.scan'd block with params
    # stacked [depth, ...] — XLA traces/compiles a single layer regardless
    # of depth (the idiomatic TPU pattern for 32+ layer models; an unrolled
    # llama2-7b traces 32 copies of the block). Param names move from
    # layer_{i}/... to layers/... with a leading depth axis; TP metadata is
    # preserved (the stacked axis stays unsharded). Training/eval only —
    # decode and the interop converters use the unrolled layout.
    scan_layers: bool = False
    # remat_layers=True checkpoints each scanned layer: backward stores only
    # the per-layer boundary activations and recomputes inside the layer —
    # the scan+remat memory pattern that makes depth-32+ long-sequence
    # training fit (requires scan_layers; legacy sugar for
    # remat_policy="full")
    remat_layers: bool = False
    # per-BLOCK rematerialization policy (tpudist.remat names: "full",
    # "dots_saveable", "save_nothing"; None/"none" off), honored in BOTH
    # the scanned and unrolled layouts (unrolled keeps layer_{i} param
    # names — nn.remat is name-transparent). Ignored on the decode path.
    remat_policy: str | None = None
    # num_experts > 0: every moe_every-th block is Mixtral-style MoE (SwiGLU
    # experts over the 'expert' mesh axis, tpudist.parallel.ep); aux
    # load-balance losses are sowed and added by the train step
    num_experts: int = 0
    moe_every: int = 1  # Mixtral: every block is MoE
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    # dispatch impl + router hardening, threaded into every MoE block
    moe_dispatch: str = "einsum"
    router_z_loss: float = 0.0
    router_jitter: float = 0.0
    # fused_ln=True: every RMSNorm (attn_norm/mlp_norm/final norm) runs
    # the Pallas fused residual-add+norm kernel (tpudist.ops.layernorm,
    # rms=True) — same param tree, decode path untouched. Usually set via
    # make_train_step(fused="ln"|"all"), which clones the model.
    fused_ln: bool = False

    @property
    def has_aux_loss(self) -> bool:
        return self.num_experts > 0

    @property
    def flops_counter(self) -> str | None:
        """Analytic-FLOPs family tag (tpudist.telemetry.flops) — the MFU
        numerator dispatch. MoE geometries use "llama_moe" (active-param
        accounting: top_k SwiGLU experts + router GEMM per MoE block), so
        MFU rows stay real for sparse models."""
        return "llama_moe" if self.num_experts > 0 else "llama"

    def init_cache(self, batch_size: int):
        """Zeroed decode KV cache for ``batch_size`` rows — the serving
        engine's slot-pool allocation hook (``tpudist.serve.slots``); built
        via ``eval_shape`` so no params materialize."""
        from tpudist.generate import zero_cache

        return zero_cache(self, batch_size)

    @nn.compact
    def __call__(self, tokens, train: bool = True, return_hidden: bool = False,
                 decode: bool = False, positions=None, block_tables=None):
        b, s = tokens.shape
        if s > self.max_seq_len:
            raise ValueError(f"sequence {s} exceeds max_seq_len {self.max_seq_len}")
        kv = self.num_kv_heads or self.num_heads
        ffn = self.ffn_dim or default_ffn_dim(self.hidden_dim)
        embed = self.param(
            "embed",
            _partitioned(nn.initializers.normal(0.02), TENSOR_AXIS, None),
            (self.vocab_size, self.hidden_dim), jnp.float32,
        )
        x = embed[tokens].astype(self.dtype)  # RoPE: no position table
        block_cfg = dict(
            num_heads=self.num_heads, num_kv_heads=kv, ffn_dim=ffn,
            dtype=self.dtype, attn_impl=self.attn_impl,
            rope_theta=self.rope_theta, mesh=self.mesh,
            norm_eps=self.norm_eps, fused_ln=self.fused_ln,
        )
        from tpudist.remat import remat_module

        block_policy = self.remat_policy or (
            "full" if self.remat_layers else None
        )
        if self.scan_layers:
            if decode:
                raise ValueError(
                    "scan_layers has no decode path (the KV cache needs "
                    "per-layer variables); generate with scan_layers=False"
                )
            if self.num_experts:
                raise ValueError("scan_layers supports dense blocks only")
            body = remat_module(_CarryBlock, block_policy)
            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=self.depth,
                # stacked depth axis carries no partition name (unsharded);
                # the per-layer TENSOR_AXIS metadata shifts right intact
                metadata_params={nn.PARTITION_NAME: None},
            )(train=train, **block_cfg, name="layers")
            x, _ = scanned(x, None)
        elif self.remat_layers:
            raise ValueError("remat_layers requires scan_layers=True "
                             "(set remat_policy to checkpoint the unrolled "
                             "blocks, or make_train_step(remat=...) for a "
                             "whole-forward checkpoint)")
        else:
            # per-block checkpoint in the unrolled layout: layer_{i} param
            # names unchanged; train/decode/max_len static under the remat
            block_cls = (
                remat_module(LlamaBlock, block_policy, static_argnums=(2, 3, 4))
                if not decode else LlamaBlock
            )
            for i in range(self.depth):
                moe_here = self.num_experts > 0 and (
                    i % self.moe_every == self.moe_every - 1
                )
                x = block_cls(
                    **block_cfg,
                    num_experts=self.num_experts if moe_here else 0,
                    moe_top_k=self.moe_top_k,
                    capacity_factor=self.capacity_factor,
                    moe_dispatch=self.moe_dispatch,
                    router_z_loss=self.router_z_loss,
                    router_jitter=self.router_jitter,
                    name=f"layer_{i}",
                )(x, train, decode, self.max_seq_len,
                  # only the (remat-free) decode path threads per-slot
                  # positions/block tables (same contract as GPT-2)
                  **({"positions": positions,
                      "block_tables": block_tables} if decode else {}))
        if self.fused_ln and not decode:
            from tpudist.ops.layernorm import FusedLayerNorm

            x = FusedLayerNorm(
                epsilon=self.norm_eps, dtype=self.dtype, rms=True,
                mesh=self.mesh, name="norm",
            )(x)
        else:
            x = nn.RMSNorm(
                epsilon=self.norm_eps, dtype=self.dtype, name="norm"
            )(x)
        if return_hidden:
            # the chunked-CE path applies the head per sequence chunk so the
            # [B,S,V] fp32 logits never materialize (gpt2.chunked_lm_forward)
            return x
        if self.tie_embeddings:
            head = embed
        else:
            head = self.param(
                "lm_head",
                _partitioned(nn.initializers.normal(0.02), TENSOR_AXIS, None),
                (self.vocab_size, self.hidden_dim), jnp.float32,
            )
        return jnp.einsum(
            "bsd,vd->bsv", x, head.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )


def stack_llama_layers(params, depth: int) -> dict:
    """Unrolled ``layer_{i}`` params → the ``scan_layers`` layout; lets
    checkpoints move between layouts (e.g. warm-start a scan model from an
    HF import). See :func:`tpudist.models.lm_utils.stack_layers`."""
    from tpudist.models.lm_utils import stack_layers

    return stack_layers(params, depth, prefix="layer_", dest="layers")


def unstack_llama_layers(params) -> dict:
    """``scan_layers`` layout → unrolled ``layer_{i}`` params (the layout
    decode/generation and the HF exporters use)."""
    from tpudist.models.lm_utils import unstack_layers

    return unstack_layers(params, prefix="layer_", dest="layers")


def llama_125m(**kw) -> Llama:
    """GPT-2-124M-comparable Llama: 12 layers, 768 hidden, GQA 12/4."""
    kw.setdefault("num_kv_heads", 4)
    return Llama(**kw)


def llama2_7b(**kw) -> Llama:
    """Llama-2 7B geometry: 32 layers, 4096 hidden, MHA, ffn 11008."""
    kw.setdefault("hidden_dim", 4096)
    kw.setdefault("depth", 32)
    kw.setdefault("num_heads", 32)
    kw.setdefault("ffn_dim", 11008)
    kw.setdefault("max_seq_len", 4096)
    return Llama(**kw)


def mixtral_8x7b(**kw) -> Llama:
    """Mixtral-8x7B geometry: Llama-7B trunk, every block an 8-expert
    top-2 SwiGLU MoE, GQA 32/8, 32k rope theta 1e6."""
    kw.setdefault("hidden_dim", 4096)
    kw.setdefault("depth", 32)
    kw.setdefault("num_heads", 32)
    kw.setdefault("num_kv_heads", 8)
    kw.setdefault("ffn_dim", 14336)
    kw.setdefault("vocab_size", 32000)
    kw.setdefault("rope_theta", 1e6)
    kw.setdefault("max_seq_len", 32768)
    kw.setdefault("num_experts", 8)
    kw.setdefault("moe_top_k", 2)
    return Llama(**kw)


def llama3_8b(**kw) -> Llama:
    """Llama-3 8B geometry: GQA 32/8, ffn 14336, 128k vocab, theta 5e5."""
    kw.setdefault("hidden_dim", 4096)
    kw.setdefault("depth", 32)
    kw.setdefault("num_heads", 32)
    kw.setdefault("num_kv_heads", 8)
    kw.setdefault("ffn_dim", 14336)
    kw.setdefault("vocab_size", 128256)
    kw.setdefault("rope_theta", 500000.0)
    kw.setdefault("max_seq_len", 8192)
    return Llama(**kw)
