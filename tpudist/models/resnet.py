"""ResNet in Flax (NHWC, TPU-native).

Equivalent of ``torchvision.models.resnet50`` as used by the reference
(/root/reference/main.py:8,40): 25.6M-param bottleneck ResNet-50 with
batch-norm everywhere and a 1000-way head (the reference does NOT adapt the
head to CIFAR-100 — ``num_classes`` defaults to 1000 for parity, SURVEY.md
§2a). ResNet-18 covers BASELINE config 1.

TPU-first choices:
- NHWC layout (XLA's native conv layout on TPU; torchvision is NCHW).
- Cross-replica batch-norm — the reference wraps the net in
  ``SyncBatchNorm.convert_sync_batchnorm`` (/root/reference/main.py:82) so BN
  statistics span the *global* batch. Under pjit/GSPMD the batch is one
  logical array sharded over the ``data`` axis, so plain ``nn.BatchNorm``
  already computes global-batch statistics (XLA inserts the cross-replica
  reduction); ``axis_name`` is accepted for explicit shard_map/pmap use.
- bf16-friendly: ``dtype`` controls activation/compute precision; params and
  BN statistics stay float32.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        # final BN of each block: scale init zeros (standard modern recipe is
        # optional; torchvision inits gamma=1, keep 1 for parity)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), (self.strides, self.strides), name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), (self.strides, self.strides), name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    axis_name: str | None = None
    small_inputs: bool = False  # CIFAR stem: 3x3/s1 conv, no maxpool
    # "conv7" = torchvision's 7x7/s2 stem (parity default). "space_to_depth"
    # = the MLPerf TPU stem: 2x2 space-to-depth on the image then a 4x4/s1
    # conv — same function class (bijective reparametrization of a padded
    # 8x8/s2 conv) but the MXU sees 12 input channels instead of 3, which
    # the 128-lane systolic array tiles far better
    stem: str = "conv7"

    @property
    def flops_counter(self) -> str:
        """Analytic-FLOPs family tag (tpudist.telemetry.flops) — the
        counter itself returns None for geometries other than the
        standard bottleneck ResNet-50, so every variant may carry the
        tag safely."""
        return "resnet"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.axis_name,
        )
        x = jnp.asarray(x, self.dtype)
        if self.small_inputs:
            if self.stem != "conv7":
                raise ValueError(
                    f"stem={self.stem!r} has no effect with small_inputs "
                    "(the CIFAR stem is a single 3x3/s1 conv) — drop the "
                    "stem override rather than silently ignoring it"
                )
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        elif self.stem == "space_to_depth":
            n, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(f"space_to_depth stem needs even H/W, got {(h, w)}")
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            x = conv(self.num_filters, (4, 4), name="conv_init_s2d")(x)
        elif self.stem == "conv7":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def resnet18(**kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock, **kw)


def resnet34(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock, **kw)


def resnet152(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock, **kw)
