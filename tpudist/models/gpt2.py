"""GPT-2 decoder in Flax — BASELINE.json config 5 (GPT-2 124M, DP + grad
accumulation, tokens/sec).

No reference counterpart (SURVEY.md §2.12); built for the LM leg of the
baseline ladder. TPU-first: causal attention through tpudist.ops (XLA or
Pallas flash path), bf16 compute with fp32 params, weight-tied LM head as a
single MXU matmul against the embedding table.

Tensor parallelism is expressed as Megatron-style param partitioning
metadata over the ``tensor`` mesh axis (``nn.with_partitioning``): qkv and
mlp_fc are column-parallel (heads / ffn dim sharded), out and mlp_proj are
row-parallel, and the embedding table is vocab-sharded. GSPMD inserts the
pair of all-reduces per block from these shardings — there is no hand-written
collective. On a mesh with ``tensor=1`` the metadata is inert.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpudist.mesh import PIPELINE_AXIS, TENSOR_AXIS
from tpudist.ops.attention import multi_head_attention
from tpudist.parallel.pp import pipeline_apply
from tpudist.parallel.tp import partitioned as _partitioned


class Block(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    # tp=False drops the tensor-axis partitioning metadata — required when
    # the block runs inside a shard_map manual-mesh context (the pipelined
    # model), where flax's eval_shape re-run of boxed initializers would
    # apply sharding constraints that cannot be resolved
    tp: bool = True
    # num_experts > 0 swaps the dense MLP for a mixture-of-experts FFN
    # (tpudist.parallel.ep) routed top-k with expert-sharded weights
    num_experts: int = 0
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    # expert dispatch implementation (tpudist.parallel.ep): "einsum" (the
    # one-hot oracle) or "index" (slot-index gather/scatter + explicit
    # expert-axis all-to-all on a real expert mesh axis)
    moe_dispatch: str = "einsum"
    # router hardening knobs (off by default, byte-inert when 0.0)
    router_z_loss: float = 0.0
    router_jitter: float = 0.0
    mesh: Any = None
    # residual dropout (GPT-2 uses 0.1); needs a 'dropout' rng when > 0 and
    # train=True — tpudist.train supplies a per-step key automatically
    dropout: float = 0.0
    # fused_ln=True swaps both LayerNorms for the Pallas fused
    # residual-add+LN kernel (tpudist.ops.layernorm — identical param
    # names/shapes, so checkpoints and the unfused-built TrainState drive
    # it unchanged). The decode path keeps the reference composition (a
    # single-token norm is launch-bound, not bandwidth-bound).
    fused_ln: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True, decode: bool = False,
                 max_len: int = 0, positions=None, block_tables=None):
        b, s, d = x.shape
        h = self.num_heads
        drop = lambda y: (
            nn.Dropout(self.dropout, deterministic=not train)(y)
            if self.dropout else y
        )
        dense_init = nn.initializers.lecun_normal()
        partitioned = _partitioned if self.tp else (lambda init, *axes: init)
        fused = self.fused_ln and not decode
        if fused:
            from tpudist.ops.layernorm import FusedLayerNorm

            ln = lambda name: FusedLayerNorm(
                epsilon=1e-5, dtype=self.dtype, mesh=self.mesh, name=name
            )
        else:
            ln = lambda name: nn.LayerNorm(
                epsilon=1e-5, dtype=self.dtype, name=name
            )
        y = ln("ln_1")(x)
        # column-parallel: head dim sharded over 'tensor'
        qkv = nn.DenseGeneral(
            (3, h, d // h), dtype=self.dtype, name="qkv",
            kernel_init=partitioned(dense_init, None, None, TENSOR_AXIS, None),
            bias_init=partitioned(nn.initializers.zeros_init(), None, TENSOR_AXIS, None),
        )(y)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if decode:
            # autoregressive KV-cache attention (tpudist.ops.decode): the
            # context-parallel impls don't apply to single-token steps
            if self.attn_impl in ("ring", "ulysses", "ulysses_flash"):
                raise ValueError(
                    f"attn_impl={self.attn_impl!r} has no decode path; "
                    "generate with the xla/flash model"
                )
            from tpudist.ops.decode import (
                cached_kv, decode_attention, paged_decode_attention,
            )

            keys, values, mask, pos = cached_kv(
                self, k, v, max_len, positions=positions,
                block_tables=block_tables,
            )
            if block_tables is not None:
                # paged decode (tpudist.serve.blocks): keys/values are the
                # SHARED block pool and `mask` the per-row block tables;
                # the paged kernel walks each row's table up to its cursor
                attn = paged_decode_attention(
                    q, keys, values, mask, pos,
                    impl="xla" if self.attn_impl == "xla" else "paged",
                    mesh=self.mesh,
                )
            else:
                # one fused Pallas launch per layer per token unless the
                # caller pinned the dense oracle (attn_impl="xla") — decode
                # is launch-bound, not bandwidth-bound (docs/PERF.md §7)
                attn = decode_attention(
                    q, keys, values, mask, pos,
                    impl="xla" if self.attn_impl == "xla" else "fused",
                )
        elif self.attn_impl in ("ring", "ulysses", "ulysses_flash"):
            # context-parallel attention over the 'seq' mesh axis
            # (tpudist.parallel.cp); activations arrive sequence-sharded and
            # the shard_map keeps them that way — requires ``mesh``
            if self.mesh is None:
                raise ValueError(
                    f"attn_impl={self.attn_impl!r} needs the model's mesh= "
                    "field set (the shard_map runs over its 'seq' axis)"
                )
            from tpudist.parallel.cp import ring_attention, ulysses_attention

            if self.attn_impl == "ring":
                attn = ring_attention(q, k, v, self.mesh, causal=True)
            else:
                attn_fn = None
                if self.attn_impl == "ulysses_flash":
                    # full-sequence attention per head group via the best
                    # Pallas kernel for the shape (vmem ≤1024 / blockwise
                    # flash ≥2048) — the long-context composition
                    # (all_to_all re-shard + fused-kernel softmax)
                    from tpudist.ops.attention import kernel_attention

                    attn_fn = kernel_attention
                attn = ulysses_attention(
                    q, k, v, self.mesh, causal=True, attn_fn=attn_fn
                )
        else:
            attn = multi_head_attention(
                q, k, v, causal=True, impl=self.attn_impl,
                # multi-chip Pallas runs need the per-shard shard_map wrap
                mesh=self.mesh,
            )
        # row-parallel: contraction dim sharded; GSPMD all-reduces the output
        y = nn.DenseGeneral(
            d, axis=(-2, -1), dtype=self.dtype, name="out",
            kernel_init=partitioned(dense_init, TENSOR_AXIS, None, None),
        )(attn)
        if fused:
            # one kernel sweep: residual add + LN (+ the compute-dtype
            # cast); both the normed value and the updated residual
            # stream come back from the same HBM pass
            y, x = ln("ln_2")(drop(y), residual=x)
        else:
            x = x + drop(y)
            y = ln("ln_2")(x)
        if self.num_experts > 0:
            from tpudist.parallel.ep import MoEMlp

            y = MoEMlp(
                num_experts=self.num_experts, top_k=self.moe_top_k,
                capacity_factor=self.capacity_factor,
                dispatch_impl=self.moe_dispatch,
                router_z_loss=self.router_z_loss,
                router_jitter=self.router_jitter, dtype=self.dtype,
                mesh=self.mesh, name="moe",
            )(y, deterministic=not train)
        else:
            y = nn.Dense(
                4 * d, dtype=self.dtype, name="mlp_fc",
                kernel_init=partitioned(dense_init, None, TENSOR_AXIS),
                bias_init=partitioned(nn.initializers.zeros_init(), TENSOR_AXIS),
            )(y)
            y = nn.gelu(y)
            y = nn.Dense(
                d, dtype=self.dtype, name="mlp_proj",
                kernel_init=partitioned(dense_init, TENSOR_AXIS, None),
            )(y)
        return x + drop(y)


class _CarryBlock(nn.Module):
    """:class:`Block` with the (carry, xs) -> (carry, ys) signature
    ``nn.scan`` maps over (``train`` rides as a field; dropout rngs are
    split per layer by the scan)."""

    num_heads: int
    train: bool = True
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    mesh: Any = None
    dropout: float = 0.0
    fused_ln: bool = False

    @nn.compact
    def __call__(self, x, _):
        x = Block(
            self.num_heads, dtype=self.dtype, attn_impl=self.attn_impl,
            mesh=self.mesh, dropout=self.dropout, fused_ln=self.fused_ln,
            name="block",
        )(x, train=self.train)
        return x, None


class GPT2(nn.Module):
    vocab_size: int = 50257
    max_seq_len: int = 1024
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    dtype: Any = jnp.float32
    attn_impl: str = "xla"
    # num_experts > 0 makes every ``moe_every``-th block an MoE block
    # (tpudist.parallel.ep); aux load-balance losses are sowed into the
    # ``losses`` collection, which tpudist.train adds to the task loss
    num_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    # dispatch impl + router hardening, threaded into every MoE block
    # (see Block / tpudist.parallel.ep.MoEMlp)
    moe_dispatch: str = "einsum"
    router_z_loss: float = 0.0
    router_jitter: float = 0.0
    mesh: Any = None
    dropout: float = 0.0  # embedding + residual dropout (GPT-2 paper: 0.1)
    # scan_layers=True runs the depth as ONE nn.scan'd block (params stacked
    # [depth, ...], one traced layer at any depth — see the Llama field of
    # the same name). Dense blocks only; decode/MoE use the unrolled layout.
    scan_layers: bool = False
    # remat_layers=True checkpoints each scanned layer (store layer
    # boundaries, recompute inside) — requires scan_layers; legacy sugar
    # for remat_policy="full"
    remat_layers: bool = False
    # per-BLOCK rematerialization policy (tpudist.remat names: "full",
    # "dots_saveable", "save_nothing"; None/"none" off). Works in BOTH
    # layouts — scanned (policy on the scanned body) and unrolled (each
    # h_{i} checkpointed, param names unchanged) — so deep models trade
    # recompute for activation HBM without switching layouts. Ignored on
    # the decode path (the KV-cache step has no backward).
    remat_policy: str | None = None
    # fused_ln=True runs every LayerNorm (ln_1/ln_2/ln_f) through the
    # Pallas fused residual-add+LN kernel (tpudist.ops.layernorm) — the
    # non-GEMM-tail lever of docs/PERF.md §4c. Same param tree as the
    # flax modules; decode keeps the reference composition. Usually set
    # via make_train_step(fused="ln"|"all"), which clones the model.
    fused_ln: bool = False

    @property
    def has_aux_loss(self) -> bool:
        return self.num_experts > 0

    @property
    def flops_counter(self) -> str | None:
        """Analytic-FLOPs family tag (tpudist.telemetry.flops) — the MFU
        numerator dispatch. MoE geometries get their own counter
        ("gpt2_moe": active-param accounting — routed experts count
        ``top_k`` FFNs per MoE block plus the router GEMM), so MFU rows
        stay real for sparse models."""
        return "gpt2_moe" if self.num_experts > 0 else "gpt2"

    def init_cache(self, batch_size: int):
        """Zeroed decode KV cache for ``batch_size`` rows — the serving
        engine's slot-pool allocation hook (``tpudist.serve.slots``); built
        via ``eval_shape`` so no params materialize."""
        from tpudist.generate import zero_cache

        return zero_cache(self, batch_size)

    @nn.compact
    def __call__(self, tokens, train: bool = True, return_hidden: bool = False,
                 decode: bool = False, positions=None, block_tables=None):
        b, s = tokens.shape
        wte = self.param(
            "wte",
            _partitioned(nn.initializers.normal(0.02), TENSOR_AXIS, None),
            (self.vocab_size, self.hidden_dim), jnp.float32,
        )
        wpe = self.param(
            "wpe", nn.initializers.normal(0.01), (self.max_seq_len, self.hidden_dim), jnp.float32
        )
        if decode and positions is not None:
            # slot-pooled decode (tpudist.serve): each row reads its wpe
            # entry at its OWN per-slot cursor; the scalar counter below is
            # neither read nor advanced (the engine owns per-slot lengths),
            # but stays declared so the cache tree matches the scalar path
            self.variable("cache", "position", lambda: jnp.zeros((), jnp.int32))
            positions = jnp.asarray(positions, jnp.int32)
            # per-ENTRY overrun: row b's chunk entry i sits at pos_b + i.
            # s > 1 is the speculative verify chunk (tpudist.serve.spec),
            # whose tail may legitimately poke past the table on a
            # near-end row — those entries NaN-poison individually (their
            # K/V writes self-clamp in cached_kv and the engine's
            # acceptance cap never consumes their logits), while an
            # eagerly-detected FULLY-overrun row still fails loudly.
            row_pos = positions[:, None] + jnp.arange(s)[None, :]  # [B, s]
            overrun = row_pos + 1 > self.max_seq_len
            # probe OVERRUN for tracer-ness, not positions: under jit a
            # closed-over concrete positions array still yields a traced
            # comparison (constants lift to tracers inside the trace)
            if not isinstance(overrun, jax.core.Tracer) and bool(
                jnp.any(overrun[:, 0])
            ):
                raise ValueError(
                    f"per-slot decode past max_seq_len {self.max_seq_len} "
                    f"(positions {positions}); the KV cache and wpe table "
                    "end there"
                )
            pos = jnp.take(
                wpe, jnp.minimum(row_pos, self.max_seq_len - 1), axis=0
            )  # [B, s, d]
            pos = jnp.where(overrun[:, :, None], jnp.nan, pos)
        elif decode:
            # learned positions follow the cache cursor, not [0, s); the
            # init trace only creates the counter (no advance)
            initialized = self.has_variable("cache", "position")
            pos_var = self.variable(
                "cache", "position", lambda: jnp.zeros((), jnp.int32)
            )
            # overrun guard, same contract as T5's decode path: past
            # max_seq_len the wpe dynamic_slice (and the KV caches'
            # update) would clamp silently; fail loudly eagerly, NaN-
            # poison the step under jit (generate() bounds-checks at
            # entry, so the guarded path never pays it)
            cursor = pos_var.value
            overrun = cursor + s > self.max_seq_len
            if not isinstance(cursor, jax.core.Tracer) and bool(overrun):
                raise ValueError(
                    f"incremental decode past max_seq_len "
                    f"{self.max_seq_len} (cursor {int(cursor)} + chunk "
                    f"{s}); the KV cache and wpe table end there"
                )
            pos = jax.lax.dynamic_slice(wpe, (cursor, 0),
                                        (s, self.hidden_dim))
            pos = jnp.where(overrun, jnp.nan, pos)
            if initialized:
                pos_var.value = cursor + s
        else:
            pos = wpe[:s]
        x = wte[tokens].astype(self.dtype) + pos.astype(self.dtype)
        if self.dropout:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        from tpudist.remat import remat_module

        block_policy = self.remat_policy or (
            "full" if self.remat_layers else None
        )
        if self.scan_layers:
            if decode:
                raise ValueError(
                    "scan_layers has no decode path (the KV cache needs "
                    "per-layer variables); generate with scan_layers=False"
                )
            if self.num_experts:
                raise ValueError("scan_layers supports dense blocks only")
            body = remat_module(_CarryBlock, block_policy)
            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=self.depth,
                metadata_params={nn.PARTITION_NAME: None},
            )(
                num_heads=self.num_heads, train=train, dtype=self.dtype,
                attn_impl=self.attn_impl, mesh=self.mesh,
                dropout=self.dropout, fused_ln=self.fused_ln, name="hs",
            )
            x, _ = scanned(x, None)
        elif self.remat_layers:
            raise ValueError("remat_layers requires scan_layers=True "
                             "(set remat_policy to checkpoint the unrolled "
                             "blocks, or make_train_step(remat=...) for a "
                             "whole-forward checkpoint)")
        else:
            # per-block checkpoint in the unrolled layout too: h_{i} param
            # names unchanged (nn.remat is name-transparent), train/decode/
            # max_len static (they steer python-level structure)
            block_cls = (
                remat_module(Block, block_policy, static_argnums=(2, 3, 4))
                if not decode else Block
            )
            for i in range(self.depth):
                moe_here = self.num_experts > 0 and (i % self.moe_every == self.moe_every - 1)
                x = block_cls(
                    self.num_heads, dtype=self.dtype, attn_impl=self.attn_impl,
                    num_experts=self.num_experts if moe_here else 0,
                    moe_top_k=self.moe_top_k, capacity_factor=self.capacity_factor,
                    moe_dispatch=self.moe_dispatch,
                    router_z_loss=self.router_z_loss,
                    router_jitter=self.router_jitter,
                    mesh=self.mesh, dropout=self.dropout,
                    fused_ln=self.fused_ln, name=f"h_{i}",
                )(x, train, decode, self.max_seq_len,
                  # only the (remat-free) decode path threads per-slot
                  # positions/block tables; the remat wrapper's
                  # static_argnums contract stays untouched
                  **({"positions": positions,
                      "block_tables": block_tables} if decode else {}))
        if self.fused_ln and not decode:
            from tpudist.ops.layernorm import FusedLayerNorm

            x = FusedLayerNorm(
                epsilon=1e-5, dtype=self.dtype, mesh=self.mesh, name="ln_f"
            )(x)
        else:
            x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln_f")(x)
        if return_hidden:
            # the chunked-CE path (chunked_lm_forward) applies the tied head
            # per sequence chunk so the [B,S,V] f32 logits never materialize
            return x
        # weight-tied LM head
        logits = jnp.einsum(
            "bsd,vd->bsv", x, wte.astype(self.dtype), preferred_element_type=jnp.float32
        )
        return logits


def gpt2_124m(**kw) -> GPT2:
    return GPT2(**kw)


def gpt2_medium(**kw) -> GPT2:
    """GPT-2 medium (355M): 24 layers, 1024 hidden, 16 heads."""
    kw.setdefault("hidden_dim", 1024)
    kw.setdefault("depth", 24)
    kw.setdefault("num_heads", 16)
    return GPT2(**kw)


def gpt2_large(**kw) -> GPT2:
    """GPT-2 large (774M): 36 layers, 1280 hidden, 20 heads."""
    kw.setdefault("hidden_dim", 1280)
    kw.setdefault("depth", 36)
    kw.setdefault("num_heads", 20)
    return GPT2(**kw)


# family-neutral home; re-exported here for the established import path
from tpudist.models.lm_utils import chunked_lm_forward  # noqa: E402,F401


def stack_gpt2_params(variables, depth: int):
    """Convert a plain (unrolled) :class:`GPT2` param tree into
    :class:`PipelinedGPT2`'s stacked layout.

    The per-layer subtrees ``h_0 .. h_{depth-1}`` are stacked leaf-for-leaf
    into ``blocks`` with a new leading ``[depth]`` dim boxed over ``pipe``;
    boxed leaves keep their tensor-axis names shifted past the layer dim
    (Megatron TP-within-stage), and ``wte``/``wpe``/``ln_f`` pass through
    with their boxes. Because this is a pure re-layout, a
    ``PipelinedGPT2`` holding the converted params computes the *identical
    function* as the source model — the property the PP agreement
    certification relies on, and what enables warm-starting the pipelined
    model from an unrolled checkpoint (``examples/train_gpt2.py`` routes
    ``--init_hf --pipe`` through this conversion). Accepts boxed or
    unboxed trees, and a full ``{"params": ...}`` variables dict or a bare
    param tree.
    """
    p = variables["params"] if "params" in variables else variables

    def is_box(x):
        return isinstance(x, nn.Partitioned)

    def stack(*leaves):
        if is_box(leaves[0]):
            vals = [leaf.value for leaf in leaves]
            names = leaves[0].names
        else:
            vals = list(leaves)
            names = (None,) * jnp.ndim(leaves[0])
        return nn.Partitioned(jnp.stack(vals), names=(PIPELINE_AXIS, *names))

    blocks = jax.tree_util.tree_map(
        stack, *[p[f"h_{i}"] for i in range(depth)], is_leaf=is_box
    )
    return {
        "params": {
            "wte": p["wte"],
            "wpe": p["wpe"],
            "blocks": blocks,
            "ln_f": p["ln_f"],
        }
    }


class PipelinedGPT2:
    """GPT-2 with its blocks stacked ``[depth, ...]`` and run through GPipe
    microbatch pipelining over the ``pipe`` mesh axis
    (``tpudist.parallel.pp``).

    Duck-types the flax ``init``/``apply`` surface that
    ``tpudist.train.create_train_state``/``make_train_step`` drive, so the
    ordinary compiled train step works unchanged: ``init`` boxes the stacked
    block params with ``nn.Partitioned(('pipe', ...))`` metadata, which
    ``create_train_state`` turns into layer-over-stage placement (and
    matching Adam-moment shardings); ``apply`` embeds, pipelines the blocks,
    and runs the stage-replicated final LayerNorm + weight-tied head.

    ``init`` is *init-by-conversion*: it initializes the plain unrolled
    :class:`GPT2` twin with the caller's rng and re-stacks its params
    (:func:`stack_gpt2_params`), so the same seed yields the same function
    as the plain model — making PP certifiable against the DP reference
    (and every Adam update identical, since the stacked layout is a pure
    re-indexing of the same leaves). The blocks' Megatron ``tensor``
    shardings survive the conversion, and the pipeline's ``shard_map`` is
    manual over ``pipe`` only, so PP×TP (and ×DP) composes under GSPMD —
    see ``tpudist.parallel.pp``.

    Embedding/head stay outside the pipeline (computed replicated over
    ``pipe``) — standard for shallow heads; the depth is where the memory is.

    ``schedule`` selects the microbatch schedule (``tpudist.parallel.pp``):
    ``"gpipe"`` (default) or ``"1f1b"`` — same function and gradients,
    different backward memory profile (1F1B banks stage inputs and
    recomputes internals in its interleaved backward ring).
    """

    def __init__(
        self,
        mesh,
        *,
        num_micro: int,
        vocab_size: int = 50257,
        max_seq_len: int = 1024,
        hidden_dim: int = 768,
        depth: int = 12,
        num_heads: int = 12,
        dtype: Any = jnp.float32,
        attn_impl: str = "xla",
        schedule: str = "gpipe",
    ):
        if depth % mesh.shape[PIPELINE_AXIS]:
            raise ValueError(
                f"depth {depth} not divisible by pipe={mesh.shape[PIPELINE_AXIS]}"
            )
        if attn_impl != "xla":
            # pallas_call inside the pipe-manual shard_map region trips the
            # varying-manual-axes checks in the kernels' interpret/backward
            # scans — refuse loudly rather than fail with a cryptic trace
            raise ValueError(
                f"attn_impl={attn_impl!r} does not compose with the GPipe "
                "schedule yet; the pipelined model runs XLA attention "
                "(attn_impl='xla')"
            )
        from tpudist.parallel.pp import SCHEDULES

        if schedule not in SCHEDULES:
            raise ValueError(
                f"schedule must be one of {SCHEDULES}, got {schedule!r}"
            )
        self.mesh = mesh
        self.num_micro = num_micro
        self.vocab_size = vocab_size
        self.max_seq_len = max_seq_len
        self.hidden_dim = hidden_dim
        self.depth = depth
        self.num_heads = num_heads
        self.dtype = dtype
        self.schedule = schedule
        # the unrolled twin: the source of init (same seed -> same function)
        self.unrolled = GPT2(
            vocab_size=vocab_size, max_seq_len=max_seq_len,
            hidden_dim=hidden_dim, depth=depth, num_heads=num_heads,
            dtype=dtype, attn_impl=attn_impl,
        )
        # partitioning metadata on the apply-side Block is irrelevant (its
        # initializers never run — params arrive pre-boxed from the
        # conversion), so tp=False keeps the module free of boxing logic
        self.block = Block(num_heads, dtype=dtype, attn_impl=attn_impl, tp=False)

    @property
    def flops_counter(self) -> str:
        """Same analytic family as the unrolled twin (it IS the same
        function): pipelining is an execution schedule, and the MFU
        numerator must not vanish just because the depth moved onto the
        ``pipe`` axis — telemetry divides by the mesh's FULL chip count
        (``tpudist.telemetry.flops``)."""
        return "gpt2"

    def init(self, rng, tokens, train: bool = False):
        return stack_gpt2_params(
            self.unrolled.init(rng, tokens, train=train), self.depth
        )

    def apply(self, variables, tokens, train: bool = True):
        p = variables["params"]
        s = tokens.shape[1]
        x = p["wte"][tokens].astype(self.dtype) + p["wpe"][:s].astype(self.dtype)

        def block_fn(bp, h):
            return self.block.apply({"params": bp}, h)

        x = pipeline_apply(
            block_fn, p["blocks"], x, self.mesh, num_micro=self.num_micro,
            schedule=self.schedule,
        )
        # same module (and epsilon) as plain GPT2's ln_f
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype).apply({"params": p["ln_f"]}, x)
        return jnp.einsum(
            "bsd,vd->bsv", x, p["wte"].astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
