"""GPT-2 decoder in Flax — BASELINE.json config 5 (GPT-2 124M, DP + grad
accumulation, tokens/sec).

No reference counterpart (SURVEY.md §2.12); built for the LM leg of the
baseline ladder. TPU-first: causal attention through tpudist.ops (XLA or
Pallas flash path), bf16 compute with fp32 params, weight-tied LM head as a
single MXU matmul against the embedding table.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from tpudist.ops.attention import multi_head_attention


class Block(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        h = self.num_heads
        y = nn.LayerNorm(dtype=self.dtype, name="ln_1")(x)
        qkv = nn.DenseGeneral((3, h, d // h), dtype=self.dtype, name="qkv")(y)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = multi_head_attention(q, k, v, causal=True, impl=self.attn_impl)
        y = nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype, name="out")(attn)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype, name="ln_2")(x)
        y = nn.Dense(4 * d, dtype=self.dtype, name="mlp_fc")(y)
        y = nn.gelu(y)
        y = nn.Dense(d, dtype=self.dtype, name="mlp_proj")(y)
        return x + y


class GPT2(nn.Module):
    vocab_size: int = 50257
    max_seq_len: int = 1024
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    dtype: Any = jnp.float32
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        b, s = tokens.shape
        wte = self.param(
            "wte", nn.initializers.normal(0.02), (self.vocab_size, self.hidden_dim), jnp.float32
        )
        wpe = self.param(
            "wpe", nn.initializers.normal(0.01), (self.max_seq_len, self.hidden_dim), jnp.float32
        )
        x = wte[tokens].astype(self.dtype) + wpe[:s].astype(self.dtype)
        for i in range(self.depth):
            x = Block(self.num_heads, dtype=self.dtype, attn_impl=self.attn_impl, name=f"h_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        # weight-tied LM head
        logits = jnp.einsum(
            "bsd,vd->bsv", x, wte.astype(self.dtype), preferred_element_type=jnp.float32
        )
        return logits


def gpt2_124m(**kw) -> GPT2:
    return GPT2(**kw)
