"""Communication primitives: gradient bucketing, int8 quantization, and the
int8-wire ring all-reduce.

The reference's DDP Reducer flattens gradients into fixed-size buckets and
all-reduces each bucket asynchronously as backward produces it (SURVEY.md
§2.5) — in fp32, because NCCL reduces in the tensor's dtype. tpudist's
default path hands the whole reduction to XLA (one implicit psum from the
global-batch loss), which is optimal on ICI but bandwidth-bound on
multi-slice DCN links, where cross-slice gradient reduction becomes the
dominant step-time term once per-chip batch is fixed (arXiv:2204.06514 §5).
EQuARX (arXiv:2506.17615) shows a quantized all-reduce recovers most of that
bandwidth at negligible quality cost. This module is the primitive layer for
that path — :mod:`tpudist.parallel.dp` builds the train-step integration on
top of it:

- :class:`BucketLayout`: the DDP-bucket equivalent — a params-shaped tree
  flattened into ``[n_buckets, bucket_size]`` fp32 rows, zero-padded, with
  the bucket count rounded up to the reduce axis size so the ring can chunk
  evenly (the padding IS the "empty bucket" case and reduces as exact
  zeros).
- :func:`quantize_bucket` / :func:`dequantize`: symmetric int8 with one
  fp32 scale per bucket; stochastic rounding (unbiased — the property the
  error-feedback convergence argument needs) when a key is passed,
  round-to-nearest otherwise.
- :func:`ring_allreduce_quantized`: the EQuARX-style all-reduce as an
  explicit ring — reduce-scatter then all-gather via ``lax.ppermute``, ONE
  int8 payload (+ per-bucket fp32 scales) per hop, accumulation in fp32 on
  every hop (the "fp32 master accumulation": partial sums are dequantized,
  added in fp32, and re-quantized only for the wire). Every element crosses
  the link as 1 byte instead of 4, which is the whole point on a DCN-bound
  mesh; :meth:`BucketLayout.wire_bytes` does the exact accounting.

Also here (it is link plumbing, not data plumbing):
:func:`measure_h2d_mbps`, the host→device bandwidth probe ``fit()`` and
``bench.py`` use to tag link-bound runs instead of failing silently slow.
"""

from __future__ import annotations

import math
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tpudist.utils.compat import axis_size

# DDP's default bucket is 25 MB; ours is element-denominated so the int8 and
# fp32 accounting share it: 4 Mi elements = 16 MB fp32 / 4 MB int8 per
# bucket. Big enough that the per-bucket fp32 scale is <0.0001% overhead,
# small enough that a 124M-param model still spreads over ~30 buckets.
DEFAULT_BUCKET_ELEMS = 4 * 1024 * 1024


class BucketLayout:
    """How a gradient pytree maps onto fixed-size reduction buckets.

    ``flatten`` concatenates every leaf (raveled, cast fp32) into one vector,
    zero-pads it to ``n_buckets * bucket_size``, and views it as
    ``[n_buckets, bucket_size]``; ``unflatten`` inverts exactly.
    ``n_buckets`` is rounded up to a multiple of ``world`` so the ring
    all-reduce can split the buckets into ``world`` equal chunks — the
    rounding is what creates all-zero padding buckets, which quantize to
    q=0/scale=1 and cost wire bytes but no correctness (the "empty bucket"
    degenerate case is a first-class citizen, not an error).

    Shapes only — a layout built from a concrete tree, a tracer tree, or a
    ``jax.eval_shape`` result is the same layout.
    """

    def __init__(self, tree, world: int, bucket_size: int = DEFAULT_BUCKET_ELEMS):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if bucket_size < 1:
            raise ValueError(f"bucket_size must be >= 1, got {bucket_size}")
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            raise ValueError("BucketLayout needs at least one leaf")
        self.shapes = [tuple(np.shape(x)) for x in leaves]
        self.dtypes = [jnp.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype
                       for x in leaves]
        self.sizes = [math.prod(s) for s in self.shapes]
        self.total = sum(self.sizes)
        self.world = int(world)
        # cap the bucket at the model's per-chunk share: a model smaller
        # than world × bucket_size would otherwise pad to world full-size
        # buckets and reduce megabytes of zeros for kilobytes of grads
        self.bucket_size = max(1, min(
            int(bucket_size), -(-self.total // self.world)
        ))
        n = -(-self.total // self.bucket_size)  # ceil
        self.n_buckets = n + (-n % self.world)
        self.padded_total = self.n_buckets * self.bucket_size
        self.buckets_per_chunk = self.n_buckets // self.world

    def flatten(self, tree) -> jax.Array:
        """Tree → ``[n_buckets, bucket_size]`` fp32 buckets."""
        leaves = self.treedef.flatten_up_to(tree)
        flat = jnp.concatenate(
            [jnp.ravel(x).astype(jnp.float32) for x in leaves]
        )
        flat = jnp.pad(flat, (0, self.padded_total - self.total))
        return flat.reshape(self.n_buckets, self.bucket_size)

    def unflatten(self, buckets: jax.Array):
        """``[n_buckets, bucket_size]`` buckets → tree (original dtypes)."""
        flat = jnp.ravel(buckets)
        leaves, off = [], 0
        for shape, size, dtype in zip(self.shapes, self.sizes, self.dtypes):
            leaves.append(
                jax.lax.dynamic_slice_in_dim(flat, off, size)
                .reshape(shape).astype(dtype)
            )
            off += size
        return self.treedef.unflatten(leaves)

    # -- wire-byte accounting ---------------------------------------------

    def wire_bytes(self, method: str, *, reductions: int = 1) -> int:
        """Bytes THIS replica moves over the link per step.

        ``"quantized"``: the explicit ring — ``2·(world-1)`` hops (RS + AG),
        each carrying one chunk of ``padded_total/world`` int8 elements plus
        its ``buckets_per_chunk`` fp32 scales.
        ``"bucketed"``: the explicit fp32 all-reduce at the classic
        bandwidth-optimal AR cost, ``2·(world-1)/world · N · 4`` — the same
        bytes XLA's implicit psum moves, so it doubles as the fp32 baseline
        the quantized ratio is quoted against.
        ``reductions`` scales for schedules that reduce more than once per
        step (the double-buffered grad-accumulation overlap reduces every
        microbatch — docs/PERF.md §11 carries the trade's honest math).
        """
        w, n = self.world, self.padded_total
        if w == 1:
            return 0
        if method == "quantized":
            per = 2 * (w - 1) * (n // w + self.buckets_per_chunk * 4)
        elif method == "bucketed":
            per = round(2 * (w - 1) / w * n * 4)
        else:
            raise ValueError(f"no wire accounting for method {method!r}")
        return per * reductions


def quantize_bucket(x: jax.Array, key: jax.Array | None = None):
    """Symmetric int8 quantization along the last axis (one scale per
    bucket): ``q = round(x / scale)`` with ``scale = amax/127``.

    With ``key``, rounding is stochastic — ``floor(y + u)``, ``u~U[0,1)`` —
    so ``E[dequantize(q)] = x`` exactly; the unbiasedness is what lets the
    error-feedback residual argument go through (the carried error is
    zero-mean noise, not drift). An all-zero bucket (padding, or a dead
    layer) gets scale 1 and q=0: exact. A NON-FINITE bucket keeps its
    non-finite amax as the scale, so the dequantized value is non-finite
    too: a NaN amax would otherwise fail the ``amax > 0`` test, fall back
    to scale 1, and cast the NaN to int8 0 — LAUNDERING a poisoned
    gradient into finite garbage that no downstream non-finite guard
    (which all run on the dequantized values) could ever catch. Returns
    ``(q int8, scale fp32)`` with scale shaped ``[..., 1]`` for
    broadcast-dequantization.
    """
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    scale = jnp.where(jnp.isfinite(amax), scale, amax).astype(jnp.float32)
    y = x / scale
    if key is None:
        q = jnp.round(y)
    else:
        q = jnp.floor(y + jax.random.uniform(key, y.shape))
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale


def ring_allreduce_quantized(
    chunks: jax.Array, axis_name: str, key: jax.Array
) -> jax.Array:
    """int8-wire ring all-reduce — call INSIDE ``shard_map``.

    ``chunks``: this replica's full local value, ``[world, bpc, B]`` fp32
    (``BucketLayout`` buckets viewed as ``world`` ring chunks). Returns the
    element-wise SUM over the ``axis_name`` replicas, bit-identical on every
    replica (each chunk's final owner quantizes the finished sum once and
    that one ``(q, scale)`` pair is what every replica — owner included —
    dequantizes, so replicated params stay replicated to the bit).

    Reduce-scatter phase: ``world-1`` hops; each hop quantizes the running
    partial sum (per-bucket scale, stochastic rounding), ships int8+scales
    one neighbor over, and the receiver dequantizes and adds in fp32 — the
    fp32 master accumulation; quantization exists only on the wire.
    All-gather phase: ``world-1`` more hops broadcasting each finished
    chunk's int8 form around the ring.

    ``key`` must already be folded with this replica's ``axis_index`` (each
    replica quantizes different values, so the stochastic-rounding noise
    must be independent across replicas — a shared key would correlate it).
    """
    w = axis_size(axis_name)
    if w == 1:
        return chunks
    rank = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % w) for j in range(w)]

    def rs_hop(acc, s):
        # send the chunk whose partial sum we just extended; receive our
        # predecessor's and extend it with our local contribution
        send_idx = (rank - s) % w
        blk = jax.lax.dynamic_index_in_dim(acc, send_idx, 0, keepdims=False)
        q, scale = quantize_bucket(blk, jax.random.fold_in(key, s))
        q = jax.lax.ppermute(q, axis_name, perm)
        scale = jax.lax.ppermute(scale, axis_name, perm)
        recv_idx = (rank - s - 1) % w
        upd = jax.lax.dynamic_index_in_dim(
            acc, recv_idx, 0, keepdims=False
        ) + dequantize(q, scale)
        return jax.lax.dynamic_update_index_in_dim(acc, upd, recv_idx, 0), None

    acc, _ = jax.lax.scan(rs_hop, chunks, jnp.arange(w - 1))

    # after w-1 hops, chunk (rank+1) % w holds the full sum on this rank
    own = (rank + 1) % w
    q0, s0 = quantize_bucket(
        jax.lax.dynamic_index_in_dim(acc, own, 0, keepdims=False),
        jax.random.fold_in(key, w),
    )
    out = jnp.zeros_like(chunks)
    out = jax.lax.dynamic_update_index_in_dim(out, dequantize(q0, s0), own, 0)

    def ag_hop(carry, s):
        out, q, scale = carry
        q = jax.lax.ppermute(q, axis_name, perm)
        scale = jax.lax.ppermute(scale, axis_name, perm)
        idx = (rank - s) % w  # hop s delivers the chunk owned by rank-s-1
        out = jax.lax.dynamic_update_index_in_dim(
            out, dequantize(q, scale), idx, 0
        )
        return (out, q, scale), None

    (out, _, _), _ = jax.lax.scan(ag_hop, (out, q0, s0), jnp.arange(w - 1))
    return out


def reduce_buckets(
    buckets: jax.Array,
    residual: jax.Array | None,
    layout: BucketLayout,
    axis_name: str,
    key: jax.Array,
    *,
    method: str,
):
    """One gradient reduction over ``axis_name`` — call INSIDE ``shard_map``.

    ``buckets``: this replica's local gradient buckets (``layout.flatten``
    output). Returns ``(mean_buckets, new_residual)`` where ``mean_buckets``
    is the cross-replica MEAN (what the optimizer consumes) and
    ``new_residual`` carries the error feedback (``None`` in/out when EF is
    off or the method is exact).

    ``"bucketed"`` is the explicit fp32 path: one ``lax.psum`` per call —
    exact, no residual; it isolates the restructuring (explicit reduction,
    double-buffered overlap) from the quantization so the two levers can be
    A/B'd independently. ``"quantized"`` quantizes ONCE locally (per-bucket
    int8, stochastic rounding), banks ``x - dequantize(Q(x))`` as the next
    step's residual, and ring-all-reduces the quantized value with int8 on
    every hop. The residual is added BEFORE quantization — error feedback:
    what one step drops, a later step transmits.
    """
    if method == "bucketed":
        mean = jax.lax.psum(buckets, axis_name) / axis_size(axis_name)
        return mean, residual
    if method != "quantized":
        raise ValueError(f"unknown reduce method {method!r}")
    x = buckets if residual is None else buckets + residual
    q0, s0 = quantize_bucket(x, jax.random.fold_in(key, 0))
    xq = dequantize(q0, s0)
    new_residual = None if residual is None else x - xq
    w = axis_size(axis_name)
    chunks = xq.reshape(w, layout.buckets_per_chunk, layout.bucket_size)
    total = ring_allreduce_quantized(chunks, axis_name, jax.random.fold_in(key, 1))
    mean = total.reshape(layout.n_buckets, layout.bucket_size) / w
    return mean, new_residual


def measure_h2d_mbps(nbytes: int = 8 * 1024 * 1024) -> float:
    """Host→device link bandwidth, MB/s, by staging one ``nbytes`` buffer.

    Synced by VALUE FETCH, not ``block_until_ready`` — the remote-attach
    tunnel has been observed to release the latter before the copy lands
    (bench.py's probe rule). One 8 MB probe is ~amortization-free on a
    healthy link and diagnostic gold on a collapsed one (docs/PERF.md §3:
    a measured 7 MB/s attach is 0.08× on the e2e leg); ``fit()`` uses this
    to tag link-bound runs in telemetry instead of failing silently slow.
    """
    probe = np.zeros(max(int(nbytes), 1024), dtype=np.uint8)
    t0 = time.perf_counter()
    int(np.asarray(jax.device_put(probe)[-1]))
    return probe.nbytes / 1e6 / (time.perf_counter() - t0)


def multislice_dcn(devices: Any = None) -> bool:
    """True when the visible devices span more than one slice — i.e. the
    ``data`` axis crosses DCN, the regime where the quantized path pays
    (``reduce="auto"``'s decision input). Single-slice / CPU → False."""
    devices = jax.devices() if devices is None else devices
    return len({getattr(d, "slice_index", 0) for d in devices}) > 1
