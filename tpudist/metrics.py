"""Throughput/loss TSV logging — the reference's de-facto metrics API.

Reproduces the exact file contract of /root/reference/main.py:65-67,107-117:

- every rank opens ``{jobId}_{batch_size}_{global_rank}.log`` and writes the
  header ``datetime\tg_step\tg_img\tloss_value\texamples_per_sec``;
- only rank 0 appends rows, every ``log_every`` (5) global steps:
  ``{datetime.now()}\t{global_step*world_size}\t{global_step*world_size*batch_size}\t{loss}\t{examples_per_sec}``
  where ``examples_per_sec = batch_size / step_duration`` is *per-rank*
  throughput (a documented quirk of the reference — preserved for
  apples-to-apples baseline comparison, SURVEY.md §7 hard-part #4);
- rank 0 prints ``Epoch: {e} step: {idx} loss: {loss}`` every
  ``print_every`` (10) batches (/root/reference/main.py:113-114);
- a final ``TrainTime\t%f`` row with total wall seconds
  (/root/reference/main.py:117).
"""

from __future__ import annotations

import time
from datetime import datetime
from pathlib import Path

HEADER = "datetime\tg_step\tg_img\tloss_value\texamples_per_sec\n"


class MetricsLogger:
    def __init__(
        self,
        job_id: str,
        batch_size: int,
        global_rank: int,
        world_size: int,
        *,
        log_every: int = 5,
        print_every: int = 10,
        log_dir: str | Path = ".",
    ):
        self.job_id = job_id
        self.batch_size = batch_size
        self.global_rank = global_rank
        self.world_size = world_size
        self.log_every = log_every
        self.print_every = print_every
        Path(log_dir).mkdir(parents=True, exist_ok=True)
        self.file_name = Path(log_dir) / f"{job_id}_{batch_size}_{global_rank}.log"
        # every rank opens + writes the header; only rank 0 writes rows —
        # exact reference behavior (main.py:65-67 vs :107)
        self._file = open(self.file_name, "w")
        self._file.write(HEADER)
        self._file.flush()
        self._train_begin = time.time()
        self._sink = None

    def attach_sink(self, sink) -> None:
        """Dual-sink mode (tpudist.telemetry): every row this logger writes
        — throughput data rows, HBM rows, the TrainTime footer — is ALSO
        mirrored as a structured JSONL object into ``sink`` (a
        ``TelemetrySink``). The TSV side is untouched byte-for-byte: the
        reference contract is what baseline comparisons parse, the JSONL
        side is what dashboards parse, and neither needs the other."""
        self._sink = sink

    def start_timer(self) -> None:
        """Reset the TrainTime clock (reference starts it just before the
        epoch loop, main.py:87)."""
        self._train_begin = time.time()

    def log_step(self, global_step: int, loss_value: float, step_duration: float) -> None:
        """Call once per step on every rank; writes on rank 0 at the cadence.

        ``step_duration <= 0`` (a coarse clock under a sub-resolution CPU
        step, or wall-clock skew) would make the reference's
        ``batch_size / step_duration`` a ZeroDivisionError or an inf row;
        instead the row is written with ``0.0`` throughput under a
        ``ZeroDur`` tag — footer-style like ``HBM``/``TrainTime``, so plain
        data rows keep the guarantee that examples_per_sec is a real
        measurement."""
        if self.global_rank == 0 and global_step % self.log_every == 0:
            degenerate = step_duration <= 0.0
            examples_per_sec = (
                0.0 if degenerate else self.batch_size / step_duration
            )
            row = (
                f"{datetime.now()}\t{global_step * self.world_size}\t"
                f"{global_step * self.world_size * self.batch_size}\t"
                f"{loss_value}\t{examples_per_sec}\n"
            )
            if degenerate:
                row = "ZeroDur\t" + row
            self._file.write(row)
            self._file.flush()
            if self._sink is not None:
                self._sink.write(
                    "throughput", global_step,
                    g_step=global_step * self.world_size,
                    g_img=global_step * self.world_size * self.batch_size,
                    loss=loss_value,
                    examples_per_sec=examples_per_sec,
                    zero_duration=degenerate,
                )

    def print_progress(self, epoch: int, idx: int, loss_value: float) -> None:
        if self.global_rank == 0 and idx % self.print_every == 0:
            print("Epoch: {} step: {} loss: {}".format(epoch, idx, loss_value))

    def log_memory(self, stats: dict | None,
                   peak_bytes_in_use: int | None = None) -> None:
        """One ``HBM\\t{json}`` row (rank 0) with live device memory stats
        (``tpudist.memory.device_memory_stats``) — the measured side of the
        pre-compile HBM budget, written next to the throughput rows it
        explains. Footer-style like ``TrainTime`` (a tagged row, not a data
        row), so the reference's field-exact TSV contract is untouched.
        No-op when the backend reports nothing (CPU) or off rank 0.

        ``peak_bytes_in_use``, when given, is the PER-INTERVAL peak fit()
        derives from the allocator's lifetime high-water mark — it
        replaces the raw (monotone, spike-hiding) allocator value and is
        appended AFTER the existing fields in the JSONL row, so transient
        activation spikes between cadence rows stay visible. ``None``
        keeps both streams byte-identical to the pre-feature rows."""
        if not stats or self.global_rank != 0:
            return
        import json

        fields = dict(stats)
        if peak_bytes_in_use is not None:
            fields.pop("peak_bytes_in_use", None)
            fields["peak_bytes_in_use"] = int(peak_bytes_in_use)
        self._file.write("HBM\t%s\n" % json.dumps(fields, sort_keys=True))
        self._file.flush()
        if self._sink is not None:
            self._sink.write("memory", **fields)

    def finish(self) -> float:
        train_time = time.time() - self._train_begin
        self._file.write("TrainTime\t%f\n" % train_time)
        self._file.close()
        if self._sink is not None:
            self._sink.write("train_time", seconds=round(train_time, 6))
        return train_time

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if not self._file.closed:
            self.finish()
