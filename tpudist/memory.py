"""HBM accounting: byte budgets BEFORE compile, live device stats after.

The memory-discipline half of the perf story (docs/PERF.md §10): at ~1B
params on a 16 GB chip the question "will it fit?" must be answerable
before the first (minutes-long) compile, and the answer must be checkable
against what the device actually allocated. Three layers:

1. **per-tree bytes, exact** — :func:`tree_bytes` / :func:`per_device_bytes`
   work on concrete arrays, ``jax.eval_shape`` results, or (shape-tree,
   sharding-tree) pairs, so the params/master/moments budget costs one
   trace, no device.
2. **activation estimate, analytic** — :func:`transformer_activation_bytes`
   models the saved-residual footprint per remat policy (documented coarse
   coefficients; an estimate, clearly labeled as one).
3. **live stats** — :func:`device_memory_stats` surfaces the runtime
   allocator's view (``bytes_in_use``/``peak_bytes_in_use``/``bytes_limit``
   on TPU; ``None`` on backends that don't report, e.g. CPU), logged by
   ``fit()`` through ``MetricsLogger.log_memory``.

:func:`train_state_budget` assembles 1+2 into the report the bench's ~1B
leg prints: bytes-per-param for params / moments / activations, replicated
vs ``shard_state``, against a stated HBM budget.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import numpy as np

from tpudist.utils.tree import tree_bytes, tree_size

__all__ = [
    "tree_bytes",
    "tree_size",
    "per_device_bytes",
    "state_bytes",
    "transformer_activation_bytes",
    "train_state_budget",
    "device_memory_stats",
    "xla_memory_stats",
    "budget_columns",
    "format_budget",
]


def per_device_bytes(tree, shardings=None) -> int:
    """Bytes ONE device holds for ``tree``.

    ``tree`` may be concrete placed arrays (their own ``.sharding`` is
    used) or a shape tree (``jax.eval_shape`` output) paired with a
    matching ``shardings`` tree. Replicated leaves count in full; sharded
    leaves count their largest single-device shard (ceil division — the
    padded shard is what the allocator actually reserves).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if shardings is not None:
        # flatten the shardings UP TO the value tree's structure: a
        # structural mismatch raises (never a silent zip truncation), and
        # a None left at a leaf position survives as "replicated" instead
        # of being dropped by tree_leaves and misaligning every later pair
        shard_leaves = treedef.flatten_up_to(shardings)
    else:
        shard_leaves = [getattr(x, "sharding", None) for x in leaves]
    total = 0
    for x, s in zip(leaves, shard_leaves):
        shape = tuple(np.shape(x)) if not hasattr(x, "shape") else tuple(x.shape)
        if s is not None and hasattr(s, "shard_shape"):
            try:
                shape = s.shard_shape(shape)
            except ValueError:
                # an indivisible dim (e.g. an unpadded vocab under a
                # tensor split): jax refuses the placement at runtime,
                # but the BUDGET question "what would one chip hold" is
                # still answerable — ceil per dim, the padded shard the
                # allocator would reserve
                shape = _ceil_shard_shape(shape, s)
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(x.dtype).itemsize
    return total


def _ceil_shard_shape(shape, sharding) -> tuple:
    """Ceil-division per-device shard shape from a NamedSharding's spec —
    the fallback for dims the mesh axes don't divide evenly."""
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return shape
    out = list(shape)
    for i, part in enumerate(spec):
        if part is None or i >= len(out):
            continue
        names = part if isinstance(part, tuple) else (part,)
        factor = 1
        for name in names:
            if name is not None:
                factor *= int(mesh.shape[name])
        out[i] = -(-out[i] // factor)
    return tuple(out)


def state_bytes(state, shardings=None) -> dict[str, dict[str, int]]:
    """Per-component byte table for a TrainState(-shaped) tree.

    Returns ``{component: {"global": bytes, "per_device": bytes}}`` for
    ``params`` / ``opt_state`` / ``batch_stats`` plus a ``total`` row.
    ``state`` may be concrete or an ``eval_shape`` result (then pass the
    matching ``shardings`` tree, e.g. from ``optim.shard_state``'s
    ``state_shardings`` — that pairing is how the pre-compile budget knows
    the moments will live at ~1/world_size per chip).
    """
    out: dict[str, dict[str, int]] = {}
    total_g = total_d = 0
    for name in ("params", "opt_state", "batch_stats"):
        sub = getattr(state, name, None)
        if sub is None:
            continue
        sh = getattr(shardings, name, None) if shardings is not None else None
        g = tree_bytes(sub)
        d = per_device_bytes(sub, sh)
        out[name] = {"global": g, "per_device": d}
        total_g += g
        total_d += d
    out["total"] = {"global": total_g, "per_device": total_d}
    return out


def transformer_activation_bytes(
    batch: int,
    seq: int,
    hidden: int,
    depth: int,
    *,
    num_heads: int | None = None,
    remat_policy: str | bool | None = "none",
    dtype_bytes: int = 2,
    ffn_mult: int = 4,
    attention_scores: bool = False,
) -> int:
    """ESTIMATED live activation bytes of one transformer microbatch's
    forward, as held for backward under ``remat_policy``.

    Coarse per-token-per-layer accounting (bf16 default), stated so the
    numbers are auditable rather than mysterious:

    - ``none``: every block internal is saved — residual in + 2 norms +
      qkv (3H) + attn out + proj in + mlp up (ffn_mult·H) + gelu
      (ffn_mult·H) + proj ≈ ``(8 + 2·ffn_mult)·H`` per layer;
    - ``dots_saveable``: dot/MXU outputs only — qkv (3H) + attn out +
      mlp up (ffn_mult·H) + proj ≈ ``(5 + ffn_mult)·H``;
    - ``full`` / ``save_nothing`` (per-block checkpoint): the inter-block
      residual stream (1·H per layer) plus ONE block's internals live
      during its recompute.

    ``attention_scores=True`` adds the [B, heads, S, S] score matrix per
    layer (the XLA-attention path; the fused kernels never materialize
    it). Plus the embedding output once. This is an estimate for budget
    tables — the measured check is :func:`device_memory_stats`.
    """
    per_tok = {
        "none": (8 + 2 * ffn_mult) * hidden,
        "dots_saveable": (5 + ffn_mult) * hidden,
        "full": hidden,
        "save_nothing": hidden,
    }
    key = {False: "none", None: "none", True: "full"}.get(
        remat_policy, remat_policy
    )
    if key not in per_tok:
        raise ValueError(f"unknown remat policy {remat_policy!r}")
    tokens = batch * seq
    per_layer = per_tok[key] * tokens
    if attention_scores and key in ("none", "dots_saveable"):
        per_layer += (num_heads or 1) * batch * seq * seq
    total = depth * per_layer + tokens * hidden  # + embedding output
    if key in ("full", "save_nothing"):
        # one block's internals, alive during its backward recompute
        total += (8 + 2 * ffn_mult) * hidden * tokens
    return int(total) * dtype_bytes


def train_state_budget(
    model,
    tx,
    sample_input,
    *,
    batch: int,
    seq: int,
    world_size: int = 1,
    remat_policy: str | bool | None = "none",
    grad_dtype_bytes: int = 4,
    hbm_budget_bytes: int = 16 * 1024**3,
    workspace_fraction: float = 0.08,
    plan=None,
) -> dict[str, Any]:
    """The pre-compile fits-or-not report for one LM training config.

    One ``jax.eval_shape`` trace (no device, no compile — a ~1B model
    costs seconds on a laptop) yields exact params/opt-state bytes;
    activations come from :func:`transformer_activation_bytes` using the
    model's ``hidden_dim``/``depth``/``num_heads`` fields; gradients count
    one params-sized fp32 tree (the donated step's transient);
    ``workspace_fraction`` reserves allocator/fusion scratch. Optimizer
    state divides by ``world_size`` when ``tx`` is a
    ``tpudist.optim.shard_state`` wrapper (its own ``state_shardings``
    rule is consulted leaf-for-leaf — exact, not world_size-rounded).

    Returns a dict with per-component bytes (global and per-chip), the
    per-chip total, ``fits`` against ``hbm_budget_bytes``, and
    ``bytes_per_param`` — the budget-table row docs/PERF.md §10 prints.

    ``plan`` (:class:`tpudist.parallel.plan.ParallelPlan`) makes the
    whole table PER-CHIP under the composed placement: params and
    gradients count their largest single-chip shard (the plan's resolved
    metadata+fsdp shardings — exact, from the same ``eval_shape``),
    opt-state follows the plan's ZeRO-1 overlay (pass the
    ``plan.wrap_zero1``-wrapped ``tx``), and the activation ESTIMATE is
    scaled by the plan's axes (batch over ``data×fsdp``, depth over
    ``pipe``, block internals over ``tensor`` — coarse like the base
    estimate, labeled as one). This is the pre-compile answer to "does
    this geometry fit ONLY under the plan?" — the ``parallel3d`` bench
    leg prints both sides.
    """
    import jax.numpy as jnp

    # boxed init so the plan can read the Megatron/pipe metadata; tree
    # math sees through the boxes, so the plan-less path is unchanged
    params_boxed = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.asarray(sample_input), train=False
        )["params"]
    )
    from flax import linen as nn

    params_shapes = nn.meta.unbox(params_boxed)
    n_params = tree_size(params_shapes)
    params_global = tree_bytes(params_shapes)
    params_bytes = params_global
    if plan is not None:
        params_bytes = per_device_bytes(
            params_shapes, plan.shardings(params_boxed)
        )
    opt_shapes = jax.eval_shape(tx.init, params_shapes)
    opt_global = tree_bytes(opt_shapes)
    if plan is not None:
        opt_per_chip = per_device_bytes(
            opt_shapes, plan.opt_state_shardings(params_boxed, tx)
        )
    elif hasattr(tx, "state_shardings"):
        opt_per_chip = per_device_bytes(
            opt_shapes, tx.state_shardings(params_shapes)
        )
    else:
        opt_per_chip = opt_global
    depth = int(getattr(model, "depth", 0) or 0)
    act_batch, act_depth, act_div = batch, depth, 1
    if plan is not None:
        # per-chip activation scaling, coarse by construction: each chip
        # sees batch/(data·fsdp) rows, depth/pipe layers, and 1/tensor of
        # every block-internal (qkv/ffn activations shard with their
        # kernels' output dims)
        act_batch = max(batch // (plan.data * plan.fsdp), 1)
        act_depth = max(-(-depth // plan.pipe), 1) if depth else depth
        act_div = plan.tensor
    acts = transformer_activation_bytes(
        act_batch, seq, int(getattr(model, "hidden_dim", 0) or 0),
        act_depth,
        num_heads=getattr(model, "num_heads", None),
        remat_policy=remat_policy,
        # "auto" may dispatch to the XLA path (shape-dependent), so it
        # counts the [B,H,S,S] scores too — over-budgeting is the safe
        # direction for a fits verdict; only an explicit kernel choice
        # (vmem/flash, which never materialize scores) drops the term
        attention_scores=getattr(model, "attn_impl", "xla") in ("xla", "auto"),
    ) // max(act_div, 1)
    # gradients are params-shaped transients: under a plan they live at
    # the params' sharded footprint (GSPMD reduce-scatters them), scaled
    # from the sharded params ratio so mixed fp32/bf16 trees stay honest
    grads = n_params * grad_dtype_bytes
    if plan is not None and params_global:
        grads = int(grads * params_bytes / params_global)
    subtotal = params_bytes + opt_per_chip + acts + grads
    per_chip_total = int(subtotal * (1.0 + workspace_fraction))
    out = {
        "n_params": int(n_params),
        "world_size": int(world_size),
        "remat_policy": str(remat_policy),
        "params_bytes": int(params_bytes),
        "opt_state_bytes_global": int(opt_global),
        "opt_state_bytes_per_chip": int(opt_per_chip),
        "grad_bytes": int(grads),
        "activation_bytes_est": int(acts),
        "workspace_bytes_est": int(per_chip_total - subtotal),
        "per_chip_total_bytes": per_chip_total,
        "hbm_budget_bytes": int(hbm_budget_bytes),
        "fits": bool(per_chip_total <= hbm_budget_bytes),
        "bytes_per_param": round(per_chip_total / max(n_params, 1), 2),
    }
    if plan is not None:
        out["params_bytes_global"] = int(params_global)
        out["plan"] = plan.describe()
        out.update(plan.axis_worlds())
    return out


def xla_memory_stats(compiled) -> dict[str, int] | None:
    """The compiler's own static HBM breakdown of a COMPILED program
    (``Compiled.memory_analysis()``, normalized by
    :func:`tpudist.telemetry.anatomy.program_memory`): argument / output /
    temp / generated-code bytes and the resident-sum ``peak_bytes``. The
    middle column of the budget table — between the pre-compile estimate
    and the live allocator — and fail-soft ``None`` on backends (or
    merely-lowered objects) that don't implement memory analysis."""
    from tpudist.telemetry.anatomy import program_memory

    return program_memory(compiled)


def budget_columns(report: Mapping[str, Any] | None = None, *,
                   compiled=None, device=None) -> dict[str, int | None]:
    """The three-source HBM comparison row (docs/PERF.md §10): the
    pre-compile analytic ESTIMATE, the compiler's XLA-STATIC reservation,
    and the LIVE allocator peak — each ``None`` where its source is
    unavailable (no report / no compiled program / a CPU backend), never
    a fabricated number. Estimate ≫ static usually means a stale
    activation model; live ≫ static means fragmentation or an allocator
    the program doesn't own alone."""
    xla = xla_memory_stats(compiled) if compiled is not None else None
    live = device_memory_stats(device)
    return {
        "estimate_bytes": (
            None if report is None else report.get("per_chip_total_bytes")
        ),
        "xla_static_bytes": None if xla is None else xla.get("peak_bytes"),
        "live_peak_bytes": (
            None if live is None else live.get("peak_bytes_in_use")
        ),
    }


def format_budget(report: Mapping[str, Any], *,
                  xla_static_bytes: int | None = None,
                  live_peak_bytes: int | None = None) -> str:
    """One human line per component, GB with the fits verdict — what the
    bench leg and PERF table print. ``xla_static_bytes`` /
    ``live_peak_bytes`` (from :func:`budget_columns`) append the measured
    columns next to the estimate when a compiled program / a reporting
    backend is at hand; ``None`` (the default, and what fail-soft sources
    return) leaves the line byte-identical to the estimate-only form."""
    gb = 1024**3

    def f(k):
        return f"{report[k] / gb:.2f}"

    line = (
        f"params {f('params_bytes')} GB + opt_state "
        f"{f('opt_state_bytes_per_chip')} GB/chip "
        f"(global {f('opt_state_bytes_global')}) + grads {f('grad_bytes')} "
        f"GB + acts~{f('activation_bytes_est')} GB (remat="
        f"{report['remat_policy']}) + ws~{f('workspace_bytes_est')} GB = "
        f"{f('per_chip_total_bytes')} GB/chip vs {f('hbm_budget_bytes')} GB"
        f" -> {'FITS' if report['fits'] else 'DOES NOT FIT'} "
        f"({report['bytes_per_param']} B/param, world={report['world_size']})"
    )
    if xla_static_bytes is not None:
        line += f" | xla-static {xla_static_bytes / gb:.2f} GB"
    if live_peak_bytes is not None:
        line += f" | live-peak {live_peak_bytes / gb:.2f} GB"
    return line


def device_memory_stats(device=None) -> dict[str, int] | None:
    """Live allocator stats for one device — ``bytes_in_use`` /
    ``peak_bytes_in_use`` / ``bytes_limit`` (whatever subset the backend
    reports), or ``None`` where unsupported (CPU). The measured
    counterpart of :func:`train_state_budget`."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats()
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_free_block_bytes")
    out = {k: int(v) for k, v in stats.items() if k in keep}
    return out or {k: int(v) for k, v in stats.items()}
