"""Autoregressive text generation with a KV cache.

No reference counterpart (the reference is a training-only CNN script); this
is the inference half every LM framework needs. TPU-first design: the whole
generation — prompt prefill and sampling — is ONE jit-compiled program.
Prefill is ONE bulk decode pass over the whole prompt (causal within the
chunk); sampling is a ``lax.scan`` of single-token decode steps. Both run
against a static-shaped head-major ``[B, H, max_seq_len, dh]`` KV cache
(:mod:`tpudist.ops.decode` — head-major so the fused decode kernel DMAs
each head's panel contiguously), so there is exactly one compilation
regardless of prompt length or tokens requested, and the cache never
reallocates.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def sample_logits(logits, rng, *, temperature: float = 1.0,
                  top_k: int | None = None, top_p: float | None = None):
    """One sampling step over ``[B, V]`` logits. ``temperature=0`` is
    greedy; ``top_k`` keeps the k most likely tokens (exactly k: on an
    exact tie at the k-th value the later tied ids are dropped, where a
    threshold formulation would keep them — see the inline note); ``top_p``
    keeps the smallest set of tokens whose probabilities sum to >= p
    (nucleus sampling). Filters compose in the HF order: temperature →
    top_k → top_p."""
    if temperature == 0.0:
        # top_k(1) indices, not jnp.argmax: same first-occurrence winner,
        # but measured 2.2 ms/step cheaper at (128, 50257) on v5e (argmax
        # lowers to a slower full-vocab reduction than the top-k kernel)
        return jax.lax.top_k(logits, 1)[1][:, 0].astype(jnp.int32)
    logits = logits / temperature

    def nucleus_thresh(sorted_desc):
        # nucleus: keep tokens whose EXCLUSIVE cumulative probability is
        # < p (the most likely token always survives); the threshold is
        # the last kept token's logit
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
        keep = exclusive_cum < top_p
        # the docstring's guarantee, unconditionally: at top_p <= 0.0 (or
        # denormal-tiny p) the exclusive-cum test keeps NOTHING, the
        # threshold becomes +inf and categorical samples over all -inf
        # logits — undefined output. HF guards the same edge with
        # min_tokens_to_keep=1; position 0 of the descending sort IS the
        # most likely token, so force-keep it.
        keep = keep.at[..., 0].set(True)
        return jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )

    if top_k is not None:
        # sample IN THE TOP-K SUBSET: categorical over the k kept values
        # and map the winner back through the top-k indices. The
        # full-vocab formulation paid a [B, V] gumbel + reduction per
        # token — measured ~8 ms/step at (128, 50257) on v5e, i.e. more
        # than the entire 12-layer transformer step (docs/PERF.md §7b);
        # the subset pays it on [B, k]. Tie semantics: EXACTLY k ids are
        # candidates — ids tied with the k-th value beyond the k-th slot
        # are dropped (a `logits < kth` threshold, like HF's warper,
        # keeps every tied id). Tied ids carry equal probability, so this
        # only narrows which of the exchangeable tied ids can appear; for
        # float logits ties have measure zero.
        k = min(top_k, logits.shape[-1])  # clamp k > vocab, like HF/torch
        topk_vals, topk_idx = jax.lax.top_k(logits, k)  # [B, k], sorted
        if top_p is not None and top_p < 1.0:
            # composed filters: after the top-k cut only the k kept logits
            # carry probability mass, so the nucleus threshold over the
            # full filtered vocab equals the one over the (already sorted)
            # top-k values — no [B, V] sort
            topk_vals = jnp.where(
                topk_vals < nucleus_thresh(topk_vals), -jnp.inf, topk_vals
            )
        choice = jax.random.categorical(rng, topk_vals, axis=-1)
        return jnp.take_along_axis(
            topk_idx, choice[:, None], axis=-1
        )[:, 0].astype(jnp.int32)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        logits = jnp.where(
            logits < nucleus_thresh(sorted_logits), -jnp.inf, logits
        )
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _sample_scan(decode_step, cache, first_logits, rng, *, max_new_tokens,
                 temperature, top_k, top_p, eos_id=None, pad_id=0):
    """The shared sampling loop of both generation paths: scan
    ``max_new_tokens`` (sample from the previous position's logits, decode
    one step) iterations. The final carry's logits go unused — the last
    decode_step primes a position that is never sampled.

    ``eos_id``: rows that have emitted it produce ``pad_id`` from then on
    (the sequence stays static-shaped — the TPU way to "stop"; the cache
    keeps advancing, which is harmless since padded positions are never
    read back). The scan always runs ``max_new_tokens`` steps: a
    data-dependent early exit would force a ``while_loop`` that defeats
    the fixed-shape single compilation."""

    def sample_step(carry, _):
        cache, last_logits, rng, done = carry
        rng, sub = jax.random.split(rng)
        tok = sample_logits(
            last_logits, sub, temperature=temperature, top_k=top_k,
            top_p=top_p,
        )
        if eos_id is not None:
            tok = jnp.where(done, pad_id, tok)
            done = done | (tok == eos_id)
        cache, next_logits = decode_step(cache, tok)
        return (cache, next_logits, rng, done), tok

    done0 = jnp.zeros(first_logits.shape[0], bool)
    (cache, _, _, _), toks = jax.lax.scan(
        sample_step, (cache, first_logits, rng, done0), None,
        length=max_new_tokens,
    )
    return toks.T  # [B, max_new_tokens]


def _zero_cache(init_fn):
    """Freshly-zeroed decode cache with ``init_fn``'s cache shapes — via
    ``eval_shape``, so the throwaway init never materializes a second copy
    of the params (``model.init`` would — a 2× HBM spike at 7B scale)."""
    shapes = jax.eval_shape(init_fn)["cache"]
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )


def _fetch_tokens(out) -> np.ndarray:
    """Generated device tokens → host numpy, multi-process-safe."""
    if not out.is_fully_addressable:
        # multi-process with sharded/global params: the jit output may span
        # hosts, and np.asarray on a non-addressable array raises; every
        # process runs the same decode on the same prompt, so allgathering
        # the token ids (tiny) yields the identical [B, T] everywhere.
        # tiled=True is required for global non-addressable inputs and
        # returns the global [B, T] (no leading process dim)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(out, tiled=True))
    return np.asarray(out)


def generate(
    model,
    params,
    prompt,
    max_new_tokens: int,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    seed: int = 0,
    eos_id: int | None = None,
    pad_id: int = 0,
) -> np.ndarray:
    """Continue ``prompt`` (``[B, P]`` int tokens) by ``max_new_tokens``.

    Works for any model with the decode contract (``decode=True`` +
    ``cache`` collection): GPT-2 and Llama. Returns ``[B, max_new_tokens]``
    int32. Greedy when ``temperature=0``, else temperature/top-k/top-p
    (nucleus) sampling. With ``eos_id``, rows that emit it produce
    ``pad_id`` thereafter (static shapes — the compiled program always
    runs ``max_new_tokens`` steps).
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    if p + max_new_tokens > model.max_seq_len:
        raise ValueError(
            f"prompt {p} + {max_new_tokens} new tokens exceeds the model's "
            f"max_seq_len {model.max_seq_len} (the KV cache size)"
        )

    cache = _zero_cache(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((b, 1), jnp.int32),
            train=False, decode=True,
        )
    )
    out = _run(
        model, params, cache, prompt, jax.random.key(seed),
        max_new_tokens=max_new_tokens, temperature=temperature, top_k=top_k,
        top_p=top_p, eos_id=eos_id, pad_id=pad_id,
    )
    return _fetch_tokens(out)


def generate_seq2seq(
    model,
    params,
    enc_tokens,
    max_new_tokens: int,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    seed: int = 0,
    start_id: int = 0,
    eos_id: int | None = None,
    pad_id: int = 0,
) -> np.ndarray:
    """Seq2seq generation for encoder-decoder models (T5): encode
    ``enc_tokens`` ``[B, Se]`` once, then autoregressively decode
    ``max_new_tokens`` tokens from ``start_id`` against the decoder's KV
    cache — all (encode + prefill + sampling) as ONE jit-compiled program,
    the same single-compilation contract as :func:`generate`. Returns
    ``[B, max_new_tokens]`` int32; same sampling controls as
    :func:`sample_logits`.

    The model must support the ``encode_only``/``decode`` entry points
    (:class:`tpudist.models.t5.T5`); the cache buffer is
    ``model.max_decode_len`` slots (the start token takes one).
    ``eos_id`` (T5's natural stop: its EOS ends the span-target sequence)
    pads each row with ``pad_id`` after its first EOS.
    """
    enc_tokens = jnp.asarray(enc_tokens, jnp.int32)
    if max_new_tokens + 1 > model.max_decode_len:
        raise ValueError(
            f"start token + {max_new_tokens} new tokens exceeds the "
            f"model's max_decode_len {model.max_decode_len} (the decoder "
            "KV cache size)"
        )
    out = _run_seq2seq(
        model, params, enc_tokens, jax.random.key(seed),
        max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, start_id=start_id, eos_id=eos_id,
        pad_id=pad_id,
    )
    return _fetch_tokens(out)


@partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "start_id", "eos_id", "pad_id"),
)
def _run_seq2seq(model, params, enc_tokens, rng, *, max_new_tokens,
                 temperature, top_k, top_p, start_id, eos_id, pad_id):
    b = enc_tokens.shape[0]
    enc = model.apply(
        {"params": params}, enc_tokens, train=False, encode_only=True
    )
    # the cache depends on the decoder side alone, so a length-1 dummy enc
    # keeps the throwaway init trace cheap
    cache = _zero_cache(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((b, 1), jnp.int32),
            train=False, decode=True,
            enc=jnp.zeros((b, 1, model.hidden_dim), enc.dtype),
        )
    )

    def decode_step(cache, tok):
        logits, updates = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            train=False, decode=True, enc=enc, mutable=["cache"],
        )
        return updates["cache"], logits[:, -1]

    cache, logits = decode_step(
        cache, jnp.full((b,), start_id, jnp.int32)
    )
    return _sample_scan(
        decode_step, cache, logits, rng, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p, eos_id=eos_id,
        pad_id=pad_id,
    )


@partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "eos_id", "pad_id"),
)
def _run(model, params, cache, prompt, rng, *, max_new_tokens, temperature,
         top_k, top_p, eos_id, pad_id):
    """One compiled program for prefill + sampling. ``params`` is a traced
    argument (not a closure constant), and jit caches on the static
    (model, length, sampling) config — repeated generate() calls with the
    same setup reuse the compilation."""

    def decode_chunk(cache, toks):
        """toks [B, s] → (updated cache, [B, V] logits for the position
        after the chunk's last token)."""
        logits, updates = model.apply(
            {"params": params, "cache": cache}, toks,
            train=False, decode=True, mutable=["cache"],
        )
        return updates["cache"], logits[:, -1]

    def decode_step(cache, tok):
        return decode_chunk(cache, tok[:, None])

    # BULK prefill: the whole prompt in ONE decode pass — cached_kv's mask
    # is causal within the chunk (slot t attendable by row i iff
    # t <= pos + i), so a P-token prompt costs one MXU-shaped forward
    # instead of a P-iteration scan of launch-bound single-token steps.
    # Measured at P=512, batch 8, GPT-2 124M on v5e: 127.5 vs 676.7 ms =
    # 5.3x (the 127.5 includes the attach's ~100 ms per-call floor;
    # docs/PERF.md §7b).
    cache, logits = decode_chunk(cache, prompt)
    return _sample_scan(
        decode_step, cache, logits, rng, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p, eos_id=eos_id,
        pad_id=pad_id,
    )
