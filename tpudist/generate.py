"""Autoregressive text generation with a KV cache.

No reference counterpart (the reference is a training-only CNN script); this
is the inference half every LM framework needs. TPU-first design: the whole
generation — prompt prefill and sampling — is ONE jit-compiled program.
Prefill is ONE bulk decode pass over the whole prompt (causal within the
chunk); sampling is a ``lax.scan`` of single-token decode steps. Both run
against a static-shaped head-major ``[B, H, max_seq_len, dh]`` KV cache
(:mod:`tpudist.ops.decode` — head-major so the fused decode kernel DMAs
each head's panel contiguously), so the cache never reallocates and the
compile count stays bounded: prompts are padded to power-of-two BUCKETS
(:func:`bucket_length`) with the true length a traced scalar, so repeated
calls with varying prompt lengths share a handful of compiled programs
instead of one per length.

The continuous-batching serving engine (:mod:`tpudist.serve`) builds on the
pieces here: :func:`zero_cache` allocates its slot pool,
:func:`sample_logits_per_row` is its vectorized per-slot sampler, and
:func:`eos_retire` is the ONE stop rule shared between :func:`generate`'s
in-scan masking and the engine's per-slot retirement.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def bucket_length(n: int, cap: int | None = None, *, minimum: int = 8) -> int:
    """Smallest power of two >= ``n`` (floored at ``minimum``), capped at
    ``cap`` — the shared prompt-padding rule of :func:`generate` and the
    serving prefiller (:mod:`tpudist.serve.prefill`). Bucketing is what
    keeps XLA's compile cache bounded under mixed-length traffic: every
    prompt length lands on one of ~log2(max_seq_len) shapes."""
    if n > (cap if cap is not None else n):
        raise ValueError(f"length {n} exceeds the bucket cap {cap}")
    b = minimum
    while b < n:
        b *= 2
    return min(b, cap) if cap is not None else b


def _nucleus_threshold_from_probs(sorted_desc, probs, top_p):
    """Nucleus (top-p) threshold over DESCENDING-sorted logits with their
    probabilities supplied by the caller (the per-row sampler's candidate
    subset carries full-vocab or filtered-subset probabilities depending
    on the row's filter mix): keep tokens whose EXCLUSIVE cumulative
    probability is < p (the most likely token always survives); the
    threshold is the last kept token's logit. ``top_p`` is a python float
    (scalar sampling) or a ``[B, 1]`` array (the per-row sampler)."""
    exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
    keep = exclusive_cum < top_p
    # the docstring's guarantee, unconditionally: at top_p <= 0.0 (or
    # denormal-tiny p) the exclusive-cum test keeps NOTHING, the
    # threshold becomes +inf and categorical samples over all -inf
    # logits — undefined output. HF guards the same edge with
    # min_tokens_to_keep=1; position 0 of the descending sort IS the
    # most likely token, so force-keep it.
    keep = keep.at[..., 0].set(True)
    return jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )


def _nucleus_threshold(sorted_desc, top_p):
    """The scalar-path flavor: probabilities are the softmax of the
    (already filtered) sorted values themselves."""
    return _nucleus_threshold_from_probs(
        sorted_desc, jax.nn.softmax(sorted_desc, axis=-1), top_p
    )


def sample_logits(logits, rng, *, temperature: float = 1.0,
                  top_k: int | None = None, top_p: float | None = None):
    """One sampling step over ``[B, V]`` logits. ``temperature=0`` is
    greedy; ``top_k`` keeps the k most likely tokens (exactly k: on an
    exact tie at the k-th value the later tied ids are dropped, where a
    threshold formulation would keep them — see the inline note); ``top_p``
    keeps the smallest set of tokens whose probabilities sum to >= p
    (nucleus sampling). Filters compose in the HF order: temperature →
    top_k → top_p."""
    if temperature == 0.0:
        # top_k(1) indices, not jnp.argmax: same first-occurrence winner,
        # but measured 2.2 ms/step cheaper at (128, 50257) on v5e (argmax
        # lowers to a slower full-vocab reduction than the top-k kernel)
        return jax.lax.top_k(logits, 1)[1][:, 0].astype(jnp.int32)
    logits = logits / temperature

    if top_k is not None:
        # sample IN THE TOP-K SUBSET: categorical over the k kept values
        # and map the winner back through the top-k indices. The
        # full-vocab formulation paid a [B, V] gumbel + reduction per
        # token — measured ~8 ms/step at (128, 50257) on v5e, i.e. more
        # than the entire 12-layer transformer step (docs/PERF.md §7b);
        # the subset pays it on [B, k]. Tie semantics: EXACTLY k ids are
        # candidates — ids tied with the k-th value beyond the k-th slot
        # are dropped (a `logits < kth` threshold, like HF's warper,
        # keeps every tied id). Tied ids carry equal probability, so this
        # only narrows which of the exchangeable tied ids can appear; for
        # float logits ties have measure zero.
        k = min(top_k, logits.shape[-1])  # clamp k > vocab, like HF/torch
        topk_vals, topk_idx = jax.lax.top_k(logits, k)  # [B, k], sorted
        if top_p is not None and top_p < 1.0:
            # composed filters: after the top-k cut only the k kept logits
            # carry probability mass, so the nucleus threshold over the
            # full filtered vocab equals the one over the (already sorted)
            # top-k values — no [B, V] sort
            topk_vals = jnp.where(
                topk_vals < _nucleus_threshold(topk_vals, top_p),
                -jnp.inf, topk_vals,
            )
        choice = jax.random.categorical(rng, topk_vals, axis=-1)
        return jnp.take_along_axis(
            topk_idx, choice[:, None], axis=-1
        )[:, 0].astype(jnp.int32)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        logits = jnp.where(
            logits < _nucleus_threshold(sorted_logits, top_p),
            -jnp.inf, logits,
        )
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


# the per-row sampler resolves its filters inside a static top-K candidate
# subset (one lax.top_k, no [B, V] sort in the serving hot path — the same
# full-vocab-chain trap the scalar sampler's subset rework removed,
# docs/PERF.md §7b). Per-row top_k clamps to the cap; a nucleus that would
# extend past the cap truncates there — at serving temperatures the
# nucleus lives far inside 128 candidates.
PER_ROW_TOPK_CAP = 128


def _per_row_warp(logits, temperature, top_k, top_p):
    """The per-row filter resolution shared by :func:`sample_logits_per_row`
    and :func:`per_row_log_probs`: temperature scaling, the static
    top-``PER_ROW_TOPK_CAP`` candidate subset, and the composed top-k /
    nucleus cut expressed as ONE per-row value threshold. Factored out so
    the speculative-decoding acceptance ratio (:mod:`tpudist.serve.spec`)
    scores EXACTLY the distribution the sampler draws from — any drift
    between the two breaks the acceptance-rejection identity."""
    b, v = logits.shape
    temperature = jnp.asarray(temperature, jnp.float32)
    cap = min(PER_ROW_TOPK_CAP, v)
    k = jnp.clip(jnp.asarray(top_k, jnp.int32), 0, cap)
    p = jnp.asarray(top_p, jnp.float32)
    greedy = jax.lax.top_k(logits, 1)[1][:, 0].astype(jnp.int32)
    # greedy rows divide by 1.0 — their scaled values feed the (discarded)
    # sampled branch, and an inf/NaN there would be harmless but noisy
    scaled = logits / jnp.where(temperature > 0.0, temperature, 1.0)[:, None]
    k_active = k > 0
    p_active = p < 1.0
    top_vals, top_idx = jax.lax.top_k(scaled, cap)  # [B, cap], sorted desc
    rank = jnp.arange(cap)[None, :]
    # top-k as a per-row threshold: the k-th largest value (rank k-1)
    kth = jnp.take_along_axis(top_vals, jnp.maximum(k - 1, 0)[:, None], axis=-1)
    k_thresh = jnp.where(k_active[:, None], kth, -jnp.inf)
    # nucleus (HF order — over the top-k-FILTERED mass): k-active rows
    # renormalize over their k-subset; k-inactive rows use TRUE full-vocab
    # probabilities (one logsumexp pass, no sort) so the exclusive-cumsum
    # over the sorted candidates is exact for every candidate rank
    in_k = jnp.where(k_active[:, None], rank < k[:, None], True)
    masked_vals = jnp.where(in_k, top_vals, -jnp.inf)
    logz = jnp.where(
        k_active[:, None],
        jax.nn.logsumexp(masked_vals, axis=-1, keepdims=True),
        jax.nn.logsumexp(scaled, axis=-1, keepdims=True),
    )
    probs = jnp.exp(masked_vals - logz)
    p_thresh = _nucleus_threshold_from_probs(
        masked_vals, probs, jnp.minimum(p, 1.0)[:, None]
    )
    p_thresh = jnp.where(p_active[:, None], p_thresh, -jnp.inf)
    thresh = jnp.maximum(k_thresh, p_thresh)  # [B, 1]
    return (greedy, scaled, top_vals, top_idx, masked_vals, thresh,
            k_active, p_active, temperature)


def per_row_log_probs(logits, *, temperature, top_k, top_p):
    """Log-probabilities ``[B, V]`` of the WARPED per-row distribution
    :func:`sample_logits_per_row` draws from — the exact ``log p(token)``
    the speculative-decoding acceptance ratio needs for both the target
    and the draft side (:mod:`tpudist.serve.spec`). Filtered-out tokens
    are ``-inf``; kept tokens are renormalized over the kept set.

    Greedy rows (``temperature == 0``) are a point mass: ``0.0`` at the
    first-occurrence argmax, ``-inf`` elsewhere — the distribution the
    greedy branch of the sampler actually realizes.

    The kept set is expressed as the full-vocab threshold test
    ``scaled >= thresh`` rather than a candidate-subset membership list;
    the two coincide except on exact value ties at the cut boundary
    (measure zero for float logits — the same tie caveat the sampler
    documents)."""
    (greedy, scaled, _, _, _, thresh, k_active, p_active,
     temperature) = _per_row_warp(logits, temperature, top_k, top_p)
    filtered = (k_active | p_active)[:, None]
    keep = jnp.where(filtered, scaled >= thresh, True)
    masked = jnp.where(keep, scaled, -jnp.inf)
    logp = masked - jax.nn.logsumexp(masked, axis=-1, keepdims=True)
    v = logits.shape[-1]
    point = jnp.where(
        jnp.arange(v)[None, :] == greedy[:, None], 0.0, -jnp.inf
    )
    return jnp.where((temperature == 0.0)[:, None], point, logp)


def sample_logits_per_row(logits, keys, *, temperature, top_k, top_p):
    """Per-ROW sampling over ``[B, V]`` logits: ``temperature``/``top_k``/
    ``top_p`` are ``[B]`` arrays and ``keys`` is a ``[B]`` array of rng
    keys — one compiled program serves every mix of per-slot sampling
    params, which is what lets the serving engine keep requests with
    different decoding configs in ONE masked decode step
    (:mod:`tpudist.serve.engine`).

    Per-row semantics: ``temperature == 0`` is greedy (the same
    first-occurrence ``lax.top_k(·, 1)`` winner as :func:`sample_logits`,
    so a greedy slot is bit-identical to the static path); ``top_k <= 0``
    disables the top-k filter for that row; ``top_p >= 1`` disables
    nucleus. Filters compose in the HF order (temperature → top_k →
    top_p) and resolve inside a static top-``PER_ROW_TOPK_CAP`` candidate
    subset: per-row ``top_k`` clamps to the cap, and a ``top_p`` whose
    nucleus would extend past the cap keeps exactly the cap's candidates
    (vocab-size subsets are exact — the cap only binds at ``V > 128``).
    Tie semantics are THRESHOLD-based (every id tied with the k-th value
    is kept, like HF's warper; the scalar path keeps exactly k) — for
    float logits ties have measure zero. Sampling is gumbel-max with one
    ``[V]`` gumbel field per row from that row's key (each slot owns an
    rng stream independent of its neighbors — retiring or admitting a
    request cannot perturb another slot's draw); an unfiltered row's
    categorical runs over the full vocab, a filtered row's over its
    candidate subset through the same gumbel field."""
    b, v = logits.shape
    (greedy, scaled, top_vals, top_idx, masked_vals, thresh, k_active,
     p_active, temperature) = _per_row_warp(logits, temperature, top_k, top_p)
    # ONE [B, V] gumbel field serves both sampling flavors: unfiltered
    # rows argmax over the full vocab; filtered rows over their candidate
    # subset (the subset reads its gumbel values through top_idx, so a
    # candidate's noise is identical either way)
    gumbel = jax.vmap(lambda key: jax.random.gumbel(key, (v,)))(keys)
    free_choice = jnp.argmax(scaled + gumbel, axis=-1)
    sub_gumbel = jnp.take_along_axis(gumbel, top_idx, axis=-1)
    sub_scores = jnp.where(
        masked_vals >= thresh, masked_vals + sub_gumbel, -jnp.inf
    )
    sub_choice = jnp.take_along_axis(
        top_idx, jnp.argmax(sub_scores, axis=-1)[:, None], axis=-1
    )[:, 0]
    sampled = jnp.where(
        k_active | p_active, sub_choice, free_choice
    ).astype(jnp.int32)
    return jnp.where(temperature == 0.0, greedy, sampled)


def eos_retire(tok, done, eos_id, pad_id=0):
    """The ONE stop rule shared by :func:`generate`'s in-scan masking and
    the serving engine's per-slot retirement (:mod:`tpudist.serve.engine`):
    rows already done emit ``pad_id``, and a row is done after it emits
    ``eos_id`` (the EOS token itself is still delivered). ``eos_id`` and
    ``pad_id`` may be scalars or per-row arrays — the engine passes per-
    request stop ids with ``-1`` meaning "no stop token" (token ids are
    non-negative, so ``-1`` never matches)."""
    tok = jnp.where(done, pad_id, tok)
    return tok, done | (tok == eos_id)


def _sample_scan(decode_step, cache, first_logits, rng, *, max_new_tokens,
                 temperature, top_k, top_p, eos_id=None, pad_id=0):
    """The shared sampling loop of both generation paths: scan
    ``max_new_tokens`` (sample from the previous position's logits, decode
    one step) iterations. The final carry's logits go unused — the last
    decode_step primes a position that is never sampled.

    ``eos_id``: rows that have emitted it produce ``pad_id`` from then on
    (the sequence stays static-shaped — the TPU way to "stop"; the cache
    keeps advancing, which is harmless since padded positions are never
    read back). The scan always runs ``max_new_tokens`` steps: a
    data-dependent early exit would force a ``while_loop`` that defeats
    the fixed-shape single compilation.

    Returns ``(tokens [B, max_new_tokens], lengths [B])`` — ``lengths``
    counts each row's real tokens (through its first EOS inclusive;
    ``max_new_tokens`` when it never stopped)."""

    def sample_step(carry, _):
        cache, last_logits, rng, done = carry
        rng, sub = jax.random.split(rng)
        tok = sample_logits(
            last_logits, sub, temperature=temperature, top_k=top_k,
            top_p=top_p,
        )
        alive = ~done  # this step emits a REAL token for still-alive rows
        if eos_id is not None:
            tok, done = eos_retire(tok, done, eos_id, pad_id)
        cache, next_logits = decode_step(cache, tok)
        return (cache, next_logits, rng, done), (tok, alive)

    done0 = jnp.zeros(first_logits.shape[0], bool)
    (cache, _, _, _), (toks, alive) = jax.lax.scan(
        sample_step, (cache, first_logits, rng, done0), None,
        length=max_new_tokens,
    )
    lengths = jnp.sum(alive, axis=0).astype(jnp.int32)
    return toks.T, lengths  # [B, max_new_tokens], [B]


def zero_cache(model, batch_size: int, **init_kwargs):
    """Freshly-zeroed decode cache for ``batch_size`` rows, with the
    shapes ``model.init(..., decode=True)`` would create — via
    ``eval_shape``, so the throwaway init never materializes a second copy
    of the params (``model.init`` would — a 2× HBM spike at 7B scale).
    The serving engine's slot pool is exactly this at
    ``batch_size=max_slots`` (:mod:`tpudist.serve.slots`)."""
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((batch_size, 1), jnp.int32),
            train=False, decode=True, **init_kwargs,
        )
    )["cache"]
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )


def _reset_cursors(cache, true_len):
    """Rewind every scalar position counter (the per-block ``cache_index``
    and GPT-2's wpe cursor) to the TRUE prompt length after a
    bucket-padded prefill: the pad tail existed only for shape bucketing,
    and decode must continue at position ``true_len`` (the stale pad K/V
    above the cursor is overwritten step by step and never attended — the
    mask only admits slots <= cursor)."""
    t = jnp.asarray(true_len, jnp.int32)
    return jax.tree_util.tree_map(
        lambda leaf: t
        if jnp.ndim(leaf) == 0 and jnp.issubdtype(leaf.dtype, jnp.integer)
        else leaf,
        cache,
    )


def _fetch(out) -> np.ndarray:
    """Generated device tokens → host numpy, multi-process-safe."""
    if not out.is_fully_addressable:
        # multi-process with sharded/global params: the jit output may span
        # hosts, and np.asarray on a non-addressable array raises; every
        # process runs the same decode on the same prompt, so allgathering
        # the token ids (tiny) yields the identical [B, T] everywhere.
        # tiled=True is required for global non-addressable inputs and
        # returns the global [B, T] (no leading process dim)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(out, tiled=True))
    return np.asarray(out)


def generate(
    model,
    params,
    prompt,
    max_new_tokens: int,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    seed: int = 0,
    eos_id: int | None = None,
    pad_id: int = 0,
    return_lengths: bool = False,
) -> np.ndarray:
    """Continue ``prompt`` (``[B, P]`` int tokens) by ``max_new_tokens``.

    Works for any model with the decode contract (``decode=True`` +
    ``cache`` collection): GPT-2 and Llama. Returns ``[B, max_new_tokens]``
    int32. Greedy when ``temperature=0``, else temperature/top-k/top-p
    (nucleus) sampling. With ``eos_id``, rows that emit it produce
    ``pad_id`` thereafter (static shapes — the compiled program always
    runs ``max_new_tokens`` steps); ``return_lengths=True`` additionally
    returns a ``[B]`` int32 array of real lengths (through each row's
    first EOS inclusive) — the same per-row retirement rule the serving
    engine applies (:func:`eos_retire`).

    The prompt is padded to a power-of-two BUCKET (:func:`bucket_length`)
    with the true length passed as a traced scalar, so repeated calls
    with varying prompt lengths reuse one compiled program per bucket
    instead of compiling per length.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    if p + max_new_tokens > model.max_seq_len:
        raise ValueError(
            f"prompt {p} + {max_new_tokens} new tokens exceeds the model's "
            f"max_seq_len {model.max_seq_len} (the KV cache size)"
        )

    bucket = bucket_length(p, cap=model.max_seq_len)
    if bucket > p:
        # pad-token VALUES are irrelevant: prefill is causal within the
        # chunk, so real rows never attend the tail, and _reset_cursors
        # rewinds the write cursor so decode overwrites the tail's K/V
        prompt = jnp.pad(prompt, ((0, 0), (0, bucket - p)))
    cache = zero_cache(model, b)
    toks, lengths = _run(
        model, params, cache, prompt, jnp.asarray(p, jnp.int32),
        jax.random.key(seed),
        max_new_tokens=max_new_tokens, temperature=temperature, top_k=top_k,
        top_p=top_p, eos_id=eos_id, pad_id=pad_id,
    )
    if return_lengths:
        return _fetch(toks), _fetch(lengths)
    return _fetch(toks)


def generate_seq2seq(
    model,
    params,
    enc_tokens,
    max_new_tokens: int,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    seed: int = 0,
    start_id: int = 0,
    eos_id: int | None = None,
    pad_id: int = 0,
    return_lengths: bool = False,
) -> np.ndarray:
    """Seq2seq generation for encoder-decoder models (T5): encode
    ``enc_tokens`` ``[B, Se]`` once, then autoregressively decode
    ``max_new_tokens`` tokens from ``start_id`` against the decoder's KV
    cache — all (encode + prefill + sampling) as ONE jit-compiled program,
    the same single-compilation contract as :func:`generate`. Returns
    ``[B, max_new_tokens]`` int32; same sampling controls as
    :func:`sample_logits`.

    The model must support the ``encode_only``/``decode`` entry points
    (:class:`tpudist.models.t5.T5`); the cache buffer is
    ``model.max_decode_len`` slots (the start token takes one).
    ``eos_id`` (T5's natural stop: its EOS ends the span-target sequence)
    pads each row with ``pad_id`` after its first EOS;
    ``return_lengths=True`` adds the ``[B]`` real lengths.
    """
    enc_tokens = jnp.asarray(enc_tokens, jnp.int32)
    if max_new_tokens + 1 > model.max_decode_len:
        raise ValueError(
            f"start token + {max_new_tokens} new tokens exceeds the "
            f"model's max_decode_len {model.max_decode_len} (the decoder "
            "KV cache size)"
        )
    toks, lengths = _run_seq2seq(
        model, params, enc_tokens, jax.random.key(seed),
        max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, start_id=start_id, eos_id=eos_id,
        pad_id=pad_id,
    )
    if return_lengths:
        return _fetch(toks), _fetch(lengths)
    return _fetch(toks)


@partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "start_id", "eos_id", "pad_id"),
)
def _run_seq2seq(model, params, enc_tokens, rng, *, max_new_tokens,
                 temperature, top_k, top_p, start_id, eos_id, pad_id):
    b = enc_tokens.shape[0]
    enc = model.apply(
        {"params": params}, enc_tokens, train=False, encode_only=True
    )
    # the cache depends on the decoder side alone, so a length-1 dummy enc
    # keeps the throwaway init trace cheap
    cache = zero_cache(
        model, b, enc=jnp.zeros((b, 1, model.hidden_dim), enc.dtype)
    )

    def decode_step(cache, tok):
        logits, updates = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            train=False, decode=True, enc=enc, mutable=["cache"],
        )
        return updates["cache"], logits[:, -1]

    cache, logits = decode_step(
        cache, jnp.full((b,), start_id, jnp.int32)
    )
    return _sample_scan(
        decode_step, cache, logits, rng, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p, eos_id=eos_id,
        pad_id=pad_id,
    )


@partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "eos_id", "pad_id"),
)
def _run(model, params, cache, prompt, true_len, rng, *, max_new_tokens,
         temperature, top_k, top_p, eos_id, pad_id):
    """One compiled program for prefill + sampling. ``params``, the
    bucket-padded ``prompt``, and ``true_len`` are traced arguments (not
    closure constants), and jit caches on the static (model, bucket,
    length, sampling) config — repeated generate() calls with the same
    setup (any prompt length within the bucket) reuse the compilation."""

    def decode_chunk(cache, toks):
        """toks [B, s] → (updated cache, [B, s, V] logits)."""
        logits, updates = model.apply(
            {"params": params, "cache": cache}, toks,
            train=False, decode=True, mutable=["cache"],
        )
        return updates["cache"], logits

    def decode_step(cache, tok):
        cache, logits = decode_chunk(cache, tok[:, None])
        return cache, logits[:, -1]

    # BULK prefill: the whole (bucket-padded) prompt in ONE decode pass —
    # cached_kv's mask is causal within the chunk (slot t attendable by
    # row i iff t <= pos + i), so a P-token prompt costs one MXU-shaped
    # forward instead of a P-iteration scan of launch-bound single-token
    # steps. Measured at P=512, batch 8, GPT-2 124M on v5e: 127.5 vs
    # 676.7 ms = 5.3x (the 127.5 includes the attach's ~100 ms per-call
    # floor; docs/PERF.md §7b). The first sampled position is the TRUE
    # last prompt token's logits (a traced index — the pad tail feeds
    # nothing), and the cursors rewind to true_len so decode continues
    # exactly where the real prompt ended.
    cache, all_logits = decode_chunk(cache, prompt)
    logits = jax.lax.dynamic_index_in_dim(
        all_logits, true_len - 1, axis=1, keepdims=False
    )
    cache = _reset_cursors(cache, true_len)
    return _sample_scan(
        decode_step, cache, logits, rng, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p, eos_id=eos_id,
        pad_id=pad_id,
    )
