"""Autoregressive text generation with a KV cache.

No reference counterpart (the reference is a training-only CNN script); this
is the inference half every LM framework needs. TPU-first design: the whole
generation — prompt prefill and sampling — is ONE jit-compiled program.
Both phases are ``lax.scan`` over single-token decode steps against a
static-shaped ``[B, max_seq_len, H, dh]`` KV cache
(:mod:`tpudist.ops.decode`), so there is exactly one compilation regardless
of prompt length or tokens requested, and the cache never reallocates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def sample_logits(logits, rng, *, temperature: float = 1.0,
                  top_k: int | None = None):
    """One sampling step over ``[B, V]`` logits. ``temperature=0`` is
    greedy; ``top_k`` keeps only the k most likely tokens."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        k = min(top_k, logits.shape[-1])  # clamp like HF/torch samplers
        kth = jax.lax.top_k(logits, k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(
    model,
    params,
    prompt,
    max_new_tokens: int,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    seed: int = 0,
) -> np.ndarray:
    """Continue ``prompt`` (``[B, P]`` int tokens) by ``max_new_tokens``.

    Works for any model with the decode contract (``decode=True`` +
    ``cache`` collection): GPT-2 and Llama. Returns ``[B, max_new_tokens]``
    int32. Greedy when ``temperature=0``, else temperature/top-k sampling.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    if p + max_new_tokens > model.max_seq_len:
        raise ValueError(
            f"prompt {p} + {max_new_tokens} new tokens exceeds the model's "
            f"max_seq_len {model.max_seq_len} (the KV cache size)"
        )

    cache = model.init(
        jax.random.key(0), jnp.zeros((b, 1), jnp.int32),
        train=False, decode=True,
    )["cache"]

    def decode_step(cache, tok):
        """tok [B] → (updated cache, [B, V] logits for the next position)."""
        logits, updates = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            train=False, decode=True, mutable=["cache"],
        )
        return updates["cache"], logits[:, -1]

    @jax.jit
    def run(cache, prompt, rng):
        # prefill: feed prompt tokens through the cache, keep the last logits
        cache, logits = jax.lax.scan(decode_step, cache, prompt.T)

        def sample_step(carry, _):
            cache, last_logits, rng = carry
            rng, sub = jax.random.split(rng)
            tok = sample_logits(
                last_logits, sub, temperature=temperature, top_k=top_k
            )
            cache, next_logits = decode_step(cache, tok)
            return (cache, next_logits, rng), tok

        (cache, _, _), toks = jax.lax.scan(
            sample_step, (cache, logits[-1], rng),
            None, length=max_new_tokens,
        )
        return toks.T  # [B, max_new_tokens]

    return np.asarray(run(cache, prompt, jax.random.key(seed)))
