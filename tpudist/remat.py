"""Named rematerialization policies — the one activation-memory surface.

The framework used to expose remat as an all-or-nothing ``remat: bool`` on
``make_train_step``. At ~1B-param scale on 16 GB HBM that is too blunt: the
right trade is usually *selective* — keep the MXU outputs (cheap to store,
expensive to recompute) and recompute the elementwise tail, or checkpoint at
block boundaries only. This module names the useful points on that curve and
is consumed by every surface that remats:

- ``tpudist.train.make_train_step(remat=...)`` — whole-forward checkpoint
  under the named policy (legacy ``remat=True`` still works ≡ ``"full"``);
- the model zoo's ``remat_policy`` field (GPT-2, Llama) — per-BLOCK
  checkpoint, the memory-discipline workhorse: backward stores only the
  ``depth`` inter-block residual streams and recomputes inside one block at
  a time, so activation HBM drops from O(depth · internals) to
  O(depth · hidden + one block's internals);
- FSDP/ZeRO runs compose through the same two hooks (``parallel/fsdp.py``);
  remat is orthogonal to state sharding.

Policies, by descending aggressiveness (ascending activation HBM):

===============  ============================================================
``save_nothing`` save no intermediates (explicit
                 ``jax.checkpoint_policies.nothing_saveable``) — the floor
``full``         plain ``jax.checkpoint`` (its default is also
                 save-nothing; kept as the legacy ``remat=True`` spelling)
``dots_saveable``save MXU/dot outputs, recompute the elementwise tail —
                 usually the best FLOP/HBM trade on TPU, where recomputing
                 a matmul costs real roofline and recomputing a gelu is free
``none``         no checkpointing — store everything (fastest, hungriest)
===============  ============================================================

Measured/contracted ordering of live activation bytes:
``save_nothing ≤ full ≤ dots_saveable ≤ none``
(asserted against XLA's compiled memory analysis in
``tests/test_sharded_optim.py``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax

# name -> jax.checkpoint policy callable (None = jax.checkpoint's default,
# which saves nothing). "none" is absent on purpose: it means "do not wrap".
_POLICIES: dict[str, Any] = {
    "full": None,
    "save_nothing": jax.checkpoint_policies.nothing_saveable,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
}

POLICY_NAMES = ("none", "full", "dots_saveable", "save_nothing")


def resolve(policy: str | bool | None | Callable):
    """Normalize a remat policy argument.

    Returns ``None`` for "no remat" (``False``/``None``/``"none"``), else a
    dict of kwargs for ``jax.checkpoint``/``nn.remat``. Accepts the legacy
    bool (``True`` ≡ ``"full"``), a policy name, or a raw
    ``jax.checkpoint_policies`` callable (the escape hatch for custom
    ``save_only_these_names`` policies).
    """
    if policy in (False, None, "none"):
        return None
    if policy is True:
        policy = "full"
    if callable(policy):
        return {"policy": policy}
    try:
        fn = _POLICIES[policy]
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown remat policy {policy!r}; expected one of "
            f"{POLICY_NAMES}, a bool, or a jax.checkpoint_policies callable"
        ) from None
    return {} if fn is None else {"policy": fn}


def checkpoint(fn: Callable, policy: str | bool | None | Callable) -> Callable:
    """``jax.checkpoint(fn)`` under the named policy; ``fn`` unchanged for
    ``"none"``/``False``/``None``. The function-level hook
    (``make_train_step``'s whole-forward remat)."""
    kwargs = resolve(policy)
    if kwargs is None:
        return fn
    return jax.checkpoint(fn, **kwargs)


def remat_module(module_cls, policy: str | bool | None | Callable,
                 **nn_remat_kwargs):
    """``nn.remat(module_cls)`` under the named policy; the class unchanged
    for ``"none"``. The module-level hook (the model zoo's per-block
    ``remat_policy`` field)."""
    from flax import linen as nn

    kwargs = resolve(policy)
    if kwargs is None:
        return module_cls
    return nn.remat(module_cls, **kwargs, **nn_remat_kwargs)
