"""Training-health telemetry: in-step metrics, NaN flight recorder,
step-time breakdown, MFU accounting, and a structured JSONL sink.

The reference's observability surface is a rank-0 TSV of loss and
examples/sec plus one profiler window (``tpudist/metrics.py``,
``tpudist/profiling.py`` — reproduced exactly and untouched). That answers
"how fast"; this subsystem answers the three questions a production run
dies without (docs/OBSERVABILITY.md):

- **is training healthy?** — global grad-norm, param-norm, update-norm and
  non-finite counts computed INSIDE the jit-compiled SPMD step
  (``make_train_step(telemetry=True)``): a handful of reductions XLA fuses
  into the existing gradient psum path, fetched through the same
  one-step-delayed async pipeline as the loss — zero extra host syncs.
  The bench leg ``telemetry_overhead_pct`` holds the cost under 2% of
  step time.
- **why did it die?** — :class:`NanSentry`, the flight recorder: the
  in-graph guard (``make_train_step(guard_nonfinite=True)``) skips the
  poisoned update the step it happens (params/opt-state/BN stats keep
  their pre-step values, the step counter still advances so data position
  stays exact); the host sentry then emits a structured ``anomaly`` event
  and arms :class:`~tpudist.profiling.WindowedProfiler` for an on-demand
  trace window around the anomaly. Rolling-window loss-spike detection
  catches divergence that never reaches NaN.
- **where does the time go?** — per-step data-wait / dispatch /
  device-compute attribution in ``fit()`` plus per-process heartbeat rows,
  so a slow input pipeline, a dispatch-bound host, and a multi-host
  straggler all look different in the log. MFU rows combine the analytic
  counters (:mod:`tpudist.telemetry.flops`) with measured step time.

Everything lands in a per-process JSONL stream (:class:`TelemetrySink`)
NEXT TO the reference TSV, which stays byte-identical when telemetry is
off. Enable with ``fit(..., telemetry=True)`` or pass a
:class:`TelemetryConfig` to tune knobs.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import numbers
import threading
import time
from pathlib import Path
from typing import Any, Mapping

from tpudist.telemetry import flops

__all__ = [
    "TelemetryConfig",
    "TelemetrySink",
    "NanSentry",
    "TimedIterator",
    "Telemetry",
    "build_telemetry",
    "flops",
]

SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Knobs for the telemetry subsystem; the defaults are what
    ``fit(..., telemetry=True)`` runs.

    ``health_metrics``/``guard_nonfinite`` steer the compiled step (norms
    in-graph; skip poisoned updates). ``sentry`` drives the host-side
    flight recorder: non-finite loss/grads fire an event; a loss
    counts as a spike when it exceeds the rolling window's mean by
    ``spike_sigma`` standard deviations (window of ``spike_window`` recent
    finite losses, armed only after ``spike_min_steps`` observations);
    ``cooldown_steps`` suppresses event storms after a detection.
    ``capture_steps`` sizes the on-demand profiler window an anomaly arms.
    ``peak_flops`` is PER-CHIP peak (``None`` → v5e bf16,
    ``flops.DEFAULT_PEAK_FLOPS``). ``heartbeat_every`` is in steps
    (``None`` → 10× the TSV log cadence; ``0`` → no heartbeat rows, the
    same off-switch contract as ``fit``'s ``memory_log_every``).
    ``jsonl_dir`` overrides where the sink writes (``None`` → fit's
    ``log_dir``).

    The run-health fields (:mod:`tpudist.telemetry.health`) default OFF so
    the JSONL/TSV streams stay byte-identical unless asked for:
    ``aggregate_every`` (steps between cross-process folds; 0 = off) with
    ``straggler_ratio``/``straggler_patience`` tuning the one-shot
    straggler rule; ``divergence_every`` (steps between replica-checksum
    probes; 0 = off); ``hang_timeout_s`` (step deadline for the watchdog;
    ``None`` = off). ``run_report`` (on) writes ``{job}_report.json`` at
    run end / crash — a separate file, never a stream row.
    ``jsonl_max_bytes`` caps each JSONL segment before rotation
    (``None`` = one unbounded file, the pre-rotation contract);
    :func:`tpudist.telemetry.health.health_config` is the one-call
    production preset (``main.py --health``).

    ``hang_action`` escalates the watchdog: ``"report"`` (default, the
    pre-resilience behavior) writes the forensics and lets a resolving
    stall finish the run; ``"exit"`` additionally terminates the process
    with :data:`tpudist.resilience.EXIT_HANG` (76) AFTER the crash
    file/report/row are on disk — the restartable code
    ``tpudist.launch``'s supervisor relaunches from the last checkpoint,
    closing the detection → forensics → recovery loop.
    """

    health_metrics: bool = True
    guard_nonfinite: bool = True
    sentry: bool = True
    spike_window: int = 32
    spike_sigma: float = 8.0
    spike_min_steps: int = 16
    cooldown_steps: int = 16
    capture_on_anomaly: bool = True
    capture_steps: int = 6
    breakdown: bool = True
    mfu: bool = True
    peak_flops: float | None = None
    heartbeat_every: int | None = None
    jsonl_dir: str | None = None
    # run-health layer (tpudist.telemetry.health) — off by default
    aggregate_every: int = 0
    straggler_ratio: float = 1.5
    straggler_patience: int = 3
    divergence_every: int = 0
    hang_timeout_s: float | None = None
    hang_action: str = "report"
    run_report: bool = True
    jsonl_max_bytes: int | None = None
    # span layer (tpudist.telemetry.trace) — off by default; on, fit()
    # re-emits the step breakdown, checkpoint saves, health probes, and
    # repair/reshard events as `span` rows on the same sink
    trace: bool = False
    # program-anatomy layer (tpudist.telemetry.anatomy) — off by default.
    # `anatomy` makes fit() introspect the compiled step at bring-up (one
    # `anatomy` row; a stale-counter `warning` when the analytic FLOPs
    # counter drifts from XLA's count beyond `anatomy_tolerance`).
    # `regression_detect` arms the in-run step-time sentinel: rolling
    # median over `regression_window` intervals vs the post-compile
    # baseline, one-shot `perf_regression` row past `regression_threshold`
    anatomy: bool = False
    anatomy_tolerance: float = 0.1
    regression_detect: bool = False
    regression_threshold: float = 0.25
    regression_window: int = 16

    def step_kwargs(self) -> dict:
        """The ``make_train_step`` knobs this config implies — the ONE
        mapping from config fields to compiled-step behavior (``fit()``
        passes these through verbatim)."""
        return {
            "telemetry": self.health_metrics,
            "guard_nonfinite": self.guard_nonfinite,
        }


def _json_safe(v):
    """JSONL rows must stay strict-JSON parseable: non-finite floats become
    null (a ``NaN`` literal breaks downstream ``json.loads``), numpy
    scalars become python numbers, containers (the run-health fleet row's
    per-rank maps) recurse element-wise."""
    if isinstance(v, Mapping):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, bool) or v is None or isinstance(v, (str, int)):
        return v
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if not math.isfinite(f):
        return None
    # numpy integer scalars are not python ints (the early return above)
    # but ARE Integral — keep counts like nonfinite_grad_count integers in
    # the JSONL, not 5.0
    return int(f) if isinstance(v, numbers.Integral) else f


class TelemetrySink:
    """Append-only structured JSONL writer — one file per process
    (``{job_id}_telemetry_{rank}.jsonl``), one object per line:
    ``{"v": 1, "t": <unix seconds>, "kind": ..., "rank": ..., "step": ...,
    <kind-specific fields>}``. Kinds written by ``fit()``: ``health``,
    ``step_breakdown``, ``mfu``, ``throughput``, ``memory``, ``anomaly``,
    ``heartbeat``, ``train_time``, ``run_meta``, ``comm`` (explicit
    gradient reduction's one-time wire accounting), ``fusion`` (one-time
    step-fusion config: which Pallas kernels — fused LN, fused optimizer
    — the compiled step engaged, and the compute-copy dtype), ``warning``
    (tagged one-shot diagnoses, e.g. ``h2d_link_bound``,
    ``checkpoint_fallback``), ``reshard`` (one-time elastic-resume record:
    cross-world-size ZeRO-1 relayout, residual flush, cursor remap),
    ``compile_cache`` (one-time AOT executable-cache outcome:
    hit/miss/bytes/load_s), ``repair`` (one record per executed repair
    action — cause, rollback step, skipped window, action taken:
    ``tpudist.resilience.repair``), ``anatomy`` (one-shot per-program
    compiler introspection: XLA-counted FLOPs/bytes and the static HBM
    breakdown, cross-checked against the analytic counters —
    ``tpudist.telemetry.anatomy``), ``perf_regression`` (the in-run
    step-time sentinel's one-shot verdict). The serving engine
    (``tpudist.serve``) writes ``serve``/``serve_summary`` SLO rows
    through the same sink — TTFT/TPOT percentiles, slot utilization,
    and in paged mode the block-pool triple (``pool_occupancy``,
    ``prefix_hit_rate``, ``preemptions``). Schema glossary in docs/OBSERVABILITY.md. Rows flush per write, and the file opens in
    APPEND mode — both halves of the flight-recorder contract: the anomaly
    row must survive the crash it describes, including a checkpoint-resume
    of the same job_id truncating the evidence before anyone read it.
    Attempts are separable by the ``t`` timestamps.

    ``max_bytes`` caps the ACTIVE file's size: when the next row would
    exceed it, the file rotates to the next numbered segment
    (``X.jsonl`` → ``X.jsonl.1``, ``.2``, …; the base path is always the
    live tail) so a multi-day run never grows one unbounded file.
    :meth:`segments` lists the segment chain oldest→active (the run
    report records it); ``None`` (default) keeps the single-file
    contract byte-identical. Writes are serialized by a lock (the hang
    watchdog writes its ``watchdog`` row from the monitor thread while
    the main thread may be mid-row), and the last 256 rows are kept in a
    host ring buffer (:meth:`tail`) — the crash report's "what was the
    run doing" evidence, readable even when the filesystem is the thing
    that hung."""

    TAIL_ROWS = 256

    def __init__(self, path: str | Path, *, rank: int = 0, clock=time.time,
                 max_bytes: int | None = None, run_id: str | None = None):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.rank = rank
        self._clock = clock
        self.max_bytes = max_bytes
        # the job's stable run id: explicit > launcher env (TPUDIST_RUN_ID)
        # > absent. When set, every row gains a `run_id` field APPENDED
        # after its existing fields (the heartbeat append-only discipline)
        # so offline stitching (tools/tracelens.py) can group the segments
        # of one logical job — including relaunched generations, which
        # inherit the id via the supervisor env — without filename
        # heuristics. A bare sink with no launcher stays byte-identical.
        if run_id is None:
            from tpudist.resilience.exitcodes import run_id as _env_run_id

            run_id = _env_run_id()
        self.run_id = run_id
        self._lock = threading.Lock()
        self._tail: collections.deque = collections.deque(
            maxlen=self.TAIL_ROWS
        )
        self._size = self.path.stat().st_size if self.path.exists() else 0
        # monotonic: max existing + 1, never the first free gap — an
        # operator deleting old segments mid-run must not make the NEWEST
        # data inherit the OLDEST position in the chain
        self._next_segment = 1 + max(
            (n for _, n in self._numbered_segments()), default=0
        )
        self._file = open(self.path, "a", encoding="utf-8")

    def write(self, kind: str, step: int | None = None, **fields) -> dict:
        row: dict[str, Any] = {
            "v": SCHEMA_VERSION,
            "t": round(float(self._clock()), 6),
            "kind": kind,
            "rank": self.rank,
        }
        if step is not None:
            row["step"] = int(step)
        row.update({k: _json_safe(v) for k, v in fields.items()})
        if self.run_id is not None:
            row["run_id"] = self.run_id
        line = json.dumps(row) + "\n"
        # the cap is in BYTES on disk: a non-ASCII hostname or event
        # string is longer in UTF-8 than in characters, and len(line)
        # would under-count every such row until the segment overshoots
        nbytes = len(line.encode("utf-8"))
        with self._lock:
            if (self.max_bytes and self._size
                    and self._size + nbytes > self.max_bytes):
                self._rotate()
            self._file.write(line)
            self._file.flush()
            self._size += nbytes
            self._tail.append(row)
        return row

    def _numbered_segments(self) -> list[tuple[Path, int]]:
        out = []
        for p in self.path.parent.glob(f"{self.path.name}.*"):
            try:
                out.append((p, int(p.name[len(self.path.name) + 1:])))
            except ValueError:
                continue  # foreign suffix, not a segment
        return sorted(out, key=lambda t: t[1])

    def _rotate(self) -> None:
        # called under the lock; the active file is full — seal it as the
        # next numbered segment and start a fresh active file. Renaming
        # the SEALED file (not the active one) keeps the base path stable
        # for tailing dashboards across rotations.
        self._file.close()
        self.path.rename(
            self.path.with_name(f"{self.path.name}.{self._next_segment}")
        )
        self._next_segment += 1
        self._file = open(self.path, "a", encoding="utf-8")
        self._size = 0

    def segments(self) -> list[Path]:
        """Existing segment files oldest→newest (numeric order, tolerant
        of cleanup gaps), the active file last — what the run report
        records so a reader can reassemble the full stream after
        rotation."""
        sealed = [p for p, _ in self._numbered_segments()]
        return sealed + ([self.path] if self.path.exists() else [])

    def tail(self, n: int = TAIL_ROWS, *,
             lock_timeout: float | None = None) -> list[dict]:
        """The most recent rows (host ring buffer) — crash forensics.

        ``lock_timeout`` bounds the wait for the write lock: the hang
        watchdog reads the tail while the main thread may be wedged
        INSIDE ``write`` (a hung filesystem) holding the lock forever.
        On timeout the deque is read lockless — appends are atomic, and
        the rare concurrent-mutation ``RuntimeError`` degrades to an
        empty tail rather than a deadlocked crash handler."""
        acquired = self._lock.acquire(
            timeout=-1 if lock_timeout is None else lock_timeout
        )
        try:
            try:
                rows = list(self._tail)
            except RuntimeError:
                rows = []
        finally:
            if acquired:
                self._lock.release()
        return rows[-n:]

    def close(self) -> None:
        if not self._file.closed:
            self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NanSentry:
    """Host-side anomaly detector over the per-step loss stream.

    :meth:`observe` returns an event dict (``event``: ``"nonfinite"`` or
    ``"loss_spike"``) or ``None``. Non-finite loss, a non-zero in-step
    non-finite-gradient count, or an in-graph guard skip
    (``update_skipped``) fires ``nonfinite``. Spikes fire when a
    finite loss exceeds the rolling window's ``mean + sigma·std`` (and the
    window has seen ``min_steps`` losses) — the "diverging but not yet
    NaN" signal. Anomalous losses are NOT pushed into the window (one
    spike must not drag the baseline up), and ``cooldown`` steps of
    silence follow each event — for BOTH kinds — so a NaN'd-out or
    diverging run emits a handful of rows, not one per step (the in-graph
    skip counter still sees every poisoned step).
    """

    def __init__(self, *, window: int = 32, sigma: float = 8.0,
                 min_steps: int = 16, cooldown: int = 16):
        self.sigma = sigma
        self.min_steps = max(min_steps, 2)
        self.cooldown = cooldown
        self._window: collections.deque[float] = collections.deque(maxlen=window)
        self._quiet_until = -1
        self.events: list[dict] = []

    def observe(self, step: int, loss: float, *, nonfinite_count: int = 0,
                update_skipped: int = 0) -> dict | None:
        event = None
        if (not math.isfinite(loss) or nonfinite_count > 0
                or update_skipped > 0):
            # update_skipped is its own trigger: with health_metrics=False
            # the compiled step reports no nonfinite_grad_count, and a
            # bf16 backward can overflow gradients under a finite loss —
            # the in-graph guard's skip is then the only signal
            event = {
                "event": "nonfinite",
                "loss": loss,
                "nonfinite_grad_count": int(nonfinite_count),
                "update_skipped": int(update_skipped),
            }
        elif len(self._window) >= self.min_steps:
            mean = sum(self._window) / len(self._window)
            var = sum((x - mean) ** 2 for x in self._window) / len(self._window)
            std = math.sqrt(var)
            # floor the spread: a zero-variance plateau (converged run,
            # bf16-quantized loss) must not turn one-ulp jitter into a
            # recurring spike event — anything within 1e-6 relative of the
            # mean is noise, not divergence
            spread = max(std, 1e-6 * abs(mean), 1e-12)
            threshold = mean + self.sigma * spread
            if loss > threshold:
                event = {
                    "event": "loss_spike",
                    "loss": loss,
                    "window_mean": mean,
                    "window_std": std,
                    "threshold": threshold,
                    "update_skipped": int(update_skipped),
                }
        if event is not None:
            # anomalous either way — the loss must stay OUT of the baseline
            # window even when cooldown suppresses the event row, or a
            # still-elevated post-spike run drags the mean up and silences
            # every later detection
            if step < self._quiet_until:
                return None  # cooldown: a NaN'd-out/diverging run emits a
                # handful of rows, not one per step — the skipped-update
                # counter still accumulates in-graph, so nothing is lost,
                # only deduplicated
            event["step"] = int(step)
            self._quiet_until = step + self.cooldown
            self.events.append(event)
            return event
        if math.isfinite(loss):
            self._window.append(loss)
        return None

    def reset(self) -> None:
        """Forget the baseline window and cooldown — the repair loop's
        rollback rewound the trajectory, so losses observed on the
        discarded (possibly poisoned) span must not seed the spike
        baseline of the repaired one, and a live cooldown must not
        silence a fresh post-repair incident. Event history is kept (it
        is the report's evidence)."""
        self._window.clear()
        self._quiet_until = -1


class TimedIterator:
    """Wrap a batch iterator and record the wall seconds the consumer spent
    blocked in ``next()`` — fit()'s data-wait attribution. With the
    prefetch queue healthy this is ~0; when it grows toward the step time
    the run is input-bound (docs/PERF.md §3's diagnosis, now visible
    per-step instead of requiring a bench A/B)."""

    def __init__(self, iterator):
        self._it = iter(iterator)
        self.last_wait_s = 0.0

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        try:
            return next(self._it)
        finally:
            self.last_wait_s = time.perf_counter() - t0


class Telemetry:
    """The host half of the subsystem — owns the sink and sentry, driven by
    ``fit()`` once per resolved step (one step after dispatch, on the same
    delayed pipeline as the TSV rows). Scalar rows (``health``,
    ``step_breakdown``, ``mfu``) are written by rank 0 at the TSV's
    ``log_every`` cadence; ``heartbeat`` rows are written by EVERY process
    (that is their point: a straggler host is visible by comparing its
    heartbeat wall-clock drift against its peers'); ``anomaly`` rows are
    written by whichever rank observed the anomaly, every time."""

    def __init__(self, config: TelemetryConfig, sink: TelemetrySink, *,
                 model=None, input_key: str = "tokens", profiler=None,
                 rank: int = 0, world_size: int = 1, log_every: int = 5,
                 n_chips: int = 1):
        self.config = config
        self.sink = sink
        self.profiler = profiler
        self.rank = rank
        self.world_size = world_size
        self.log_every = max(int(log_every), 1)
        self.n_chips = max(int(n_chips), 1)
        self.peak_flops = config.peak_flops or flops.DEFAULT_PEAK_FLOPS
        # None → auto (10x the TSV cadence); 0 → off — the same contract
        # as fit()'s memory_log_every, so `or` (which eats the 0) won't do
        self.heartbeat_every = (
            config.heartbeat_every if config.heartbeat_every is not None
            else self.log_every * 10
        )
        self.sentry = (
            NanSentry(
                window=config.spike_window, sigma=config.spike_sigma,
                min_steps=config.spike_min_steps,
                cooldown=config.cooldown_steps,
            )
            if config.sentry else None
        )
        self._model = model
        self._input_key = input_key
        self._flops_per_step: float | None = None
        self._tokens_per_step: int | None = None
        self._sized = False
        # explicit-gradient-reduction accounting (tpudist.parallel.dp):
        # set_comm() fills these; step_breakdown rows then carry the comm
        # column. None ⇒ feature off ⇒ rows byte-identical to before.
        self._comm: dict | None = None
        self._comm_probe_s: float | None = None
        # H2D link probe (MB/s, fit() fills on accelerator backends) + the
        # staged-batch byte count observe_batch measures: together they
        # decide the one-shot link-bound warning row
        self.h2d_mbps: float | None = None
        self._batch_bytes: int | None = None
        self._link_warned = False
        self._link_checks = 0
        # run-health layer (tpudist.telemetry.health.RunHealth), attached
        # by build_telemetry when any health knob (or the run report) is
        # on; None keeps every health path a no-op
        self.health = None
        # detector event bus: every sentry/divergence VERDICT is published
        # to these callbacks (the repair controller subscribes) — the
        # detectors stay pure observers, the subscribers decide what a
        # verdict is worth
        self._listeners: list = []
        # executed-repair record (tpudist.resilience.repair): this
        # generation's rows via set_repair; repair_history, when fit
        # attaches the controller's live cross-generation list, is what
        # the report's `repairs` section prefers
        self.repair_events: list[dict] = []
        self.repair_history: list[dict] | None = None
        # goodput tracker (tpudist.resilience.goodput), attached by fit();
        # the run report's `goodput` section reads it. None = no section.
        self.goodput = None
        # running skipped-update total — the exporter's counter surface
        self._skips_total = 0
        # span layer (tpudist.telemetry.trace.Tracer), attached by
        # build_telemetry when config.trace; None keeps every span path a
        # no-op and the streams byte-identical
        self.tracer = None
        # in-run perf-regression sentinel (tpudist.telemetry.anatomy) —
        # None (the default) keeps on_step's path byte-identical
        if config.regression_detect:
            from tpudist.telemetry.anatomy import StepTimeRegressionDetector

            self.regression = StepTimeRegressionDetector(
                window=config.regression_window,
                threshold=config.regression_threshold,
            )
        else:
            self.regression = None
        # live-metrics exporter (tpudist.telemetry.trace.MetricsExporter),
        # attached by fit(metrics_port=); on_step pushes host-side gauges
        # into it — no device syncs, no extra rows
        self.exporter = None
        # restart generation (TPUDIST_RESTART_GENERATION, exported by the
        # supervisor; 0 on a first launch): stamps heartbeat rows and the
        # run report so streams sharing one append-mode file are
        # attributable across the lives of the job
        from tpudist.resilience import restart_generation

        self.generation = restart_generation()
        # heartbeat identity fields: process_index + hostname + a
        # monotonic clock let the cross-process aggregator (and humans)
        # align per-rank timelines — rank alone is ambiguous once
        # global_rank counts replicas instead of hosts
        import socket

        self._host = socket.gethostname()
        try:
            import jax as _jax

            self.process_index = int(_jax.process_index())
        except Exception:
            self.process_index = int(rank)

    # -- wiring ------------------------------------------------------------

    def add_listener(self, fn) -> None:
        """Subscribe to detector verdicts: ``fn(event)`` is called with
        every sentry anomaly (``{"detector": "sentry", "event":
        "nonfinite"|"loss_spike", ...}``) and every divergence-probe
        verdict (``{"detector": "divergence", ...}``) as they resolve.
        Exceptions propagate — a subscriber is run logic, not logging."""
        self._listeners.append(fn)

    def _publish(self, event: Mapping[str, Any]) -> None:
        for fn in list(self._listeners):
            fn(event)

    def set_repair(self, info: Mapping[str, Any]) -> None:
        """One ``repair`` row per executed repair action
        (``tpudist.resilience.repair``): cause, rollback step, skipped
        window, action taken. Every rank records the event (the report's
        history source); rank 0 writes the row."""
        info = dict(info)
        self.repair_events.append(info)
        if self.rank == 0:
            self.sink.write("repair", info.get("skip_from"), **info)
        if self.tracer is not None:
            self.tracer.instant(
                "repair", step=info.get("skip_from"),
                cause=info.get("cause"), action=info.get("action"),
            )

    def reset_for_repair(self) -> None:
        """The repair loop just rolled the trajectory back: clear the
        sentry's spike baseline/cooldown and drop the health layer's
        in-flight delayed fetches — a pending divergence probe or
        aggregation gather describes the DISCARDED state and must not
        re-trigger (or mis-describe) the repaired trajectory."""
        if self.sentry is not None:
            self.sentry.reset()
        if self.health is not None:
            self.health.reset_pipelines()

    def set_fusion(self, info: Mapping[str, Any]) -> None:
        """One-time ``fusion`` row (rank 0): the step-fusion layer's
        resolved configuration (``make_train_step``'s ``step.fused_info``
        — ``ln``/``optimizer`` booleans + ``compute_dtype``), written at
        bring-up so every throughput/mfu row in the stream is attributable
        to the kernel set that produced it. Not written unless ``fit`` got
        a ``fused=`` request — streams stay byte-identical otherwise."""
        if self.rank == 0:
            self.sink.write("fusion", **dict(info))

    def set_comm(self, stats: Mapping[str, Any] | None,
                 probe_s: float | None = None) -> None:
        """Attach the explicit-reduction wire accounting
        (``GradReducer.comm_stats``) and the measured standalone
        reduce-only probe. Rank 0 writes a one-time ``comm`` row so the
        stream is self-describing: per-step rows carry only the live
        numbers, the setup row carries the method/bucket geometry and the
        fp32-equivalent bytes the compression is quoted against."""
        if not stats:
            return
        self._comm = dict(stats)
        self._comm_probe_s = probe_s
        if self.rank == 0:
            self.sink.write(
                "comm",
                probe_s=None if probe_s is None else round(probe_s, 6),
                **self._comm,
            )

    def set_reshard(self, info: Mapping[str, Any]) -> None:
        """One-time ``reshard`` row: an elastic resume re-laid the
        world-bound state onto a different world size
        (``tpudist.resilience.elastic``) — old/new world, how many
        ZeRO-1 leaves moved, whether the error-feedback residual banks
        were flushed, and the sampler-cursor remap. Every rank writes its
        own row (each rank restored its own shards); absent unless a
        reshard actually happened, so streams stay byte-identical."""
        self.sink.write("reshard", **dict(info))
        if self.tracer is not None:
            self.tracer.instant(
                "reshard",
                old_world=info.get("old_world"),
                new_world=info.get("new_world"),
            )

    def set_compile_cache(self, info: Mapping[str, Any]) -> None:
        """One-time ``compile_cache`` row (rank 0): the AOT executable
        cache's bring-up outcome (``tpudist.compile_cache``) — hit/miss,
        payload bytes, measured load/compile/store seconds. Only written
        when ``fit`` got a ``compile_cache=`` request."""
        if self.rank == 0:
            self.sink.write("compile_cache", **dict(info))

    def set_anatomy(self, info: Mapping[str, Any] | None) -> None:
        """One ``anatomy`` row per introspected program (rank 0): XLA's
        own FLOPs/bytes count and static HBM breakdown for a compiled
        train/serve program (:func:`tpudist.telemetry.anatomy
        .analyze_train_step`), with the analytic cross-check fields when a
        counter exists. When the counter's drift against XLA exceeds
        ``config.anatomy_tolerance`` a ``stale_flops_counter`` warning row
        follows, naming the counter — the MFU-honesty alarm. ``None``
        (introspection unavailable) writes nothing; only written when
        ``fit``/serve got an anatomy request, so streams stay
        byte-identical otherwise."""
        if info is None or self.rank != 0:
            return
        self.sink.write("anatomy", **dict(info))
        drift = info.get("flops_drift")
        if drift is not None and abs(drift) > self.config.anatomy_tolerance:
            self.warn(
                "stale_flops_counter",
                program=info.get("program"),
                flops_counter=info.get("flops_counter"),
                xla_flops=info.get("flops_scaled"),
                analytic_flops=info.get("analytic_flops"),
                drift=round(drift, 4),
                tolerance=self.config.anatomy_tolerance,
                hint="tpudist/telemetry/flops.py's analytic counter "
                     "disagrees with XLA's cost analysis for this program "
                     "— the MFU rows' numerator is stale",
            )

    def warn(self, tag: str, step: int | None = None, **fields) -> None:
        """A tagged one-shot ``warning`` row (same schema as the
        h2d_link_bound diagnosis): the home for bring-up diagnoses other
        subsystems hand fit() — e.g. ``checkpoint_fallback`` when the
        newest checkpoint failed to deserialize and the restore walked
        back a step."""
        self.sink.write("warning", step, tag=tag, **fields)

    def observe_batch(self, batch: Mapping[str, Any]) -> None:
        """Size the MFU numerator from the first staged batch's GLOBAL
        shapes (once; analytic counters, no device work). Also records the
        staged batch's PER-HOST byte volume — the numerator of the
        link-bound check: staged arrays are global, but each host only
        ships its own shard over its own link, so the global nbytes must
        be divided by the process count or an 8-host run would see an
        8x-inflated staging estimate and warn on healthy links."""
        if self._batch_bytes is None:
            try:
                import jax as _jax

                self._batch_bytes = int(sum(
                    v.nbytes for k, v in batch.items()
                    if not k.startswith("_") and hasattr(v, "nbytes")
                ) / max(_jax.process_count(), 1))
            except Exception:
                self._batch_bytes = 0
        if self._sized or not self.config.mfu:
            return
        self._sized = True
        self._flops_per_step = flops.train_step_flops(
            self._model, batch, input_key=self._input_key
        )
        self._tokens_per_step = flops.tokens_per_step(
            self._model, batch, input_key=self._input_key
        )
        if self.rank == 0:
            self.sink.write(
                "run_meta",
                flops_per_step=self._flops_per_step,
                tokens_per_step=self._tokens_per_step,
                peak_flops_per_chip=self.peak_flops,
                n_chips=self.n_chips,
                world_size=self.world_size,
                flops_counter=getattr(self._model, "flops_counter", None),
            )

    # -- per-step drive ----------------------------------------------------

    def on_step(self, step: int, metrics: Mapping[str, float], *, epoch: int,
                interval_s: float, data_wait_s: float | None = None,
                dispatch_s: float | None = None,
                device_s: float | None = None) -> dict | None:
        """Record one RESOLVED step (host-side scalar values). Returns the
        anomaly event if the sentry fired, else None."""
        loss = float(metrics.get("loss", float("nan")))
        nonfinite = int(metrics.get("nonfinite_grad_count", 0) or 0)
        skipped = int(metrics.get("update_skipped", 0) or 0)
        self._skips_total += skipped
        cadence = step % self.log_every == 0
        mfu_val = None

        if self.rank == 0 and cadence:
            health = {
                k: metrics[k]
                for k in ("grad_norm", "param_norm", "update_norm",
                          "nonfinite_grad_count", "update_skipped")
                if k in metrics
            }
            if health:
                self.sink.write("health", step, loss=loss, **health)
            if self.config.breakdown and dispatch_s is not None:
                extra = {}
                if self._comm is not None:
                    # the comm column: the setup row's exact host integer
                    # is preferred over the compiled step's fp32 metric
                    # (whose 24-bit mantissa rounds GB-scale counts by up
                    # to ~128 bytes); the time is the one-shot standalone
                    # probe — an unoverlapped upper bound, not a per-step
                    # measurement (in-graph collectives cannot be timed
                    # from the host without a barrier)
                    extra = {
                        "comm_bytes": self._comm.get(
                            "bytes_per_step", metrics.get("comm_bytes")
                        ),
                        "comm_s": (
                            None if self._comm_probe_s is None
                            else round(self._comm_probe_s, 6)
                        ),
                    }
                self.sink.write(
                    "step_breakdown", step,
                    interval_s=round(interval_s, 6),
                    data_wait_s=round(data_wait_s or 0.0, 6),
                    dispatch_s=round(dispatch_s, 6),
                    # device_s is measured on cadence steps only (a
                    # block_until_ready there would stall the pipeline
                    # every step); null on the rest
                    device_s=None if device_s is None else round(device_s, 6),
                    **extra,
                )
            moe = {
                k[len("moe/"):]: v for k, v in metrics.items()
                if k.startswith("moe/")
            }
            if moe:
                # router observability (docs/OBSERVABILITY.md §1): one row
                # per cadence step with every MoE layer's dispatched load
                # fractions [E], dropped-choice rate, and unscaled aux-loss
                # value — the step metrics carry them as '<layer>/load',
                # '<layer>/dropped', '<layer>/aux' (tpudist.train)
                self.sink.write("moe", step, **moe)
            if self._flops_per_step is not None and interval_s > 0:
                # 8 decimals: a tiny CPU-test model's true MFU is ~1e-8
                # and must not round to a fake 0.0
                mfu_val = round(flops.mfu(
                    self._flops_per_step, interval_s,
                    peak=self.peak_flops, n_chips=self.n_chips,
                ), 8)
                self.sink.write(
                    "mfu", step,
                    mfu=mfu_val,
                    flops_per_step=self._flops_per_step,
                    step_time_s=round(interval_s, 6),
                    tokens_per_sec=(
                        None if self._tokens_per_step is None
                        else round(self._tokens_per_step / interval_s, 2)
                    ),
                )

        if (not self._link_warned and self.h2d_mbps and self._batch_bytes
                and interval_s > 0):
            # link-bound diagnosis (docs/PERF.md §3): when just STAGING the
            # batch at the probed H2D rate would eat more than half the
            # observed step interval, the run is link-bound — a regime
            # measured at 0.08× on the resnet50_e2e leg — and the framework
            # mitigation is DeviceCachedLoader (stage the set to HBM once;
            # per-step H2D becomes index-only). The first two resolved
            # intervals are skipped (they carry the jit compile, which
            # dwarfs any staging cost and would mask the diagnosis
            # permanently); after warm-up every step is checked until the
            # warning fires — a link can also COLLAPSE mid-run — and it
            # fires at most once: tagged row + one stderr line instead of
            # failing silently slow.
            self._link_checks += 1
            staging_s = self._batch_bytes / (self.h2d_mbps * 1e6)
            if self._link_checks > 2 and staging_s > 0.5 * interval_s:
                self._link_warned = True
                import sys

                self.sink.write(
                    "warning", step, tag="h2d_link_bound",
                    h2d_mbps=round(self.h2d_mbps, 1),
                    batch_bytes=self._batch_bytes,
                    est_staging_s=round(staging_s, 6),
                    interval_s=round(interval_s, 6),
                    hint="per-step H2D staging dominates the step; stage "
                         "the dataset to HBM once with DeviceCachedLoader "
                         "(docs/PERF.md §3b) or pack+cache for streaming "
                         "sets (§3c)",
                )
                print(
                    f"tpudist: H2D link-bound run (probe "
                    f"{self.h2d_mbps:.0f} MB/s, batch "
                    f"{self._batch_bytes / 1e6:.1f} MB ≈ {staging_s:.3f}s "
                    f"of a {interval_s:.3f}s step) — consider "
                    "DeviceCachedLoader (docs/PERF.md §3b)",
                    file=sys.stderr, flush=True,
                )

        event = None
        if self.sentry is not None:
            event = self.sentry.observe(
                step, loss, nonfinite_count=nonfinite, update_skipped=skipped
            )
            if event is not None:
                armed = False
                if self.config.capture_on_anomaly and self.profiler is not None:
                    armed = bool(self.profiler.arm(self.config.capture_steps))
                self.sink.write(
                    "anomaly", step, epoch=epoch, profiler_armed=armed,
                    **{k: v for k, v in event.items() if k != "step"},
                )
                # detector → event bus: the repair loop (and any other
                # subscriber) acts on the verdict the row records
                self._publish({"detector": "sentry", **event})

        if self.regression is not None and self.rank == 0:
            # in-run slowdown sentinel: collectives equalize interval_s
            # fleet-wide, so one observing rank suffices — and one row
            verdict = self.regression.observe(interval_s)
            if verdict is not None:
                self.sink.write("perf_regression", step, epoch=epoch,
                                **verdict)
                if self.tracer is not None:
                    self.tracer.instant("perf_regression", step=step)

        if self.heartbeat_every and step % self.heartbeat_every == 0:
            # every process writes its own heartbeat — the cross-host
            # straggler signal. Existing fields stay byte-identical; the
            # identity/clock triple (process_index, host, mono) is
            # appended so per-rank timelines can be aligned (wall clocks
            # skew across hosts; time.monotonic deltas do not)
            # generation rides AFTER the identity triple — the same
            # append-only discipline: existing fields byte-identical,
            # new ones appended (0 on a never-restarted run)
            self.sink.write("heartbeat", step, epoch=epoch,
                            interval_s=round(interval_s, 6),
                            process_index=self.process_index,
                            host=self._host,
                            mono=round(time.monotonic(), 6),
                            generation=self.generation)

        if self.tracer is not None:
            # one `span` row per RESOLVED step, per rank — the timeline form
            # of the step_breakdown row, with the host-side attribution as
            # args. t0 is on the tracer's monotonic clock (the heartbeat
            # `mono` domain), so tracelens aligns ranks the same way it
            # aligns heartbeats.
            self.tracer.span(
                "step", interval_s, step=step,
                data_wait_s=round(data_wait_s or 0.0, 6),
                dispatch_s=None if dispatch_s is None else round(dispatch_s, 6),
                device_s=None if device_s is None else round(device_s, 6),
            )
            if event is not None:
                self.tracer.instant(
                    "anomaly", step=step, event=event.get("event")
                )

        if self.exporter is not None:
            # live scrape surface: host-side scalars only — everything here
            # was already fetched for the rows above, zero extra device work
            self.exporter.set(
                step=step,
                loss=loss if math.isfinite(loss) else None,
                step_time_s=round(interval_s, 6),
                data_wait_s=round(data_wait_s or 0.0, 6),
                mfu=mfu_val,
                tokens_per_sec=(
                    None
                    if (self._tokens_per_step is None or interval_s <= 0)
                    else round(self._tokens_per_step / interval_s, 2)
                ),
                anomaly_events_total=(
                    len(self.sentry.events) if self.sentry else 0
                ),
                update_skips_total=self._skips_total,
                repair_events_total=len(self.repair_events),
            )

        if self.health is not None:
            # host_s is the rank-LOCAL share of the step (input wait +
            # dispatch) — the scalar that actually differs on a straggling
            # host, since lockstep collectives equalize interval_s fleet-
            # wide (tpudist.telemetry.health.CrossProcessAggregator)
            self.health.observe_interval(
                step, interval_s,
                host_s=(data_wait_s or 0.0) + (dispatch_s or 0.0),
                mfu=mfu_val, skipped=skipped,
            )
        return event

    # -- run-health passthroughs (fit()'s loop-side hooks) -----------------

    def beat(self, step: int) -> None:
        """Feed the hang watchdog — once per loop iteration."""
        if self.health is not None:
            self.health.beat(step)

    def observe_state(self, step: int, state) -> None:
        """Drive the replica-divergence probe (dispatch side; resolves one
        cadence later on the delayed pipeline)."""
        if self.health is not None:
            self.health.observe_state(step, state)
            if (self.tracer is not None and self.config.divergence_every
                    and step % self.config.divergence_every == 0):
                self.tracer.instant("probe", step=step, probe="divergence")

    def mark_crashing(self) -> None:
        """fit()'s exception handler calls this FIRST, before flushing the
        final pending step: from here on no health path may dispatch or
        resolve a collective (a fetch queued behind the hung collective
        the crash interrupted would block the crash handler forever)."""
        if self.health is not None:
            self.health.crashing = True

    def on_crash(self, exc: BaseException | None = None) -> None:
        """fit()'s exception path: snapshot the run report with a crash
        status before the exception propagates. Never raises — forensics
        must not mask the original failure."""
        if self.health is None:
            return
        label = type(exc).__name__ if exc is not None else "exception"
        try:
            # drain=False: a pending gather/probe fetch behind a HUNG
            # collective would block this very crash handler forever —
            # the crashed report comes from host-side state only
            self.health.finish(status=f"crashed:{label}", drain=False)
        except Exception:
            pass

    def shutdown(self) -> None:
        """fit()'s finally-path teardown: stop the watchdog thread, then
        close the sink (which the logger's mirrored footer must precede —
        same ordering contract as before)."""
        if self.health is not None:
            self.health.shutdown()
        if self.exporter is not None:
            self.exporter.close()
        self.sink.close()

    def finish(self, opt_state=None, status: str = "completed") -> None:
        """Final summary row (rank 0): sentry event count and — when the
        optimizer chain carries an ``amp.skip_nonfinite`` wrapper — its
        skip counter (one host fetch, at run end only). With run-health
        on, also drains the delayed aggregation/probe pipelines (all
        ranks — they hold already-dispatched collectives' results) and
        writes the end-of-run report. ``status`` stamps the report
        (``"preempted"`` from fit's graceful-preemption path — still a
        clean drain: nothing is hung, the collectives resolve)."""
        skips = None
        if self.rank == 0 and opt_state is not None:
            from tpudist.amp import maybe_skipped_steps

            skips = maybe_skipped_steps(opt_state)
        if self.rank == 0:
            self.sink.write(
                "run_summary",
                anomaly_events=len(self.sentry.events) if self.sentry else 0,
                optimizer_nonfinite_skips=skips,
            )
        if self.health is not None:
            self.health.finish(status=status, optimizer_skips=skips)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.sink.close()


def build_telemetry(
    telemetry: bool | TelemetryConfig,
    *,
    job_id: str,
    log_dir: str,
    rank: int,
    world_size: int,
    log_every: int,
    n_chips: int,
    profiler=None,
    model=None,
    input_key: str = "tokens",
    mesh=None,
) -> Telemetry | None:
    """fit()'s constructor: ``False`` → None (telemetry entirely off, the
    reference TSV contract byte-identical), ``True`` → defaults, a
    :class:`TelemetryConfig` → as configured. ``mesh`` enables the
    replica-divergence probe (it needs the device mesh to build its
    shard_map); the other health pieces work without it."""
    if not telemetry:
        return None
    config = telemetry if isinstance(telemetry, TelemetryConfig) else TelemetryConfig()
    out_dir = Path(config.jsonl_dir or log_dir)
    # the job's stable run id: the launcher's env export when supervised
    # (one id across all ranks and relaunched generations), else minted
    # here — WITHOUT touching os.environ, so one fit() call in a long
    # process (a test suite) cannot leak its id into the next
    from tpudist.resilience.exitcodes import run_id as _env_run_id

    rid = _env_run_id()
    if rid is None:
        import uuid

        rid = uuid.uuid4().hex[:12]
    sink = TelemetrySink(
        out_dir / f"{job_id}_telemetry_{rank}.jsonl",
        rank=rank, max_bytes=config.jsonl_max_bytes, run_id=rid,
    )
    tel = Telemetry(
        config, sink, model=model, input_key=input_key, profiler=profiler,
        rank=rank, world_size=world_size, log_every=log_every, n_chips=n_chips,
    )
    if config.trace:
        from tpudist.telemetry.trace import Tracer

        tel.tracer = Tracer(
            sink, cat="train",
            process_index=tel.process_index, generation=tel.generation,
        )
    if (config.run_report or config.aggregate_every
            or config.divergence_every or config.hang_timeout_s):
        from tpudist.telemetry.health import RunHealth

        tel.health = RunHealth(
            config, sink, job_id=job_id, log_dir=str(out_dir), mesh=mesh,
            rank=rank, profiler=profiler, tel=tel,
        )
    return tel
