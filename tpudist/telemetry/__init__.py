"""Training-health telemetry: in-step metrics, NaN flight recorder,
step-time breakdown, MFU accounting, and a structured JSONL sink.

The reference's observability surface is a rank-0 TSV of loss and
examples/sec plus one profiler window (``tpudist/metrics.py``,
``tpudist/profiling.py`` — reproduced exactly and untouched). That answers
"how fast"; this subsystem answers the three questions a production run
dies without (docs/OBSERVABILITY.md):

- **is training healthy?** — global grad-norm, param-norm, update-norm and
  non-finite counts computed INSIDE the jit-compiled SPMD step
  (``make_train_step(telemetry=True)``): a handful of reductions XLA fuses
  into the existing gradient psum path, fetched through the same
  one-step-delayed async pipeline as the loss — zero extra host syncs.
  The bench leg ``telemetry_overhead_pct`` holds the cost under 2% of
  step time.
- **why did it die?** — :class:`NanSentry`, the flight recorder: the
  in-graph guard (``make_train_step(guard_nonfinite=True)``) skips the
  poisoned update the step it happens (params/opt-state/BN stats keep
  their pre-step values, the step counter still advances so data position
  stays exact); the host sentry then emits a structured ``anomaly`` event
  and arms :class:`~tpudist.profiling.WindowedProfiler` for an on-demand
  trace window around the anomaly. Rolling-window loss-spike detection
  catches divergence that never reaches NaN.
- **where does the time go?** — per-step data-wait / dispatch /
  device-compute attribution in ``fit()`` plus per-process heartbeat rows,
  so a slow input pipeline, a dispatch-bound host, and a multi-host
  straggler all look different in the log. MFU rows combine the analytic
  counters (:mod:`tpudist.telemetry.flops`) with measured step time.

Everything lands in a per-process JSONL stream (:class:`TelemetrySink`)
NEXT TO the reference TSV, which stays byte-identical when telemetry is
off. Enable with ``fit(..., telemetry=True)`` or pass a
:class:`TelemetryConfig` to tune knobs.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import numbers
import time
from pathlib import Path
from typing import Any, Mapping

from tpudist.telemetry import flops

__all__ = [
    "TelemetryConfig",
    "TelemetrySink",
    "NanSentry",
    "TimedIterator",
    "Telemetry",
    "build_telemetry",
    "flops",
]

SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Knobs for the telemetry subsystem; the defaults are what
    ``fit(..., telemetry=True)`` runs.

    ``health_metrics``/``guard_nonfinite`` steer the compiled step (norms
    in-graph; skip poisoned updates). ``sentry`` drives the host-side
    flight recorder: non-finite loss/grads fire an event; a loss
    counts as a spike when it exceeds the rolling window's mean by
    ``spike_sigma`` standard deviations (window of ``spike_window`` recent
    finite losses, armed only after ``spike_min_steps`` observations);
    ``cooldown_steps`` suppresses event storms after a detection.
    ``capture_steps`` sizes the on-demand profiler window an anomaly arms.
    ``peak_flops`` is PER-CHIP peak (``None`` → v5e bf16,
    ``flops.DEFAULT_PEAK_FLOPS``). ``heartbeat_every`` is in steps
    (``None`` → 10× the TSV log cadence; ``0`` → no heartbeat rows, the
    same off-switch contract as ``fit``'s ``memory_log_every``).
    ``jsonl_dir`` overrides where the sink writes (``None`` → fit's
    ``log_dir``).
    """

    health_metrics: bool = True
    guard_nonfinite: bool = True
    sentry: bool = True
    spike_window: int = 32
    spike_sigma: float = 8.0
    spike_min_steps: int = 16
    cooldown_steps: int = 16
    capture_on_anomaly: bool = True
    capture_steps: int = 6
    breakdown: bool = True
    mfu: bool = True
    peak_flops: float | None = None
    heartbeat_every: int | None = None
    jsonl_dir: str | None = None

    def step_kwargs(self) -> dict:
        """The ``make_train_step`` knobs this config implies — the ONE
        mapping from config fields to compiled-step behavior (``fit()``
        passes these through verbatim)."""
        return {
            "telemetry": self.health_metrics,
            "guard_nonfinite": self.guard_nonfinite,
        }


def _json_safe(v):
    """JSONL rows must stay strict-JSON parseable: non-finite floats become
    null (a ``NaN`` literal breaks downstream ``json.loads``), numpy
    scalars become python numbers."""
    if isinstance(v, bool) or v is None or isinstance(v, (str, int)):
        return v
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if not math.isfinite(f):
        return None
    # numpy integer scalars are not python ints (the early return above)
    # but ARE Integral — keep counts like nonfinite_grad_count integers in
    # the JSONL, not 5.0
    return int(f) if isinstance(v, numbers.Integral) else f


class TelemetrySink:
    """Append-only structured JSONL writer — one file per process
    (``{job_id}_telemetry_{rank}.jsonl``), one object per line:
    ``{"v": 1, "t": <unix seconds>, "kind": ..., "rank": ..., "step": ...,
    <kind-specific fields>}``. Kinds written by ``fit()``: ``health``,
    ``step_breakdown``, ``mfu``, ``throughput``, ``memory``, ``anomaly``,
    ``heartbeat``, ``train_time``, ``run_meta``, ``comm`` (explicit
    gradient reduction's one-time wire accounting), ``warning`` (tagged
    one-shot diagnoses, e.g. ``h2d_link_bound``). Schema glossary in
    docs/OBSERVABILITY.md. Rows flush per write, and the file opens in
    APPEND mode — both halves of the flight-recorder contract: the anomaly
    row must survive the crash it describes, including a checkpoint-resume
    of the same job_id truncating the evidence before anyone read it.
    Attempts are separable by the ``t`` timestamps."""

    def __init__(self, path: str | Path, *, rank: int = 0, clock=time.time):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.rank = rank
        self._clock = clock
        self._file = open(self.path, "a")

    def write(self, kind: str, step: int | None = None, **fields) -> dict:
        row: dict[str, Any] = {
            "v": SCHEMA_VERSION,
            "t": round(float(self._clock()), 6),
            "kind": kind,
            "rank": self.rank,
        }
        if step is not None:
            row["step"] = int(step)
        row.update({k: _json_safe(v) for k, v in fields.items()})
        self._file.write(json.dumps(row) + "\n")
        self._file.flush()
        return row

    def close(self) -> None:
        if not self._file.closed:
            self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NanSentry:
    """Host-side anomaly detector over the per-step loss stream.

    :meth:`observe` returns an event dict (``event``: ``"nonfinite"`` or
    ``"loss_spike"``) or ``None``. Non-finite loss, a non-zero in-step
    non-finite-gradient count, or an in-graph guard skip
    (``update_skipped``) fires ``nonfinite``. Spikes fire when a
    finite loss exceeds the rolling window's ``mean + sigma·std`` (and the
    window has seen ``min_steps`` losses) — the "diverging but not yet
    NaN" signal. Anomalous losses are NOT pushed into the window (one
    spike must not drag the baseline up), and ``cooldown`` steps of
    silence follow each event — for BOTH kinds — so a NaN'd-out or
    diverging run emits a handful of rows, not one per step (the in-graph
    skip counter still sees every poisoned step).
    """

    def __init__(self, *, window: int = 32, sigma: float = 8.0,
                 min_steps: int = 16, cooldown: int = 16):
        self.sigma = sigma
        self.min_steps = max(min_steps, 2)
        self.cooldown = cooldown
        self._window: collections.deque[float] = collections.deque(maxlen=window)
        self._quiet_until = -1
        self.events: list[dict] = []

    def observe(self, step: int, loss: float, *, nonfinite_count: int = 0,
                update_skipped: int = 0) -> dict | None:
        event = None
        if (not math.isfinite(loss) or nonfinite_count > 0
                or update_skipped > 0):
            # update_skipped is its own trigger: with health_metrics=False
            # the compiled step reports no nonfinite_grad_count, and a
            # bf16 backward can overflow gradients under a finite loss —
            # the in-graph guard's skip is then the only signal
            event = {
                "event": "nonfinite",
                "loss": loss,
                "nonfinite_grad_count": int(nonfinite_count),
                "update_skipped": int(update_skipped),
            }
        elif len(self._window) >= self.min_steps:
            mean = sum(self._window) / len(self._window)
            var = sum((x - mean) ** 2 for x in self._window) / len(self._window)
            std = math.sqrt(var)
            # floor the spread: a zero-variance plateau (converged run,
            # bf16-quantized loss) must not turn one-ulp jitter into a
            # recurring spike event — anything within 1e-6 relative of the
            # mean is noise, not divergence
            spread = max(std, 1e-6 * abs(mean), 1e-12)
            threshold = mean + self.sigma * spread
            if loss > threshold:
                event = {
                    "event": "loss_spike",
                    "loss": loss,
                    "window_mean": mean,
                    "window_std": std,
                    "threshold": threshold,
                    "update_skipped": int(update_skipped),
                }
        if event is not None:
            # anomalous either way — the loss must stay OUT of the baseline
            # window even when cooldown suppresses the event row, or a
            # still-elevated post-spike run drags the mean up and silences
            # every later detection
            if step < self._quiet_until:
                return None  # cooldown: a NaN'd-out/diverging run emits a
                # handful of rows, not one per step — the skipped-update
                # counter still accumulates in-graph, so nothing is lost,
                # only deduplicated
            event["step"] = int(step)
            self._quiet_until = step + self.cooldown
            self.events.append(event)
            return event
        if math.isfinite(loss):
            self._window.append(loss)
        return None


class TimedIterator:
    """Wrap a batch iterator and record the wall seconds the consumer spent
    blocked in ``next()`` — fit()'s data-wait attribution. With the
    prefetch queue healthy this is ~0; when it grows toward the step time
    the run is input-bound (docs/PERF.md §3's diagnosis, now visible
    per-step instead of requiring a bench A/B)."""

    def __init__(self, iterator):
        self._it = iter(iterator)
        self.last_wait_s = 0.0

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        try:
            return next(self._it)
        finally:
            self.last_wait_s = time.perf_counter() - t0


class Telemetry:
    """The host half of the subsystem — owns the sink and sentry, driven by
    ``fit()`` once per resolved step (one step after dispatch, on the same
    delayed pipeline as the TSV rows). Scalar rows (``health``,
    ``step_breakdown``, ``mfu``) are written by rank 0 at the TSV's
    ``log_every`` cadence; ``heartbeat`` rows are written by EVERY process
    (that is their point: a straggler host is visible by comparing its
    heartbeat wall-clock drift against its peers'); ``anomaly`` rows are
    written by whichever rank observed the anomaly, every time."""

    def __init__(self, config: TelemetryConfig, sink: TelemetrySink, *,
                 model=None, input_key: str = "tokens", profiler=None,
                 rank: int = 0, world_size: int = 1, log_every: int = 5,
                 n_chips: int = 1):
        self.config = config
        self.sink = sink
        self.profiler = profiler
        self.rank = rank
        self.world_size = world_size
        self.log_every = max(int(log_every), 1)
        self.n_chips = max(int(n_chips), 1)
        self.peak_flops = config.peak_flops or flops.DEFAULT_PEAK_FLOPS
        # None → auto (10x the TSV cadence); 0 → off — the same contract
        # as fit()'s memory_log_every, so `or` (which eats the 0) won't do
        self.heartbeat_every = (
            config.heartbeat_every if config.heartbeat_every is not None
            else self.log_every * 10
        )
        self.sentry = (
            NanSentry(
                window=config.spike_window, sigma=config.spike_sigma,
                min_steps=config.spike_min_steps,
                cooldown=config.cooldown_steps,
            )
            if config.sentry else None
        )
        self._model = model
        self._input_key = input_key
        self._flops_per_step: float | None = None
        self._tokens_per_step: int | None = None
        self._sized = False
        # explicit-gradient-reduction accounting (tpudist.parallel.dp):
        # set_comm() fills these; step_breakdown rows then carry the comm
        # column. None ⇒ feature off ⇒ rows byte-identical to before.
        self._comm: dict | None = None
        self._comm_probe_s: float | None = None
        # H2D link probe (MB/s, fit() fills on accelerator backends) + the
        # staged-batch byte count observe_batch measures: together they
        # decide the one-shot link-bound warning row
        self.h2d_mbps: float | None = None
        self._batch_bytes: int | None = None
        self._link_warned = False
        self._link_checks = 0

    # -- wiring ------------------------------------------------------------

    def set_comm(self, stats: Mapping[str, Any] | None,
                 probe_s: float | None = None) -> None:
        """Attach the explicit-reduction wire accounting
        (``GradReducer.comm_stats``) and the measured standalone
        reduce-only probe. Rank 0 writes a one-time ``comm`` row so the
        stream is self-describing: per-step rows carry only the live
        numbers, the setup row carries the method/bucket geometry and the
        fp32-equivalent bytes the compression is quoted against."""
        if not stats:
            return
        self._comm = dict(stats)
        self._comm_probe_s = probe_s
        if self.rank == 0:
            self.sink.write(
                "comm",
                probe_s=None if probe_s is None else round(probe_s, 6),
                **self._comm,
            )

    def observe_batch(self, batch: Mapping[str, Any]) -> None:
        """Size the MFU numerator from the first staged batch's GLOBAL
        shapes (once; analytic counters, no device work). Also records the
        staged batch's PER-HOST byte volume — the numerator of the
        link-bound check: staged arrays are global, but each host only
        ships its own shard over its own link, so the global nbytes must
        be divided by the process count or an 8-host run would see an
        8x-inflated staging estimate and warn on healthy links."""
        if self._batch_bytes is None:
            try:
                import jax as _jax

                self._batch_bytes = int(sum(
                    v.nbytes for k, v in batch.items()
                    if not k.startswith("_") and hasattr(v, "nbytes")
                ) / max(_jax.process_count(), 1))
            except Exception:
                self._batch_bytes = 0
        if self._sized or not self.config.mfu:
            return
        self._sized = True
        self._flops_per_step = flops.train_step_flops(
            self._model, batch, input_key=self._input_key
        )
        self._tokens_per_step = flops.tokens_per_step(
            self._model, batch, input_key=self._input_key
        )
        if self.rank == 0:
            self.sink.write(
                "run_meta",
                flops_per_step=self._flops_per_step,
                tokens_per_step=self._tokens_per_step,
                peak_flops_per_chip=self.peak_flops,
                n_chips=self.n_chips,
                world_size=self.world_size,
                flops_counter=getattr(self._model, "flops_counter", None),
            )

    # -- per-step drive ----------------------------------------------------

    def on_step(self, step: int, metrics: Mapping[str, float], *, epoch: int,
                interval_s: float, data_wait_s: float | None = None,
                dispatch_s: float | None = None,
                device_s: float | None = None) -> dict | None:
        """Record one RESOLVED step (host-side scalar values). Returns the
        anomaly event if the sentry fired, else None."""
        loss = float(metrics.get("loss", float("nan")))
        nonfinite = int(metrics.get("nonfinite_grad_count", 0) or 0)
        skipped = int(metrics.get("update_skipped", 0) or 0)
        cadence = step % self.log_every == 0

        if self.rank == 0 and cadence:
            health = {
                k: metrics[k]
                for k in ("grad_norm", "param_norm", "update_norm",
                          "nonfinite_grad_count", "update_skipped")
                if k in metrics
            }
            if health:
                self.sink.write("health", step, loss=loss, **health)
            if self.config.breakdown and dispatch_s is not None:
                extra = {}
                if self._comm is not None:
                    # the comm column: the setup row's exact host integer
                    # is preferred over the compiled step's fp32 metric
                    # (whose 24-bit mantissa rounds GB-scale counts by up
                    # to ~128 bytes); the time is the one-shot standalone
                    # probe — an unoverlapped upper bound, not a per-step
                    # measurement (in-graph collectives cannot be timed
                    # from the host without a barrier)
                    extra = {
                        "comm_bytes": self._comm.get(
                            "bytes_per_step", metrics.get("comm_bytes")
                        ),
                        "comm_s": (
                            None if self._comm_probe_s is None
                            else round(self._comm_probe_s, 6)
                        ),
                    }
                self.sink.write(
                    "step_breakdown", step,
                    interval_s=round(interval_s, 6),
                    data_wait_s=round(data_wait_s or 0.0, 6),
                    dispatch_s=round(dispatch_s, 6),
                    # device_s is measured on cadence steps only (a
                    # block_until_ready there would stall the pipeline
                    # every step); null on the rest
                    device_s=None if device_s is None else round(device_s, 6),
                    **extra,
                )
            if self._flops_per_step is not None and interval_s > 0:
                self.sink.write(
                    "mfu", step,
                    # 8 decimals: a tiny CPU-test model's true MFU is ~1e-8
                    # and must not round to a fake 0.0
                    mfu=round(flops.mfu(
                        self._flops_per_step, interval_s,
                        peak=self.peak_flops, n_chips=self.n_chips,
                    ), 8),
                    flops_per_step=self._flops_per_step,
                    step_time_s=round(interval_s, 6),
                    tokens_per_sec=(
                        None if self._tokens_per_step is None
                        else round(self._tokens_per_step / interval_s, 2)
                    ),
                )

        if (not self._link_warned and self.h2d_mbps and self._batch_bytes
                and interval_s > 0):
            # link-bound diagnosis (docs/PERF.md §3): when just STAGING the
            # batch at the probed H2D rate would eat more than half the
            # observed step interval, the run is link-bound — a regime
            # measured at 0.08× on the resnet50_e2e leg — and the framework
            # mitigation is DeviceCachedLoader (stage the set to HBM once;
            # per-step H2D becomes index-only). The first two resolved
            # intervals are skipped (they carry the jit compile, which
            # dwarfs any staging cost and would mask the diagnosis
            # permanently); after warm-up every step is checked until the
            # warning fires — a link can also COLLAPSE mid-run — and it
            # fires at most once: tagged row + one stderr line instead of
            # failing silently slow.
            self._link_checks += 1
            staging_s = self._batch_bytes / (self.h2d_mbps * 1e6)
            if self._link_checks > 2 and staging_s > 0.5 * interval_s:
                self._link_warned = True
                import sys

                self.sink.write(
                    "warning", step, tag="h2d_link_bound",
                    h2d_mbps=round(self.h2d_mbps, 1),
                    batch_bytes=self._batch_bytes,
                    est_staging_s=round(staging_s, 6),
                    interval_s=round(interval_s, 6),
                    hint="per-step H2D staging dominates the step; stage "
                         "the dataset to HBM once with DeviceCachedLoader "
                         "(docs/PERF.md §3b) or pack+cache for streaming "
                         "sets (§3c)",
                )
                print(
                    f"tpudist: H2D link-bound run (probe "
                    f"{self.h2d_mbps:.0f} MB/s, batch "
                    f"{self._batch_bytes / 1e6:.1f} MB ≈ {staging_s:.3f}s "
                    f"of a {interval_s:.3f}s step) — consider "
                    "DeviceCachedLoader (docs/PERF.md §3b)",
                    file=sys.stderr, flush=True,
                )

        event = None
        if self.sentry is not None:
            event = self.sentry.observe(
                step, loss, nonfinite_count=nonfinite, update_skipped=skipped
            )
            if event is not None:
                armed = False
                if self.config.capture_on_anomaly and self.profiler is not None:
                    armed = bool(self.profiler.arm(self.config.capture_steps))
                self.sink.write(
                    "anomaly", step, epoch=epoch, profiler_armed=armed,
                    **{k: v for k, v in event.items() if k != "step"},
                )

        if self.heartbeat_every and step % self.heartbeat_every == 0:
            # every process writes its own heartbeat — the cross-host
            # straggler signal
            self.sink.write("heartbeat", step, epoch=epoch,
                            interval_s=round(interval_s, 6))
        return event

    def finish(self, opt_state=None) -> None:
        """Final summary row (rank 0): sentry event count and — when the
        optimizer chain carries an ``amp.skip_nonfinite`` wrapper — its
        skip counter (one host fetch, at run end only)."""
        if self.rank != 0:
            return
        skips = None
        if opt_state is not None:
            from tpudist.amp import maybe_skipped_steps

            skips = maybe_skipped_steps(opt_state)
        self.sink.write(
            "run_summary",
            anomaly_events=len(self.sentry.events) if self.sentry else 0,
            optimizer_nonfinite_skips=skips,
        )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.sink.close()


def build_telemetry(
    telemetry: bool | TelemetryConfig,
    *,
    job_id: str,
    log_dir: str,
    rank: int,
    world_size: int,
    log_every: int,
    n_chips: int,
    profiler=None,
    model=None,
    input_key: str = "tokens",
) -> Telemetry | None:
    """fit()'s constructor: ``False`` → None (telemetry entirely off, the
    reference TSV contract byte-identical), ``True`` → defaults, a
    :class:`TelemetryConfig` → as configured."""
    if not telemetry:
        return None
    config = telemetry if isinstance(telemetry, TelemetryConfig) else TelemetryConfig()
    sink = TelemetrySink(
        Path(config.jsonl_dir or log_dir) / f"{job_id}_telemetry_{rank}.jsonl",
        rank=rank,
    )
    return Telemetry(
        config, sink, model=model, input_key=input_key, profiler=profiler,
        rank=rank, world_size=world_size, log_every=log_every, n_chips=n_chips,
    )
