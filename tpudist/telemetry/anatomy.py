"""Program anatomy: what XLA actually compiled, checked against what we claim.

The MFU rows (docs/OBSERVABILITY.md §5) and the memory budget tables
(docs/PERF.md §10) both rest on hand-maintained analytic models —
``tpudist/telemetry/flops.py``'s counters and ``tpudist/memory.py``'s
activation estimates. Nothing verified them against the compiled program
until now. This module asks the compiler directly, once, at bring-up:

- :func:`program_costs` / :func:`program_memory` normalize
  ``Compiled.cost_analysis()`` / ``Compiled.memory_analysis()`` across the
  jax versions and backends we run on (list-of-dict vs dict; backends
  without memory analysis) into plain fail-soft dicts.
- :func:`analyze_train_step` produces the one-shot ``anatomy`` row for the
  train step: XLA-counted FLOPs (scaled by ``grad_accum`` — HLO cost
  analysis counts a ``lax.scan`` body ONCE, so the raw number is 1/G of
  the work the step performs), bytes accessed, and the static HBM
  breakdown, cross-checked against the analytic counter. Drift beyond
  tolerance means a counter went stale against a model edit — the MFU
  numbers are lying — and ``Telemetry.set_anatomy`` turns that into a
  ``warning`` row naming the counter.
- :class:`StepTimeRegressionDetector` is the in-run half of the regression
  sentinel (``tools/bench_gate.py`` is the cross-run half): a rolling
  median of observed step times against the post-compile baseline, firing
  a one-shot ``perf_regression`` row on sustained slowdown — the
  mid-run drift (data pipeline, thermal, host contention) that per-step
  logs show but nothing flags.

Everything here is observe-only and off by default: no knob set, no code
in this module runs and every stream stays byte-identical.
"""

from __future__ import annotations

from typing import Any, Mapping

__all__ = [
    "program_costs",
    "program_memory",
    "analyze_program",
    "analyze_train_step",
    "flops_drift",
    "StepTimeRegressionDetector",
]


def _first_mapping(obj) -> Mapping[str, Any] | None:
    """``cost_analysis()`` returns a dict on new jax, ``[dict]`` on the
    versions we pin; both collapse to the one per-program mapping."""
    if isinstance(obj, Mapping):
        return obj
    if isinstance(obj, (list, tuple)) and obj and isinstance(obj[0], Mapping):
        return obj[0]
    return None


def program_costs(compiled_or_lowered) -> dict[str, float] | None:
    """XLA's own operation count for a compiled (or merely lowered)
    program: ``{"flops", "bytes_accessed", "transcendentals"}``, or
    ``None`` where the backend doesn't implement cost analysis. Works on
    both ``Compiled`` and ``Lowered`` objects — lowering is enough for
    costs (not for memory), which is what makes the jit-path fallback
    free of a second compile."""
    try:
        cost = _first_mapping(compiled_or_lowered.cost_analysis())
    except Exception:
        return None
    if cost is None:
        return None
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals")):
        v = cost.get(key)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out if "flops" in out else None


def program_memory(compiled) -> dict[str, int] | None:
    """The static HBM breakdown of a compiled program, from
    ``Compiled.memory_analysis()``: argument / output / temp / alias /
    generated-code bytes plus ``peak_bytes`` — the sum of the resident
    pieces (args + outputs + temps + code), the closest static analogue
    of the allocator's live peak the API exposes. ``None`` (fail-soft)
    on backends or objects without memory analysis — a ``Lowered`` lands
    here, as do plugin backends that return nothing."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    out = {}
    for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes",
                        "generated_code_bytes")):
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)):
            out[name] = int(v)
    if not out:
        return None
    out["peak_bytes"] = (out.get("argument_bytes", 0)
                         + out.get("output_bytes", 0)
                         + out.get("temp_bytes", 0)
                         + out.get("generated_code_bytes", 0)
                         - out.get("alias_bytes", 0))
    return out


def analyze_program(name: str, *, compiled=None, lowered=None,
                    grad_accum: int = 1) -> dict[str, Any] | None:
    """One program's anatomy dict: costs from whichever of ``compiled`` /
    ``lowered`` answers (compiled preferred — it has memory too), memory
    from ``compiled`` only. ``grad_accum`` scales the FLOPs/bytes into
    per-step units (HLO counts the scan body once); the raw count is kept
    alongside so the row stays auditable. Returns ``None`` when neither
    object yields costs — the caller should skip the row, not fabricate
    one."""
    costs = None
    aot = False
    if compiled is not None:
        costs = program_costs(compiled)
        aot = costs is not None
    if costs is None and lowered is not None:
        costs = program_costs(lowered)
    if costs is None:
        return None
    g = max(int(grad_accum), 1)
    info: dict[str, Any] = {
        "program": name,
        "flops": costs["flops"],
        "flops_scaled": costs["flops"] * g,
        "grad_accum": g,
        "aot": aot,
    }
    if "bytes_accessed" in costs:
        info["bytes_accessed"] = costs["bytes_accessed"] * g
    if "transcendentals" in costs:
        info["transcendentals"] = costs["transcendentals"] * g
    mem = program_memory(compiled) if compiled is not None else None
    if mem is not None:
        info.update(mem)
    return info


def flops_drift(xla_flops: float, analytic: float | None) -> float | None:
    """Signed relative drift of the analytic counter against XLA's count
    (positive = analytic overcounts). ``None`` when there is no counter
    to check — an absent counter is not a stale counter."""
    if analytic is None or not xla_flops:
        return None
    return (analytic - xla_flops) / xla_flops


def analyze_train_step(step, state, staged, *, model=None,
                       input_key: str = "tokens", grad_accum: int = 1,
                       allow_compile: bool = False) -> dict[str, Any] | None:
    """The train step's ``anatomy`` row payload.

    ``step`` is ``make_train_step``'s product (or ``compile_cache``'s
    wrapper around it — same attributes): when its ``.aot`` holder carries
    the already-compiled executable, full cost + memory analysis comes for
    free; otherwise the step is lowered (cheap, no compile) for costs
    only, unless ``allow_compile=True`` (tests) pays for the compile to
    get memory too. ``staged`` must be the staged batch the step actually
    runs on (``step.stage``'s output — grad-accum reshape applied), and
    ``grad_accum`` its accumulation factor so the scan-counted-once FLOPs
    scale back to per-step units.

    The analytic cross-check and the activation estimate ride along when
    ``model`` is given: ``analytic_flops`` from the ``flops_counter``
    dispatch (on the UNstaged shapes the counter understands — the staged
    tree works too, ``_rows`` flattens leading dims) and
    ``activation_bytes_est`` from ``transformer_activation_bytes`` for
    transformer geometries. All fail-soft: a model without a counter just
    omits the fields.
    """
    exe = None
    holder = getattr(step, "aot", None)
    if isinstance(holder, Mapping):
        exe = holder.get("exe")
    lowered = None
    if exe is None:
        try:
            lowered = step.jitted.lower(state, staged)
        except Exception:
            return None
        if allow_compile:
            try:
                exe = lowered.compile()
            except Exception:
                exe = None
    info = analyze_program("train_step", compiled=exe, lowered=lowered,
                           grad_accum=grad_accum)
    if info is None:
        return None
    if model is not None:
        from tpudist.telemetry import flops as flops_mod

        analytic = flops_mod.train_step_flops(model, staged,
                                              input_key=input_key)
        if analytic is not None:
            info["analytic_flops"] = float(analytic)
            drift = flops_drift(info["flops_scaled"], analytic)
            if drift is not None:
                info["flops_drift"] = drift
            info["flops_counter"] = getattr(model, "flops_counter", None)
        est = _activation_estimate(model, staged, input_key)
        if est is not None:
            info["activation_bytes_est"] = est
    return info


def _activation_estimate(model, staged, input_key) -> int | None:
    """``memory.py``'s analytic activation bytes for the staged
    microbatch, for side-by-side reading against ``temp_bytes`` in the
    anatomy row. Token-transformer geometries only; anything else (vision,
    index-only batches) returns ``None`` rather than a wrong number."""
    hidden = getattr(model, "hidden_dim", None)
    depth = getattr(model, "depth", None)
    if not hidden or not depth:
        return None
    try:
        shape = staged[input_key].shape
    except (KeyError, TypeError, AttributeError):
        return None
    if len(shape) < 2:
        return None
    seq = int(shape[-1])
    # staged layout is [accum, micro, seq] (grad-accum) or [batch, seq]
    # (flat): either way the dim before seq is the per-pass microbatch —
    # the batch whose activations are live at once
    micro = int(shape[-2])
    try:
        from tpudist.memory import transformer_activation_bytes

        return transformer_activation_bytes(
            micro, seq, int(hidden), int(depth),
            num_heads=getattr(model, "num_heads", None),
            remat_policy=getattr(model, "remat_policy", "none") or "none",
        )
    except Exception:
        return None


class StepTimeRegressionDetector:
    """In-run slowdown sentinel over observed step intervals.

    Feed every measured interval (seconds) to :meth:`observe`. The first
    ``warmup`` intervals are discarded (compile + cache warmness), the
    next ``baseline_steps`` form the post-compile baseline (median), and
    from then on a rolling median over the last ``window`` intervals is
    compared against ``baseline · (1 + threshold)``. After ``patience``
    CONSECUTIVE exceedances :meth:`observe` returns a one-shot payload
    (then never again — one row per run, matching the other one-shot
    telemetry rows); otherwise ``None``. Median-of-window on both sides
    makes a single GC pause or host hiccup invisible — only a sustained
    shift fires.
    """

    def __init__(self, *, warmup: int = 2, baseline_steps: int = 8,
                 window: int = 16, threshold: float = 0.25,
                 patience: int = 3) -> None:
        self.warmup = max(int(warmup), 0)
        self.baseline_steps = max(int(baseline_steps), 1)
        self.window = max(int(window), 1)
        self.threshold = float(threshold)
        self.patience = max(int(patience), 1)
        self.baseline: float | None = None
        self._seen = 0
        self._baseline_buf: list[float] = []
        self._window_buf: list[float] = []
        self._hits = 0
        self.fired = False

    @staticmethod
    def _median(xs: list[float]) -> float:
        s = sorted(xs)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def observe(self, interval_s: float) -> dict[str, Any] | None:
        if self.fired or interval_s <= 0.0:
            return None
        self._seen += 1
        if self._seen <= self.warmup:
            return None
        if self.baseline is None:
            self._baseline_buf.append(float(interval_s))
            if len(self._baseline_buf) >= self.baseline_steps:
                self.baseline = self._median(self._baseline_buf)
            return None
        self._window_buf.append(float(interval_s))
        if len(self._window_buf) > self.window:
            self._window_buf.pop(0)
        if len(self._window_buf) < self.window:
            return None
        rolling = self._median(self._window_buf)
        if rolling > self.baseline * (1.0 + self.threshold):
            self._hits += 1
        else:
            self._hits = 0
            return None
        if self._hits < self.patience:
            return None
        self.fired = True
        return {
            "baseline_s": self.baseline,
            "rolling_median_s": rolling,
            "slowdown_pct": round(
                (rolling / self.baseline - 1.0) * 100.0, 2),
            "window": self.window,
            "threshold": self.threshold,
        }
